"""Whole-pipeline optimization rules: auto-caching, node-level solver
selection, and profile-guided resource planning.

Ref: src/main/scala/workflow/{AutoCacheRule,NodeOptimizationRule}.scala
(SURVEY.md §2.1, §3.5) [unverified].

Cost provenance, in preference order (the closed cost-model loop):

1. **measured** — per-node wall/bytes/shape rows recorded by a prior
   ``Pipeline.fit(profile=True)`` and persisted in the profile store
   (workflow/profile_store.py), matched back to graph nodes by
   content-stable prefix digest. On a store hit the rules run ZERO
   sample executions.
2. **sampled** — the 64-row sample-run ``Profiler`` extrapolation
   (with the compiled-FLOPs non-linearity correction).
3. **model** — the abstract ``node_cost_analysis`` AOT estimate, where
   neither of the above exists.

Every choice is appended to the process-wide decision log
(``optimizer_decisions()``), which ``tools/profile_report.py
--decisions`` renders — the optimizer explains itself.
"""

from __future__ import annotations

import logging
import threading
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from keystone_tpu.config import config
from keystone_tpu.workflow.cache import CacheOperator, NodeProfile, Profiler
from keystone_tpu.workflow.graph import Graph, GraphId, NodeId
from keystone_tpu.workflow.operators import (
    DatasetOperator,
    EstimatorOperator,
    TransformerOperator,
)
from keystone_tpu.workflow.optimizer import Rule, active_profile_key

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# Decision log — how the optimizer explains itself
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class OptimizerDecision:
    """One recorded optimizer choice: which rule, on which node, what it
    did, from which cost provenance, and why."""

    rule: str
    node: str
    action: str        # e.g. "cache-insert", "cache-skip", "solver=...",
                       # "exec_workers=4", "solve_chunk_rows=8192"
    provenance: str    # "measured" | "sampled" | "model"
    reason: str
    cost: Dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "node": self.node,
            "action": self.action,
            "provenance": self.provenance,
            "reason": self.reason,
            "cost": dict(self.cost),
        }


#: Bounded process-wide decision ring (newest kept): repeated optimizer
#: passes over hot pipelines must not grow memory.
_DECISIONS_CAP = 256
_decisions_lock = threading.Lock()
_decisions: List[OptimizerDecision] = []


def record_decision(
    rule: str, node: str, action: str, provenance: str, reason: str,
    cost: Optional[Dict[str, Any]] = None,
) -> None:
    d = OptimizerDecision(rule, node, action, provenance, reason, cost or {})
    with _decisions_lock:
        _decisions.append(d)
        if len(_decisions) > _DECISIONS_CAP:
            del _decisions[: len(_decisions) - _DECISIONS_CAP]


def optimizer_decisions() -> List[OptimizerDecision]:
    """The recorded decisions, oldest first (bounded ring)."""
    with _decisions_lock:
        return list(_decisions)


def clear_decisions() -> None:
    with _decisions_lock:
        _decisions.clear()


def _measured_profile():
    """The stored measured profile for the pipeline currently being
    optimized, or None (no store / no key / no entry / incompatible
    fingerprint — the latter logged by lookup_measured)."""
    key = active_profile_key()
    if key is None:
        return None
    from keystone_tpu.workflow.profile_store import lookup_measured

    return lookup_measured(key)


def _scaled_shape(value, scale: float):
    """Full-size shape estimate from a row-sampled value: axis 0 scales by
    the sample's row ratio, trailing dims are exact."""
    shape = getattr(value, "shape", None)
    if shape is None or len(shape) == 0:
        return None
    if scale == 1.0:
        return tuple(shape)
    return (int(round(shape[0] * scale)),) + tuple(shape[1:])


class NodeOptimizationRule(Rule):
    """Swap optimizable estimators for concrete implementations chosen from
    data statistics at optimization time.

    An estimator opts in by defining ``optimize_node(self, data_shape) ->
    estimator``. Shapes are read from directly-attached dataset nodes when
    available (the simple with_data case); estimators fed by deeper
    transformer subgraphs get their (n, d) from the MEASURED output shapes
    of a stored profile when one matches (exact full-size shapes, zero
    executions), else from ONE sampled prefix run per apply (the
    reference's optimizer profiles sampled prefixes for stats anywhere in
    the DAG — SURVEY.md §3.5), so cost-model dispatch happens at
    optimization time, not fit time.

    The concrete replacement is memoized per (estimator, shapes): every
    optimizer pass over any copy of the graph swaps in the SAME concrete
    instance, so the replaced node's structural hash — and therefore its fit
    cache entry — is stable across executions.
    """

    def __init__(self, sample_rows: int = 64):
        self._memo: Dict[tuple, tuple] = {}
        # Deep-graph shapes memoized by the deps' CONTENT-STABLE prefix
        # digests: repeated optimizer passes over graph copies hit this
        # instead of re-executing the sampled prefix. id-based prefixes
        # digest to None and are never memoized — a recycled id must not
        # serve stale shapes (same rule as the executor's fit cache).
        self._shape_memo: Dict[tuple, List] = {}
        self.sample_rows = sample_rows

    def clear_cache(self) -> None:
        self._memo.clear()
        self._shape_memo.clear()

    @staticmethod
    def _dep_prefix_key(graph: Graph, deps: Sequence[GraphId]):
        """(memo key, sampleable): the key is a tuple of content-stable
        prefix digests (None when any prefix lacks content identity — then
        shapes are recomputed each pass rather than risking a stale hit);
        sampleable=False when a prefix reaches an unbound source, where a
        sample run could never resolve the shapes."""
        from keystone_tpu.workflow.graph import structural_digest

        digests = []
        for d in deps:
            if not isinstance(d, NodeId):
                return None, False
            if graph.sources_of([d]):
                return None, False
            digests.append(structural_digest(graph, d))
        if any(x is None for x in digests):
            return None, True
        return tuple(digests), True

    @staticmethod
    def _measured_shapes(graph: Graph, deps, shapes, measured, dmemo):
        """Resolve the still-missing dep shapes from a stored measured
        profile's recorded output shapes (exact full-size values — better
        than a scaled sample). None when any gap stays unresolved."""
        from keystone_tpu.workflow.graph import structural_digest

        out = []
        for s, dep in zip(shapes, deps):
            if s is not None:
                out.append(tuple(s))
                continue
            if not isinstance(dep, NodeId):
                return None
            entry = measured.node(structural_digest(graph, dep, dmemo))
            shp = (entry or {}).get("out_shape")
            if not shp:
                return None
            out.append(tuple(int(x) for x in shp))
        return out

    def _sample_prefixes(self, graph: Graph, targets: Sequence[GraphId]):
        """One row-sampled execution of the input prefixes of every
        optimizable estimator that still NEEDS sampling — deep-graph deps
        not already served by the shape memo or by direct dataset shapes.
        All such estimators in the DAG share the run."""
        needed = []
        for nid in graph.reachable(targets):
            op = graph.operators[nid]
            if not isinstance(op, EstimatorOperator) or (
                getattr(op.estimator, "optimize_node", None) is None
            ):
                continue
            deps = graph.dependencies[nid]
            if all(
                isinstance(d, NodeId)
                and isinstance(graph.operators.get(d), DatasetOperator)
                for d in deps
            ):
                continue  # direct with_data case: shapes read off datasets
            pkey, sampleable = self._dep_prefix_key(graph, deps)
            if not sampleable:
                continue  # unbound prefix: sampling can't resolve it
            if pkey is not None and pkey in self._shape_memo:
                continue  # already served without execution
            needed.extend(d for d in deps if isinstance(d, NodeId))
        return Profiler(self.sample_rows).sample_values(graph, needed)

    def apply(self, graph: Graph, targets: Sequence[GraphId]) -> Graph:
        out = graph
        sampled = None  # lazy: only deep-graph estimators pay for the run
        sample_ok = True
        measured = _measured_profile()
        dmemo: Dict[GraphId, Any] = {}
        for nid in graph.reachable(targets):
            op = graph.operators[nid]
            if not isinstance(op, EstimatorOperator):
                continue
            optimize = getattr(op.estimator, "optimize_node", None)
            if optimize is None:
                continue
            deps = graph.dependencies[nid]
            shapes = []
            for dep in deps:
                shape = None
                if isinstance(dep, NodeId):
                    dep_op = graph.operators.get(dep)
                    if isinstance(dep_op, DatasetOperator):
                        shape = getattr(dep_op.data, "shape", None)
                shapes.append(shape)
            provenance = "model"
            if shapes and any(s is None for s in shapes):
                pkey, sampleable = self._dep_prefix_key(graph, deps)
                if not sampleable:
                    continue  # unbound prefix: nothing to sample or dispatch
                resolved = None
                if measured is not None:
                    resolved = self._measured_shapes(
                        graph, deps, shapes, measured, dmemo
                    )
                if resolved is not None:
                    shapes = resolved
                    provenance = "measured"
                else:
                    memo_shapes = (
                        self._shape_memo.get(pkey)
                        if pkey is not None else None
                    )
                    if memo_shapes is not None:
                        shapes = memo_shapes
                        provenance = "sampled"
                    else:
                        provenance = "sampled"
                        if sampled is None:
                            try:
                                sampled = self._sample_prefixes(graph, targets)
                                sample_ok = True
                            except Exception:  # lint: broad-ok sample-run probe over arbitrary user operators
                                # A prefix that can't run on a 64-row sample
                                # must not crash optimization: affected
                                # estimators keep their fit-time dispatch.
                                logger.warning(
                                    "sampled prefix run failed; deep-graph "
                                    "estimators keep fit-time dispatch",
                                    exc_info=True,
                                )
                                sampled = ({}, {}, {})
                                sample_ok = False
                        values, scales, rows_ok = sampled
                        shapes = [
                            s
                            if s is not None
                            else (
                                _scaled_shape(
                                    values.get(dep), scales.get(dep, 1.0)
                                )
                                # A row-changing prefix (sampler/aggregator)
                                # makes scaled-n a lie; defer to fit-time.
                                if rows_ok.get(dep, False)
                                else None
                            )
                            for s, dep in zip(shapes, deps)
                        ]
                        # Legitimate deferrals memoize; a FAILED run must not —
                        # a transient error would otherwise disable
                        # optimize-time dispatch for this prefix forever.
                        # Bounded by refusing inserts when full, NOT by
                        # clearing: a mid-apply clear would strand estimators
                        # that _sample_prefixes skipped on a memo hit, letting
                        # them memoize all-None shapes from a run that never
                        # sampled their deps.
                        if (
                            pkey is not None
                            and sample_ok
                            and len(self._shape_memo) < 1024
                        ):
                            self._shape_memo[pkey] = shapes
            if not shapes or shapes[0] is None:
                continue
            key = (id(op.estimator), tuple(shapes))
            memoized = self._memo.get(key)
            if memoized is not None and memoized[0]() is op.estimator:
                concrete = memoized[1]
            else:
                concrete = optimize(*shapes)
                # The original is held weakly with eviction: when the user
                # drops their pipeline the memo entry (and the concrete
                # estimator it pins, and in turn that estimator's fit-cache
                # entry with its pinned training data) is freed. A dead or
                # recycled id can never serve a stale concrete because the
                # weakref identity check above fails first.
                try:
                    ref = weakref.ref(
                        op.estimator,
                        lambda _r, key=key: self._memo.pop(key, None),
                    )
                except TypeError:  # not weak-referenceable: don't memoize
                    ref = None
                if ref is not None:
                    self._memo[key] = (ref, concrete)
            if concrete is not None and concrete is not op.estimator:
                choice = getattr(op.estimator, "last_choice", None)
                record_decision(
                    rule="NodeOptimizationRule",
                    node=op.label(),
                    action=f"solver={type(concrete).__name__}",
                    provenance=provenance,
                    reason=(
                        getattr(choice, "reason", None)
                        or "optimize_node replacement from data shapes"
                    ),
                    cost={"shapes": [list(map(int, s)) for s in shapes
                                     if s is not None]},
                )
                out = out.replace_node(
                    nid, EstimatorOperator(concrete), graph.dependencies[nid]
                )
        return out


#: Assumed host/HBM materialization bandwidth used to price PERSISTING a
#: cached value (bytes / this = seconds of materialization cost). A
#: deliberately conservative 2 GB/s: only nodes whose recompute clearly
#: dominates a memory write get cached on the measured path.
_MATERIALIZE_BYTES_PER_S = 2e9

#: Absolute floor on a measured node's per-call wall before it can earn a
#: cache slot: sub-millisecond "costs" are dispatch overhead, and caching
#: them trades a fusion boundary (and a recompile) for nothing.
_MIN_CACHE_WALL_S = 1e-3


class AutoCacheRule(Rule):
    """Insert cache nodes where a subchain's recompute cost exceeds its
    materialization cost, best time-saved-per-byte first, under a memory
    budget.

    Costs come measured-first: a stored profile for this pipeline
    (matched by prefix digest) supplies per-node wall/bytes with ZERO
    sample executions; otherwise one 64-row sample run extrapolates
    (``Profiler``, with the compiled-FLOPs non-linearity correction).

    The session cache persists values across executions (fit → later
    applies, repeated gets over graph copies); within one execution the
    structural-hash memo already dedups, so the win is cross-execution
    recompute avoidance — the reference's cached-RDD reuse, with HBM/host
    RAM as the budget.
    """

    def __init__(
        self,
        budget_bytes: int | None = None,
        sample_rows: int = 64,
        min_consumers: int = 1,
        only_if_enabled: bool = False,
    ):
        self.budget_bytes = budget_bytes
        self.sample_rows = sample_rows
        self.min_consumers = min_consumers
        # The default optimizer installs the rule unconditionally and gates
        # each apply on config.auto_cache, so toggling the flag mid-session
        # takes effect instead of silently depending on when PipelineEnv
        # was constructed. Directly-constructed rules stay unconditional.
        self.only_if_enabled = only_if_enabled

    def _skippable(self, graph: Graph, nid: NodeId, targets_set, cons) -> bool:
        """Nodes no candidate path considers (shared by both provenances)."""
        op = graph.operators[nid]
        if isinstance(op, (DatasetOperator, CacheOperator)):
            return True  # data already lives in host memory; cache is cache
        if isinstance(op, EstimatorOperator):
            # Fits persist in the fit cache already, and a cache node
            # between an estimator and its delegating consumer would
            # hide the fitted transformer from Pipeline.fit's rewrite.
            return True
        if nid in targets_set or len(cons.get(nid, ())) < self.min_consumers:
            return True
        return False

    def _measured_candidates(
        self, graph: Graph, targets, measured, targets_set, cons
    ) -> List[tuple]:
        """(ratio, bytes, nid, decision-meta) candidates priced from the
        stored profile — no execution of any kind. A candidate survives
        only when its measured per-call recompute cost exceeds the cost
        of materializing its measured output bytes. The saving a cache
        buys is ONE avoided re-execution per later walk — the executor's
        structural-hash memo already runs a multi-consumer node once per
        walk, so consumer count is reported as context, never multiplied
        into the saving (the sampled path prices identically)."""
        from keystone_tpu.workflow.graph import structural_digest

        dmemo: Dict[GraphId, Any] = {}
        out: List[tuple] = []
        for nid in graph.reachable(targets):
            if self._skippable(graph, nid, targets_set, cons):
                continue
            label = graph.operators[nid].label()
            entry = measured.node(structural_digest(graph, nid, dmemo))
            if entry is None:
                # No measured row for this prefix (e.g. it only executed
                # fused into a larger program in the recorded run): leave
                # it uncached rather than guessing.
                continue
            calls = max(1, int(entry.get("calls") or 0))
            wall_s = (int(entry.get("wall_ns") or 0) / 1e9) / calls
            nbytes = int(entry.get("out_bytes") or 0)
            if nbytes <= 0 or wall_s <= 0:
                continue
            reuse = max(1, len(
                [u for u in cons.get(nid, ()) if isinstance(u, NodeId)]
            ))
            materialize_s = nbytes / _MATERIALIZE_BYTES_PER_S
            if wall_s < _MIN_CACHE_WALL_S:
                record_decision(
                    rule="AutoCacheRule", node=label, action="cache-skip",
                    provenance="measured",
                    reason=(
                        "measured wall below the cache floor "
                        "(dispatch overhead, not recompute)"
                    ),
                    cost={"recompute_s": round(wall_s, 6),
                          "floor_s": _MIN_CACHE_WALL_S,
                          "bytes": nbytes},
                )
                continue
            if wall_s <= materialize_s:
                record_decision(
                    rule="AutoCacheRule", node=label, action="cache-skip",
                    provenance="measured",
                    reason="measured recompute cheaper than materialization",
                    cost={"recompute_s": round(wall_s, 6),
                          "materialize_s": round(materialize_s, 6),
                          "bytes": nbytes},
                )
                continue
            out.append((
                wall_s / nbytes, nbytes, nid,
                ("measured", {
                    "recompute_s": round(wall_s, 6),
                    "materialize_s": round(materialize_s, 6),
                    "bytes": nbytes, "consumers": reuse,
                }),
            ))
        return out

    def _sampled_candidates(
        self, graph: Graph, targets, targets_set, cons
    ) -> List[tuple]:
        """The original sample-run path: profile a 64-row execution and
        extrapolate (rows scale bytes; compiled FLOPs scale time)."""
        profiles = Profiler(self.sample_rows).profile(graph, targets)
        out: List[tuple] = []
        for nid, prof in profiles.items():
            if self._skippable(graph, nid, targets_set, cons):
                continue
            # Output bytes scale with rows; time scales with compiled FLOPs
            # when XLA counted them (the non-linear-stage correction).
            est_bytes = int(prof.bytes * prof.scale)
            est_seconds = prof.seconds * prof.time_scale
            if est_bytes <= 0 or est_seconds <= 0:
                continue
            out.append((
                est_seconds / est_bytes, est_bytes, nid,
                ("sampled", {
                    "recompute_s": round(est_seconds, 6),
                    "bytes": est_bytes,
                }),
            ))
        return out

    def apply(self, graph: Graph, targets: Sequence[GraphId]) -> Graph:
        if self.only_if_enabled and not config.auto_cache:
            return graph
        # `is not None`: an explicit 0 means "no cache budget", not "unset".
        if self.budget_bytes is not None:
            budget = self.budget_bytes
        else:
            # Real device budget when the runtime reports one (TPU
            # bytes_limit), config fallback otherwise.
            from keystone_tpu.utils.metrics import device_hbm_bytes

            budget = device_hbm_bytes() // 4
        cons = graph.consumers(targets)
        targets_set = set(targets)
        measured = _measured_profile()
        if measured is not None:
            # Profile hit: measured costs, ZERO sample-run executions.
            candidates = self._measured_candidates(
                graph, targets, measured, targets_set, cons
            )
        else:
            candidates = self._sampled_candidates(
                graph, targets, targets_set, cons
            )
        if not candidates:
            return graph
        candidates.sort(key=lambda c: (c[0], c[1]), reverse=True)

        ops = dict(graph.operators)
        dps = dict(graph.dependencies)
        spent = 0
        changed = False
        for _ratio, nbytes, nid, (provenance, cost) in candidates:
            label = graph.operators[nid].label()
            if spent + nbytes > budget:
                record_decision(
                    rule="AutoCacheRule", node=label, action="cache-skip",
                    provenance=provenance,
                    reason=(
                        f"budget exhausted ({spent + nbytes} of {budget} "
                        "bytes would be pinned)"
                    ),
                    cost=cost,
                )
                continue
            spent += nbytes
            changed = True
            from keystone_tpu.workflow.graph import fresh_node_id

            cache_id = fresh_node_id()
            ops[cache_id] = CacheOperator()
            dps[cache_id] = (nid,)
            for consumer in cons.get(nid, ()):
                dps[consumer] = tuple(
                    cache_id if d == nid else d for d in dps[consumer]
                )
            record_decision(
                rule="AutoCacheRule", node=label, action="cache-insert",
                provenance=provenance,
                reason=(
                    "measured recompute cost exceeds materialization cost"
                    if provenance == "measured"
                    else "best sampled time-saved-per-byte under budget"
                ),
                cost=dict(cost, budget_spent=spent, budget=budget),
            )
        return Graph(ops, dps) if changed else graph


# ---------------------------------------------------------------------------
# Serve-ladder planning — the memory-bounded serving half of the planner
# ---------------------------------------------------------------------------

#: Fraction of the device budget the AOT-warmed serve ladder may pin:
#: request buffers, the in-flight window, and XLA scratch live alongside
#: the resident executables.
SERVE_LADDER_BUDGET_FRAC = 2


def plan_serve_ladder(
    ladder: Sequence[int],
    bytes_per_row: float,
    replicas: int,
    budget_bytes: Optional[int] = None,
    provenance: str = "model",
    node: str = "-",
) -> tuple:
    """Trim a candidate bucket ladder against an HBM budget BEFORE any
    rung compiles ("Memory Safe Computations with XLA", arXiv:2206.14148
    — plan memory, don't react to OOM).

    Every rung of the ladder AOT-warms into a resident executable on
    every replica, so the whole set coexists: a rung's priced residency
    is ``bytes_per_row × rung × replicas`` (conservative — on a real
    multi-HBM pool each replica's ladder lives on its own device; on the
    CPU/forced-host pools the replicas genuinely share one memory).
    Rungs are kept smallest-first while the cumulative priced bytes fit
    ``budget_bytes`` (default ``device_hbm_bytes() //
    SERVE_LADDER_BUDGET_FRAC``); the rungs that don't fit are trimmed
    top-down — capping the top bucket, so oversize batches chunk through
    a smaller rung instead of OOMing a bigger one. The smallest rung is
    always kept (serving must stay possible; a plan still over budget at
    one rung is counted and left for KG104 to flag).

    Never silent: every trim is a counted registry decision
    (``serve_plan`` counters + the optimizer decision ring).

    Returns ``(kept_ladder, trimmed_buckets, plan_info)``.
    """
    from keystone_tpu.utils.metrics import (
        device_hbm_bytes,
        serve_plan_counters,
    )

    if budget_bytes is None:
        budget_bytes = device_hbm_bytes() // SERVE_LADDER_BUDGET_FRAC
    replicas = max(1, int(replicas))
    per_bucket = {
        int(b): int(bytes_per_row * int(b)) * replicas for b in ladder
    }
    kept: List[int] = []
    trimmed: List[int] = []
    spent = 0
    for b in sorted(per_bucket):
        cost = per_bucket[b]
        if kept and spent + cost > budget_bytes:
            trimmed.append(b)
            continue
        kept.append(b)
        spent += cost
    serve_plan_counters.bump("ladders_planned")
    over_budget = spent > budget_bytes
    if over_budget:
        serve_plan_counters.bump("plans_over_budget")
    for b in trimmed:
        serve_plan_counters.bump("buckets_trimmed")
        record_decision(
            rule="PlanServeLadder", node=node,
            action=f"trim-bucket={b}",
            provenance=provenance,
            reason=(
                f"bucket {b}'s AOT-warmed executables cannot coexist "
                f"with the smaller rungs under the HBM headroom "
                f"({spent + per_bucket[b]} of {budget_bytes} bytes "
                "would be resident)"
            ),
            cost={"bucket_bytes": per_bucket[b],
                  "ladder_bytes_kept": spent,
                  "budget_bytes": budget_bytes,
                  "replicas": replicas},
        )
    if trimmed:
        # Trims are always a top segment of the sorted ladder (per-rung
        # cost is monotone in rung size and the spent total only grows),
        # so any trim caps the top bucket.
        serve_plan_counters.bump("top_bucket_capped")
    record_decision(
        rule="PlanServeLadder", node=node,
        action=f"serve_buckets={','.join(str(b) for b in kept)}",
        provenance=provenance,
        reason=(
            f"{len(kept)} rung(s) priced at {round(bytes_per_row, 1)} "
            f"B/row x {replicas} replica(s) fit the "
            f"{budget_bytes}-byte ladder budget"
            + (f"; {len(trimmed)} rung(s) trimmed" if trimmed else "")
            + ("; STILL over budget at one rung" if over_budget else "")
        ),
        cost={"bytes_per_row": round(float(bytes_per_row), 1),
              "ladder_bytes": spent, "budget_bytes": budget_bytes,
              "replicas": replicas, "trimmed": list(trimmed)},
    )
    plan_info = {
        "bytes_per_row": round(float(bytes_per_row), 1),
        "provenance": provenance,
        "replicas": replicas,
        "budget_bytes": int(budget_bytes),
        "planned_bytes": int(spent),
        "headroom_bytes": int(budget_bytes - spent),
        "per_bucket_bytes": {b: per_bucket[b] for b in kept},
        "trimmed": list(trimmed),
        "over_budget": over_budget,
    }
    return tuple(kept), list(trimmed), plan_info


class PlanResourcesRule(Rule):
    """Profile-guided resource planning: on a measured-profile hit, pick
    the executor worker count and the solver chunk rows BEFORE any device
    work, writing a session-scoped plan (``PipelineEnv.resource_plan``)
    that the executor and the chunked solvers consult wherever the
    explicit knobs (KEYSTONE_EXEC_WORKERS / KEYSTONE_SOLVE_CHUNK_ROWS)
    are unset.

    - ``exec_workers``: the graph's independent-branch width (max fan-in
      over gather-style joins), clamped to host cores — measured
      queue-wait attribution from a previous parallel run widens nothing
      (the pool was already saturated) but is surfaced in the decision.
    - ``solve_chunk_rows``: measured bytes-per-row of each estimator's
      input against the HBM budget, so PR-3's reactive OOM-halving
      becomes a planned size ("Memory Safe Computations with XLA",
      arXiv:2206.14148).

    The graph is never rewritten — this rule only plans.
    """

    #: Fraction of the device budget one solver chunk may occupy: the
    #: accumulators, the previous in-flight chunk, and XLA scratch all
    #: live alongside it.
    CHUNK_BUDGET_FRAC = 8

    #: Fraction of the device budget the host prefetch queue may hold in
    #: flight (depth × per-batch bytes): the queued batches are the next
    #: H2D transfers, and a hand-picked depth over multi-GB batches would
    #: stage more than the device can ever accept.
    PREFETCH_BUDGET_FRAC = 8

    def __init__(self, only_if_enabled: bool = False):
        self.only_if_enabled = only_if_enabled

    #: The plan keys this rule owns (and therefore clears every pass).
    PLAN_KEYS = ("exec_workers", "solve_chunk_rows", "prefetch_depth")

    def apply(self, graph: Graph, targets: Sequence[GraphId]) -> Graph:
        if not targets:
            return graph
        from keystone_tpu.workflow.executor import PipelineEnv

        plan = PipelineEnv.get().resource_plan
        # The plan describes the pipeline being optimized NOW. Clearing
        # at pass entry — BEFORE the enable gate, so disabling the
        # planner mid-session also retires its last plan — keeps a plan
        # derived from one profiled pipeline from leaking into an
        # unrelated pipeline's walk/solve in the same session (a planned
        # chunk split regroups the gram accumulation — numerics the
        # other pipeline never opted into).
        for key in self.PLAN_KEYS:
            plan.pop(key, None)
        if self.only_if_enabled and not config.plan_resources:
            return graph
        measured = _measured_profile()
        if measured is None:
            return graph
        self._plan_workers(graph, targets, measured, plan)
        self._plan_chunk_rows(graph, targets, measured, plan)
        self._plan_prefetch_depth(graph, targets, measured, plan)
        return graph

    @staticmethod
    def _branch_width(graph: Graph, targets) -> int:
        """Independent-branch width: the widest fan-in any reachable node
        joins (a gather of B branches can run B-wide)."""
        width = 1
        for nid in graph.reachable(targets):
            deps = [d for d in graph.dependencies[nid]
                    if isinstance(d, NodeId)
                    and not isinstance(graph.operators.get(d),
                                       DatasetOperator)]
            width = max(width, len(set(deps)))
        return width

    def _plan_workers(self, graph, targets, measured, plan) -> None:
        import os

        width = self._branch_width(graph, targets)
        cores = os.cpu_count() or 1
        workers = min(width, cores)
        queue_wait_ms = round(sum(
            int(e.get("queue_wait_ns") or 0)
            for e in measured.digests.values()
        ) / 1e6, 3)
        if workers >= 2:
            plan["exec_workers"] = workers
            record_decision(
                rule="PlanResourcesRule", node="-",
                action=f"exec_workers={workers}",
                provenance="measured",
                reason=(
                    f"graph has {width} independent branch(es) on a "
                    f"{cores}-core host"
                ),
                cost={"branch_width": width, "host_cores": cores,
                      "measured_queue_wait_ms": queue_wait_ms},
            )
        else:
            record_decision(
                rule="PlanResourcesRule", node="-", action="exec_workers=0",
                provenance="measured",
                reason=(
                    "serial walk kept: "
                    + (f"only {cores} host core(s)" if cores < 2
                       else "no independent branches to overlap")
                ),
                cost={"branch_width": width, "host_cores": cores},
            )

    def _plan_chunk_rows(self, graph, targets, measured, plan) -> None:
        from keystone_tpu.workflow.graph import structural_digest
        from keystone_tpu.utils.mesh import num_data_shards
        from keystone_tpu.utils.metrics import device_hbm_bytes

        dmemo: Dict[GraphId, Any] = {}
        budget = device_hbm_bytes() // self.CHUNK_BUDGET_FRAC
        # A solver chunk is row-sharded over the mesh, so each device
        # holds rows/shards of it: the per-device HBM budget prices
        # bytes_per_row ÷ shard_count, not the whole chunk. The stored
        # profile's fingerprint already pins device_count at load
        # (ProfileFingerprintError), so a 1-device profile can never
        # reach this sizing under an 8-device mesh.
        try:
            shards = max(1, int(num_data_shards()))
        except RuntimeError:  # deviceless backend: plan as one shard
            shards = 1
        for nid in graph.reachable(targets):
            op = graph.operators[nid]
            if not isinstance(op, EstimatorOperator):
                continue
            deps = graph.dependencies[nid]
            if not deps or not isinstance(deps[0], NodeId):
                continue
            entry = measured.node(structural_digest(graph, deps[0], dmemo))
            if entry is None:
                continue
            rows = int(entry.get("out_rows") or 0)
            nbytes = int(entry.get("out_bytes") or 0)
            if rows <= 0 or nbytes <= 0:
                continue
            bytes_per_row = nbytes / rows
            planned = int(budget // max(1.0, bytes_per_row / shards))
            if planned >= rows or planned < 1:
                # The whole measured input fits the chunk budget: nothing
                # to plan (streams smaller than the budget never split).
                continue
            prior = int(plan.get("solve_chunk_rows", 0) or 0)
            plan["solve_chunk_rows"] = (
                min(prior, planned) if prior else planned
            )
            record_decision(
                rule="PlanResourcesRule", node=op.label(),
                action=f"solve_chunk_rows={planned}",
                provenance="measured",
                reason=(
                    f"measured {bytes_per_row:.0f} B/row over "
                    f"{shards} shard(s) vs {budget} B per-device chunk "
                    "budget — planned split replaces reactive OOM-halving"
                ),
                cost={"bytes_per_row": round(bytes_per_row, 1),
                      "chunk_budget_bytes": budget,
                      "data_shards": shards,
                      "measured_rows": rows},
            )

    def _plan_prefetch_depth(self, graph, targets, measured, plan) -> None:
        """Clamp the hand-picked prefetch depth against the budget share:
        depth × measured per-batch bytes staged in the host queue must
        not overrun ``device_hbm_bytes() // PREFETCH_BUDGET_FRAC`` —
        those batches are the next H2D transfers. Only ever clamps DOWN
        (the hand-picked ``config.prefetch_depth`` stays the ceiling);
        an exported KEYSTONE_PREFETCH_DEPTH wins outright at the consume
        site (loaders/stream.py)."""
        from keystone_tpu.workflow.graph import structural_digest
        from keystone_tpu.utils.metrics import (
            device_hbm_bytes,
            serve_plan_counters,
        )

        hand_picked = int(config.prefetch_depth)
        if hand_picked <= 1:
            return  # depth 0/1 is already minimal: nothing to clamp
        budget = device_hbm_bytes() // self.PREFETCH_BUDGET_FRAC
        dmemo: Dict[GraphId, Any] = {}
        worst = None  # (per-batch bytes, node label, rows per batch)
        for nid in graph.reachable(targets):
            op = graph.operators[nid]
            if not isinstance(op, EstimatorOperator):
                continue
            deps = graph.dependencies[nid]
            if not deps or not isinstance(deps[0], NodeId):
                continue
            entry = measured.node(structural_digest(graph, deps[0], dmemo))
            if entry is None:
                continue
            # out_rows/out_bytes are LAST-WRITE per-call sizes (the store
            # contract, utils/metrics._DIGEST_DELTA_FIELDS), so `rows`
            # already IS the measured per-batch row count — never divide
            # by the accumulated call count.
            rows = int(entry.get("out_rows") or 0)
            nbytes = int(entry.get("out_bytes") or 0)
            if rows <= 0 or nbytes <= 0:
                continue
            bytes_per_row = nbytes / rows
            # The prefetcher stages whatever the producer yields: the
            # planned solver chunk when one exists, else the measured
            # per-call batch.
            chunk_rows = int(plan.get("solve_chunk_rows", 0) or 0)
            batch_rows = chunk_rows if chunk_rows else rows
            batch_bytes = int(bytes_per_row * batch_rows)
            if worst is None or batch_bytes > worst[0]:
                worst = (batch_bytes, op.label(), batch_rows)
        if worst is None:
            return  # no measured estimator input: nothing to price
        batch_bytes, label, batch_rows = worst
        fit = max(1, int(budget // max(1, batch_bytes)))
        if fit >= hand_picked:
            record_decision(
                rule="PlanResourcesRule", node=label,
                action=f"prefetch_depth={hand_picked}",
                provenance="measured",
                reason=(
                    f"hand-picked depth {hand_picked} x {batch_bytes} "
                    f"B/batch fits the {budget} B prefetch budget share"
                ),
                cost={"batch_bytes": batch_bytes,
                      "batch_rows": batch_rows,
                      "prefetch_budget_bytes": budget},
            )
            return
        plan["prefetch_depth"] = fit
        serve_plan_counters.bump("prefetch_clamped")
        record_decision(
            rule="PlanResourcesRule", node=label,
            action=f"prefetch_depth={fit}",
            provenance="measured",
            reason=(
                f"hand-picked depth {hand_picked} x {batch_bytes} B/batch "
                f"overruns the {budget} B prefetch budget share — clamped "
                f"to {fit}"
            ),
            cost={"batch_bytes": batch_bytes, "batch_rows": batch_rows,
                  "prefetch_budget_bytes": budget,
                  "hand_picked_depth": hand_picked},
        )
