"""Whole-pipeline optimization rules: auto-caching and node-level solver
selection.

Ref: src/main/scala/workflow/{AutoCacheRule,NodeOptimizationRule}.scala
(SURVEY.md §2.1, §3.5) [unverified].
"""

from __future__ import annotations

import logging
import weakref
from typing import Dict, List, Sequence

from keystone_tpu.config import config
from keystone_tpu.workflow.cache import CacheOperator, NodeProfile, Profiler
from keystone_tpu.workflow.graph import Graph, GraphId, NodeId
from keystone_tpu.workflow.operators import (
    DatasetOperator,
    EstimatorOperator,
    TransformerOperator,
)
from keystone_tpu.workflow.optimizer import Rule


def _scaled_shape(value, scale: float):
    """Full-size shape estimate from a row-sampled value: axis 0 scales by
    the sample's row ratio, trailing dims are exact."""
    shape = getattr(value, "shape", None)
    if shape is None or len(shape) == 0:
        return None
    if scale == 1.0:
        return tuple(shape)
    return (int(round(shape[0] * scale)),) + tuple(shape[1:])


class NodeOptimizationRule(Rule):
    """Swap optimizable estimators for concrete implementations chosen from
    data statistics at optimization time.

    An estimator opts in by defining ``optimize_node(self, data_shape) ->
    estimator``. Shapes are read from directly-attached dataset nodes when
    available (the simple with_data case); estimators fed by deeper
    transformer subgraphs get their (n, d) from ONE sampled prefix run per
    apply (the reference's optimizer profiles sampled prefixes for stats
    anywhere in the DAG — SURVEY.md §3.5), so cost-model dispatch happens
    at optimization time, not fit time.

    The concrete replacement is memoized per (estimator, shapes): every
    optimizer pass over any copy of the graph swaps in the SAME concrete
    instance, so the replaced node's structural hash — and therefore its fit
    cache entry — is stable across executions.
    """

    def __init__(self, sample_rows: int = 64):
        self._memo: Dict[tuple, tuple] = {}
        # Deep-graph shapes memoized by the deps' CONTENT-STABLE prefix
        # digests: repeated optimizer passes over graph copies hit this
        # instead of re-executing the sampled prefix. id-based prefixes
        # digest to None and are never memoized — a recycled id must not
        # serve stale shapes (same rule as the executor's fit cache).
        self._shape_memo: Dict[tuple, List] = {}
        self.sample_rows = sample_rows

    def clear_cache(self) -> None:
        self._memo.clear()
        self._shape_memo.clear()

    @staticmethod
    def _dep_prefix_key(graph: Graph, deps: Sequence[GraphId]):
        """(memo key, sampleable): the key is a tuple of content-stable
        prefix digests (None when any prefix lacks content identity — then
        shapes are recomputed each pass rather than risking a stale hit);
        sampleable=False when a prefix reaches an unbound source, where a
        sample run could never resolve the shapes."""
        from keystone_tpu.workflow.graph import structural_digest

        digests = []
        for d in deps:
            if not isinstance(d, NodeId):
                return None, False
            if graph.sources_of([d]):
                return None, False
            digests.append(structural_digest(graph, d))
        if any(x is None for x in digests):
            return None, True
        return tuple(digests), True

    def _sample_prefixes(self, graph: Graph, targets: Sequence[GraphId]):
        """One row-sampled execution of the input prefixes of every
        optimizable estimator that still NEEDS sampling — deep-graph deps
        not already served by the shape memo or by direct dataset shapes.
        All such estimators in the DAG share the run."""
        needed = []
        for nid in graph.reachable(targets):
            op = graph.operators[nid]
            if not isinstance(op, EstimatorOperator) or (
                getattr(op.estimator, "optimize_node", None) is None
            ):
                continue
            deps = graph.dependencies[nid]
            if all(
                isinstance(d, NodeId)
                and isinstance(graph.operators.get(d), DatasetOperator)
                for d in deps
            ):
                continue  # direct with_data case: shapes read off datasets
            pkey, sampleable = self._dep_prefix_key(graph, deps)
            if not sampleable:
                continue  # unbound prefix: sampling can't resolve it
            if pkey is not None and pkey in self._shape_memo:
                continue  # already served without execution
            needed.extend(d for d in deps if isinstance(d, NodeId))
        return Profiler(self.sample_rows).sample_values(graph, needed)

    def apply(self, graph: Graph, targets: Sequence[GraphId]) -> Graph:
        out = graph
        sampled = None  # lazy: only deep-graph estimators pay for the run
        sample_ok = True
        for nid in graph.reachable(targets):
            op = graph.operators[nid]
            if not isinstance(op, EstimatorOperator):
                continue
            optimize = getattr(op.estimator, "optimize_node", None)
            if optimize is None:
                continue
            deps = graph.dependencies[nid]
            shapes = []
            for dep in deps:
                shape = None
                if isinstance(dep, NodeId):
                    dep_op = graph.operators.get(dep)
                    if isinstance(dep_op, DatasetOperator):
                        shape = getattr(dep_op.data, "shape", None)
                shapes.append(shape)
            if shapes and any(s is None for s in shapes):
                pkey, sampleable = self._dep_prefix_key(graph, deps)
                if not sampleable:
                    continue  # unbound prefix: nothing to sample or dispatch
                memo_shapes = (
                    self._shape_memo.get(pkey) if pkey is not None else None
                )
                if memo_shapes is not None:
                    shapes = memo_shapes
                else:
                    if sampled is None:
                        try:
                            sampled = self._sample_prefixes(graph, targets)
                            sample_ok = True
                        except Exception:  # lint: broad-ok sample-run probe over arbitrary user operators
                            # A prefix that can't run on a 64-row sample
                            # must not crash optimization: affected
                            # estimators keep their fit-time dispatch.
                            logging.getLogger(__name__).warning(
                                "sampled prefix run failed; deep-graph "
                                "estimators keep fit-time dispatch",
                                exc_info=True,
                            )
                            sampled = ({}, {}, {})
                            sample_ok = False
                    values, scales, rows_ok = sampled
                    shapes = [
                        s
                        if s is not None
                        else (
                            _scaled_shape(
                                values.get(dep), scales.get(dep, 1.0)
                            )
                            # A row-changing prefix (sampler/aggregator)
                            # makes scaled-n a lie; defer to fit-time.
                            if rows_ok.get(dep, False)
                            else None
                        )
                        for s, dep in zip(shapes, deps)
                    ]
                    # Legitimate deferrals memoize; a FAILED run must not —
                    # a transient error would otherwise disable
                    # optimize-time dispatch for this prefix forever.
                    # Bounded by refusing inserts when full, NOT by
                    # clearing: a mid-apply clear would strand estimators
                    # that _sample_prefixes skipped on a memo hit, letting
                    # them memoize all-None shapes from a run that never
                    # sampled their deps.
                    if (
                        pkey is not None
                        and sample_ok
                        and len(self._shape_memo) < 1024
                    ):
                        self._shape_memo[pkey] = shapes
            if not shapes or shapes[0] is None:
                continue
            key = (id(op.estimator), tuple(shapes))
            memoized = self._memo.get(key)
            if memoized is not None and memoized[0]() is op.estimator:
                concrete = memoized[1]
            else:
                concrete = optimize(*shapes)
                # The original is held weakly with eviction: when the user
                # drops their pipeline the memo entry (and the concrete
                # estimator it pins, and in turn that estimator's fit-cache
                # entry with its pinned training data) is freed. A dead or
                # recycled id can never serve a stale concrete because the
                # weakref identity check above fails first.
                try:
                    ref = weakref.ref(
                        op.estimator,
                        lambda _r, key=key: self._memo.pop(key, None),
                    )
                except TypeError:  # not weak-referenceable: don't memoize
                    ref = None
                if ref is not None:
                    self._memo[key] = (ref, concrete)
            if concrete is not None and concrete is not op.estimator:
                out = out.replace_node(
                    nid, EstimatorOperator(concrete), graph.dependencies[nid]
                )
        return out


class AutoCacheRule(Rule):
    """Profile a sample run, then greedily insert cache nodes under a
    memory budget, best time-saved-per-byte first.

    The session cache persists values across executions (fit → later
    applies, repeated gets over graph copies); within one execution the
    structural-hash memo already dedups, so the win is cross-execution
    recompute avoidance — the reference's cached-RDD reuse, with HBM/host
    RAM as the budget.
    """

    def __init__(
        self,
        budget_bytes: int | None = None,
        sample_rows: int = 64,
        min_consumers: int = 1,
        only_if_enabled: bool = False,
    ):
        self.budget_bytes = budget_bytes
        self.sample_rows = sample_rows
        self.min_consumers = min_consumers
        # The default optimizer installs the rule unconditionally and gates
        # each apply on config.auto_cache, so toggling the flag mid-session
        # takes effect instead of silently depending on when PipelineEnv
        # was constructed. Directly-constructed rules stay unconditional.
        self.only_if_enabled = only_if_enabled

    def apply(self, graph: Graph, targets: Sequence[GraphId]) -> Graph:
        if self.only_if_enabled and not config.auto_cache:
            return graph
        # `is not None`: an explicit 0 means "no cache budget", not "unset".
        if self.budget_bytes is not None:
            budget = self.budget_bytes
        else:
            # Real device budget when the runtime reports one (TPU
            # bytes_limit), config fallback otherwise.
            from keystone_tpu.utils.metrics import device_hbm_bytes

            budget = device_hbm_bytes() // 4
        profiles = Profiler(self.sample_rows).profile(graph, targets)
        if not profiles:
            return graph
        cons = graph.consumers(targets)
        targets_set = set(targets)
        candidates: List[tuple[float, int, NodeId]] = []
        for nid, prof in profiles.items():
            op = graph.operators[nid]
            if isinstance(op, (DatasetOperator, CacheOperator)):
                continue  # data already lives in host memory; cache is cache
            if isinstance(op, EstimatorOperator):
                # Fits persist in the fit cache already, and a cache node
                # between an estimator and its delegating consumer would
                # hide the fitted transformer from Pipeline.fit's rewrite.
                continue
            if nid in targets_set or len(cons.get(nid, ())) < self.min_consumers:
                continue
            # Output bytes scale with rows; time scales with compiled FLOPs
            # when XLA counted them (the non-linear-stage correction).
            est_bytes = int(prof.bytes * prof.scale)
            est_seconds = prof.seconds * prof.time_scale
            if est_bytes <= 0 or est_seconds <= 0:
                continue
            candidates.append((est_seconds / est_bytes, est_bytes, nid))
        candidates.sort(reverse=True)

        ops = dict(graph.operators)
        dps = dict(graph.dependencies)
        spent = 0
        for _ratio, nbytes, nid in candidates:
            if spent + nbytes > budget:
                continue
            spent += nbytes
            from keystone_tpu.workflow.graph import fresh_node_id

            cache_id = fresh_node_id()
            ops[cache_id] = CacheOperator()
            dps[cache_id] = (nid,)
            for consumer in cons.get(nid, ()):
                dps[consumer] = tuple(
                    cache_id if d == nid else d for d in dps[consumer]
                )
        return Graph(ops, dps)
