"""Caching operator + sample-run profiler.

Ref: src/main/scala/workflow/{Cacher,AutoCacheRule}.scala and the sampling
profiler feeding it (SURVEY.md §2.1, §3.5, §5 tracing row) [unverified].

The reference's question was "which RDDs to cache in executor memory"; the
TPU rebuild's question is "which intermediates to persist in the session
cache instead of recomputing" — the budget is HBM/host RAM instead of
executor heap, but the sample-profile → greedy-knapsack shape carries over
(SURVEY.md §7 hard part 5: the algorithm carries over, the constants
don't).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.workflow.graph import Graph, GraphId, NodeId, SourceId
from keystone_tpu.workflow.operators import (
    DatasetOperator,
    DelegatingOperator,
    EstimatorOperator,
    Operator,
)

# Profiling hint memo: (transformer signature, sample shape, dtype, scale)
# -> FLOPs ratio. Cost analysis compiles twice per entry; graph copies and
# repeated optimizer passes hit this instead. Bounded: past the cap the
# OLDEST entry is evicted (dict keeps insertion order) — wholesale clearing
# would force every live pipeline's next profile to recompile at once.
_FLOPS_MEMO_CAP = 256
_flops_ratio_memo: Dict[Any, float | None] = {}


class CacheOperator(Operator):
    """Identity node whose value the executor persists in the session cache
    (the Cacher analog). Inserted by AutoCacheRule or pipeline.cache()."""

    persist = True

    def execute(self, deps):
        return deps[0]

    def signature(self):
        # Transparent for prefix hashing: caching must not change identity.
        return ("cache",)

    def prefix_hash(self, dep_hashes):
        return dep_hashes[0]

    def prefix_digest(self, dep_digests):
        # Same transparency cross-process: cache placement is a profiling
        # decision and must not perturb content keys.
        return dep_digests[0]

    def label(self):
        return "Cache"


def _value_bytes(v: Any) -> int:
    if isinstance(v, (jax.Array, np.ndarray)):
        return int(v.size) * v.dtype.itemsize
    if hasattr(v, "nbytes"):  # e.g. SparseBatch
        return int(v.nbytes)
    if isinstance(v, (list, tuple)):
        return sum(_value_bytes(x) for x in v)
    if isinstance(v, str):
        return len(v)
    return 64  # opaque host object: nominal


def _sample(data: Any, max_rows: int) -> Any:
    try:
        return data[:max_rows]
    except TypeError:
        return data


@dataclass
class NodeProfile:
    seconds: float
    bytes: int
    scale: float  # full-size / sample-size row ratio estimate
    # XLA-counted FLOPs ratio full/sample for jittable device nodes: the
    # non-linear correction (a stage quadratic in rows has ratio ≈ scale²,
    # which linear time extrapolation under-costs by scale×).
    flops_ratio: float | None = None

    @property
    def time_scale(self) -> float:
        """Multiplier from sampled seconds to full-size seconds: compiled
        FLOPs when XLA counted them, row ratio otherwise (host nodes)."""
        return self.flops_ratio if self.flops_ratio is not None else self.scale


class Profiler:
    """Executes the graph on row-sampled dataset nodes, timing each operator
    and sizing each output (the AutoCacheRule sampling profiler). Device
    nodes additionally get an XLA cost-model correction: the transformer is
    lowered at both the sample and the full batch shape and the compiled
    FLOP counts replace the linear row extrapolation (SURVEY.md §7 hard
    part 5)."""

    def __init__(self, sample_rows: int = 64):
        self.sample_rows = sample_rows

    @staticmethod
    def _flops_ratio(transformer, sample_input, scale: float) -> float | None:
        """full/sample FLOPs from the compiled HLO; None when not countable
        (host nodes, non-arrays, compile failure). Memoized on (signature,
        shape, scale) so graph copies and repeated passes don't recompile."""
        if scale <= 1.0 or not getattr(transformer, "jittable", False):
            return None
        try:
            x = jnp.asarray(sample_input)
            if x.ndim < 1:
                return None
            key = None
            try:
                key = (transformer.signature(), x.shape, str(x.dtype), scale)
                if key in _flops_ratio_memo:
                    return _flops_ratio_memo[key]
            except TypeError:
                key = None  # unhashable signature: compute uncached
            full = jax.ShapeDtypeStruct(
                (int(round(x.shape[0] * scale)),) + x.shape[1:], x.dtype
            )
            sample = jax.ShapeDtypeStruct(x.shape, x.dtype)
            from keystone_tpu.utils.metrics import cost_analysis

            f_sample = cost_analysis(transformer.apply_batch, sample)["flops"]
            f_full = cost_analysis(transformer.apply_batch, full)["flops"]
            ratio = None
            if f_sample > 0 and f_full > 0:
                ratio = f_full / f_sample
            if key is not None:
                while len(_flops_ratio_memo) >= _FLOPS_MEMO_CAP:
                    _flops_ratio_memo.pop(next(iter(_flops_ratio_memo)))
                _flops_ratio_memo[key] = ratio
            return ratio
        except Exception:  # lint: broad-ok cost-model probe: any lowering failure means 'no FLOPs correction'
            return None

    @staticmethod
    def _execute_node(op: Operator, dep_vals: List[Any]) -> Any:
        """Execute one non-dataset node on sampled inputs. Estimator fits
        run on a COPY of the user's estimator: a sample fit is a profiling
        probe, and its side effects (fitted state, dispatch fields like
        ``last_choice``, counters) must not leak into the object the user
        holds and the real execution will fit."""
        if isinstance(op, EstimatorOperator):
            import copy

            try:
                probe = copy.deepcopy(op.estimator)
            except Exception:  # lint: broad-ok deepcopy of arbitrary estimator state can raise anything: shallow guard
                probe = copy.copy(op.estimator)
            return EstimatorOperator(probe).execute(dep_vals)
        return op.execute(dep_vals)

    def _sampled_walk(self, graph: Graph, ids: Sequence[GraphId], on_node=None):
        """Shared traversal core: row-sample dataset nodes, execute
        everything reachable from ``ids`` in topological order. Returns
        ({node: value}, {node: row-scale}, {node: rows-reliable}). A node's
        scale only predicts its FULL row count when every prefix node
        preserved row count at sample size; a row-changing node (sampler,
        aggregator, windower) poisons reliability downstream. ``on_node(nid,
        op, dep_vals, value, scale, dt)`` observes each executed node (the
        profiling hook)."""
        values: Dict[GraphId, Any] = {}
        scales: Dict[GraphId, float] = {}
        rows_ok: Dict[GraphId, bool] = {}
        if not ids:
            return values, scales, rows_ok
        for nid in graph.reachable(ids):
            op = graph.operators[nid]
            deps = graph.dependencies[nid]
            if any(isinstance(d, SourceId) for d in deps):
                continue  # unbound inference path: no sample data
            if any(d not in values and isinstance(d, NodeId) for d in deps):
                continue  # upstream skipped
            dep_vals = [values[d] for d in deps]
            if isinstance(op, DatasetOperator):
                full = op.data
                value = _sample(full, self.sample_rows)
                try:
                    scale = max(len(full), 1) / max(len(value), 1)
                except TypeError:
                    scale = 1.0
                dt = 0.0
                ok = True
            else:
                t0 = time.perf_counter()
                value = self._execute_node(op, dep_vals)
                jax.block_until_ready(value) if isinstance(
                    value, jax.Array
                ) else None
                dt = time.perf_counter() - t0
                scale = max([scales.get(d, 1.0) for d in deps], default=1.0)
                ok = all(rows_ok.get(d, True) for d in deps)
                if ok:
                    in_rows = next(
                        (
                            len(v)
                            for v in dep_vals
                            if hasattr(v, "__len__")
                        ),
                        None,
                    )
                    try:
                        out_rows = len(value)
                    except TypeError:
                        out_rows = None
                    if (
                        in_rows is not None
                        and out_rows is not None
                        and out_rows != in_rows
                    ):
                        ok = False  # row-changing node: scale no longer = n
            values[nid], scales[nid], rows_ok[nid] = value, scale, ok
            if on_node is not None:
                on_node(nid, op, dep_vals, value, scale, dt)
        return values, scales, rows_ok

    def sample_values(
        self, graph: Graph, needed: Sequence[GraphId]
    ) -> tuple[
        Dict[GraphId, Any], Dict[GraphId, float], Dict[GraphId, bool]
    ]:
        """Row-sampled prefix execution without timing: returns
        ({node: value}, {node: row-scale}, {node: rows-reliable}) for
        everything reachable from ``needed``. This is the stats channel of
        the sampling profiler — how NodeOptimizationRule obtains (n, d) for
        estimators fed by transformer subgraphs rather than
        directly-attached datasets (the reference profiles sampled prefixes
        for stats anywhere in the DAG; SURVEY.md §3.5)."""
        return self._sampled_walk(graph, needed)

    def profile(
        self, graph: Graph, targets: Sequence[GraphId]
    ) -> Dict[NodeId, NodeProfile]:
        profiles: Dict[NodeId, NodeProfile] = {}

        def on_node(nid, op, dep_vals, value, scale, dt):
            if isinstance(op, DatasetOperator):
                profiles[nid] = NodeProfile(
                    seconds=dt, bytes=_value_bytes(value), scale=scale
                )
                return
            # The fitted-transformer case (DelegatingOperator) carries
            # its transformer as a dependency value, not an attribute.
            transformer = getattr(op, "transformer", None)
            batch_val = dep_vals[0] if dep_vals else None
            if (
                transformer is None
                and isinstance(op, DelegatingOperator)
                and len(dep_vals) == 2
            ):
                transformer, batch_val = dep_vals[0], dep_vals[1]
            if transformer is not None and getattr(
                transformer, "jittable", False
            ):
                # Re-time on the warmed path so the recorded seconds exclude
                # jit compilation — compile time scaled by the FLOPs ratio
                # would dominate (and falsify) the ranking. (The walk's
                # first execute above was the warm-up.)
                t0 = time.perf_counter()
                out = op.execute(dep_vals)
                jax.block_until_ready(out) if isinstance(
                    out, jax.Array
                ) else None
                dt = time.perf_counter() - t0
            flops_ratio = None
            if transformer is not None:
                flops_ratio = self._flops_ratio(transformer, batch_val, scale)
            profiles[nid] = NodeProfile(
                seconds=dt,
                bytes=_value_bytes(value),
                scale=scale,
                flops_ratio=flops_ratio,
            )

        self._sampled_walk(graph, targets, on_node)
        return profiles
