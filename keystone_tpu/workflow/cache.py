"""Caching operator + sample-run profiler.

Ref: src/main/scala/workflow/{Cacher,AutoCacheRule}.scala and the sampling
profiler feeding it (SURVEY.md §2.1, §3.5, §5 tracing row) [unverified].

The reference's question was "which RDDs to cache in executor memory"; the
TPU rebuild's question is "which intermediates to persist in the session
cache instead of recomputing" — the budget is HBM/host RAM instead of
executor heap, but the sample-profile → greedy-knapsack shape carries over
(SURVEY.md §7 hard part 5: the algorithm carries over, the constants
don't).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Sequence

import jax
import numpy as np

from keystone_tpu.workflow.graph import Graph, GraphId, NodeId, SourceId
from keystone_tpu.workflow.operators import (
    DatasetOperator,
    EstimatorOperator,
    Operator,
)


class CacheOperator(Operator):
    """Identity node whose value the executor persists in the session cache
    (the Cacher analog). Inserted by AutoCacheRule or pipeline.cache()."""

    persist = True

    def execute(self, deps):
        return deps[0]

    def signature(self):
        # Transparent for prefix hashing: caching must not change identity.
        return ("cache",)

    def prefix_hash(self, dep_hashes):
        return dep_hashes[0]

    def prefix_digest(self, dep_digests):
        # Same transparency cross-process: cache placement is a profiling
        # decision and must not perturb content keys.
        return dep_digests[0]

    def label(self):
        return "Cache"


def _value_bytes(v: Any) -> int:
    if isinstance(v, (jax.Array, np.ndarray)):
        return int(v.size) * v.dtype.itemsize
    if isinstance(v, (list, tuple)):
        return sum(_value_bytes(x) for x in v)
    if isinstance(v, str):
        return len(v)
    return 64  # opaque host object: nominal


def _sample(data: Any, max_rows: int) -> Any:
    try:
        return data[:max_rows]
    except TypeError:
        return data


@dataclass
class NodeProfile:
    seconds: float
    bytes: int
    scale: float  # full-size / sample-size row ratio estimate


class Profiler:
    """Executes the graph on row-sampled dataset nodes, timing each operator
    and sizing each output (the AutoCacheRule sampling profiler)."""

    def __init__(self, sample_rows: int = 64):
        self.sample_rows = sample_rows

    def profile(
        self, graph: Graph, targets: Sequence[GraphId]
    ) -> Dict[NodeId, NodeProfile]:
        profiles: Dict[NodeId, NodeProfile] = {}
        values: Dict[GraphId, Any] = {}
        scales: Dict[GraphId, float] = {}
        for nid in graph.reachable(targets):
            op = graph.operators[nid]
            deps = graph.dependencies[nid]
            if any(isinstance(d, SourceId) for d in deps):
                continue  # unbound inference path: not profiled
            if any(d not in values and isinstance(d, NodeId) for d in deps):
                continue  # upstream skipped
            dep_vals = [values[d] for d in deps]
            if isinstance(op, DatasetOperator):
                full = op.data
                sampled = _sample(full, self.sample_rows)
                try:
                    scale = max(len(full), 1) / max(len(sampled), 1)
                except TypeError:
                    scale = 1.0
                t0 = time.perf_counter()
                values[nid] = sampled
                dt = time.perf_counter() - t0
                scales[nid] = scale
            else:
                t0 = time.perf_counter()
                out = op.execute(dep_vals)
                jax.block_until_ready(out) if isinstance(out, jax.Array) else None
                dt = time.perf_counter() - t0
                values[nid] = out
                scales[nid] = max(
                    [scales.get(d, 1.0) for d in deps], default=1.0
                )
            profiles[nid] = NodeProfile(
                seconds=dt,
                bytes=_value_bytes(values[nid]),
                scale=scales[nid],
            )
        return profiles
