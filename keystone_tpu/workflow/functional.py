"""Replay a fitted pipeline's optimized graph as one pure function.

A fitted pipeline is transformer-only (every Estimator already replaced by
its fitted Transformer), so its optimized graph can be re-executed
functionally over a jit argument — the whole featurization chain traces
into ONE XLA computation. This is how the driver's ``entry()`` exposes the
flagship forward step and how the AOT tests compile the full two-branch
ImageNet featurizer for a v5e target without a chip (SURVEY.md §7 hard
part 6: both deep branches fused without blowing compile time).

``layout`` threads the mesh-native SpecLayout convention through the
replay: the returned function is lowered ONCE under ``jax.jit`` with
explicit row-sharded ``in_shardings``/``out_shardings``, so the whole
fused chain is data-parallel by contract — never by whatever placement
the caller's batch happened to carry.
"""

from __future__ import annotations

import jax.numpy as jnp

from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.operators import (
    DatasetOperator,
    GatherOperator,
    TransformerOperator,
)


def fitted_forward(pipeline, example, layout=None):
    """A jittable ``fn(X)`` replaying ``pipeline``'s optimized transformer
    graph over the argument.

    ``pipeline`` must be fitted (transformer-only); ``example`` is a small
    batch used once to build + optimize the graph (chain fusion, node
    merging) — the returned function is pure and shape-polymorphic over
    the leading batch axis up to what the transformers allow.

    ``layout`` (a ``utils.mesh.SpecLayout``) lowers the replay with the
    mesh-native explicit shardings instead of returning the un-jitted pure
    function: rows sharded over the data axis in AND out, one lowering for
    the whole chain. Batch rows must divide the layout's shard count (pad
    with ``layout.pad_put`` and trim, the mask-pad idiom, when they
    don't). ``None`` keeps the legacy behavior: the caller jits (and
    places) the pure function however it likes.
    """
    ds = pipeline(example)
    g = PipelineEnv.get().optimizer.execute(ds.graph, [ds.sink])
    order = g.reachable([ds.sink])

    def fn(X):
        values = {}
        for nid in order:
            op = g.operators[nid]
            deps = g.dependencies[nid]
            if isinstance(op, DatasetOperator):
                values[nid] = X
            elif isinstance(op, TransformerOperator):
                values[nid] = op.transformer.apply_batch(values[deps[0]])
            elif isinstance(op, GatherOperator):
                values[nid] = jnp.concatenate(
                    [values[d] for d in deps], axis=-1
                )
            else:
                raise TypeError(f"unexpected op in fitted graph: {op!r}")
        return values[ds.sink]

    if layout is None:
        return fn
    return layout.jit(fn)
