"""Replay a fitted pipeline's optimized graph as one pure function.

A fitted pipeline is transformer-only (every Estimator already replaced by
its fitted Transformer), so its optimized graph can be re-executed
functionally over a jit argument — the whole featurization chain traces
into ONE XLA computation. This is how the driver's ``entry()`` exposes the
flagship forward step and how the AOT tests compile the full two-branch
ImageNet featurizer for a v5e target without a chip (SURVEY.md §7 hard
part 6: both deep branches fused without blowing compile time).
"""

from __future__ import annotations

import jax.numpy as jnp

from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.operators import (
    DatasetOperator,
    GatherOperator,
    TransformerOperator,
)


def fitted_forward(pipeline, example):
    """A jittable ``fn(X)`` replaying ``pipeline``'s optimized transformer
    graph over the argument.

    ``pipeline`` must be fitted (transformer-only); ``example`` is a small
    batch used once to build + optimize the graph (chain fusion, node
    merging) — the returned function is pure and shape-polymorphic over
    the leading batch axis up to what the transformers allow.
    """
    ds = pipeline(example)
    g = PipelineEnv.get().optimizer.execute(ds.graph, [ds.sink])
    order = g.reachable([ds.sink])

    def fn(X):
        values = {}
        for nid in order:
            op = g.operators[nid]
            deps = g.dependencies[nid]
            if isinstance(op, DatasetOperator):
                values[nid] = X
            elif isinstance(op, TransformerOperator):
                values[nid] = op.transformer.apply_batch(values[deps[0]])
            elif isinstance(op, GatherOperator):
                values[nid] = jnp.concatenate(
                    [values[d] for d in deps], axis=-1
                )
            else:
                raise TypeError(f"unexpected op in fitted graph: {op!r}")
        return values[ds.sink]

    return fn
