"""Fitted-pipeline serialization.

Ref: the reference exports models by plain serialization of fitted
transformers (SURVEY.md §5 checkpoint/resume row) [unverified]. A fitted
pipeline here is transformer objects holding array pytrees; pickling works
once per-instance jit caches are stripped (they rebuild lazily on first
use after load).
"""

from __future__ import annotations

import pickle
from typing import Any

from keystone_tpu.workflow.pipeline import Pipeline, Transformer


def _strip_jit(obj: Any) -> None:
    if isinstance(obj, Transformer):
        obj.__dict__.pop("_jit_cache", None)
        for sub in getattr(obj, "stages", []):
            _strip_jit(sub)


def save_pipeline(pipeline: Pipeline, path: str) -> None:
    """Persist a fitted (transformer-only) pipeline. Call .fit() first."""
    from keystone_tpu.workflow.operators import (
        EstimatorOperator,
        TransformerOperator,
    )

    for op in pipeline.graph.operators.values():
        if isinstance(op, EstimatorOperator):
            raise ValueError(
                "pipeline still contains unfitted estimators; call .fit() "
                "before saving"
            )
        if isinstance(op, TransformerOperator):
            _strip_jit(op.transformer)
    with open(path, "wb") as f:
        pickle.dump(pipeline, f)


def load_pipeline(path: str) -> Pipeline:
    with open(path, "rb") as f:
        return pickle.load(f)
