"""Fitted-pipeline serialization and versioned model artifacts.

Ref: the reference exports models by plain serialization of fitted
transformers (SURVEY.md §5 checkpoint/resume row) [unverified]. A fitted
pipeline here is transformer objects holding array pytrees; pickling works
once per-instance jit caches are stripped (they rebuild lazily on first
use after load).

Two layers:

- ``save_pipeline`` / ``load_pipeline`` — the bare pickle round-trip
  (kept for in-process checkpoints and the existing round-trip tests).
- ``save_artifact`` / ``load_artifact`` — the **fit→serve handoff**
  format the serving daemon (workflow/daemon.py) consumes: one file
  holding a JSON header (schema version, a blake2b fingerprint covering
  the header itself plus the payload, content-stable pipeline digest
  where available, the ``environment_fingerprint()`` backend subset,
  optional serve hints) followed by the pickled pipeline.
  ``load_artifact`` verifies the schema version and the fingerprint
  BEFORE unpickling — a truncated upload, a bit-rotted file (payload OR
  header: a flipped serve hint fails as loudly as a flipped weight), or
  a format from a different release raises a typed
  :class:`ArtifactVersionError` at load time instead of failing deep
  inside ``apply`` under traffic.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from keystone_tpu.workflow.pipeline import Pipeline, Transformer

#: Bump when the on-disk artifact layout changes incompatibly. A loader
#: refuses any other version by name — never by crashing mid-unpickle.
ARTIFACT_SCHEMA_VERSION = 1

_MAGIC = b"KEYSTONE_ARTIFACT\n"


class ArtifactVersionError(ValueError):
    """The artifact cannot be served: wrong schema version, payload bytes
    that do not match the recorded pipeline fingerprint (corruption or
    tampering), or a fingerprint pin the caller required that the file
    does not carry."""


def _strip_jit(obj: Any) -> None:
    if isinstance(obj, Transformer):
        obj.__dict__.pop("_jit_cache", None)
        for sub in getattr(obj, "stages", []):
            _strip_jit(sub)


def _check_fitted(pipeline: Pipeline) -> None:
    from keystone_tpu.workflow.operators import (
        EstimatorOperator,
        TransformerOperator,
    )

    for op in pipeline.graph.operators.values():
        if isinstance(op, EstimatorOperator):
            raise ValueError(
                "pipeline still contains unfitted estimators; call .fit() "
                "before saving"
            )
        if isinstance(op, TransformerOperator):
            _strip_jit(op.transformer)


def save_pipeline(pipeline: Pipeline, path: str) -> None:
    """Persist a fitted (transformer-only) pipeline. Call .fit() first."""
    _check_fitted(pipeline)
    with open(path, "wb") as f:
        pickle.dump(pipeline, f)


def load_pipeline(path: str) -> Pipeline:
    with open(path, "rb") as f:
        return pickle.load(f)


def pipeline_digest(pipeline: Pipeline) -> Optional[str]:
    """Content-stable digest of the fitted pipeline TEMPLATE (the free
    serve input tokenized), via ``workflow.graph.structural_digest`` —
    the same identity the cross-process fit cache keys on. None when any
    operator lacks content identity; the artifact then relies on the
    artifact fingerprint alone."""
    from keystone_tpu.workflow.graph import structural_digest

    return structural_digest(
        pipeline.graph, pipeline.sink, source_token="serve-input"
    )


def _artifact_environment() -> Dict[str, Any]:
    """The ``environment_fingerprint()`` subset an artifact records:
    enough to explain "trained where", small enough to live in every
    header."""
    import platform as _platform

    from keystone_tpu.utils.metrics import runtime_fingerprint

    env = dict(runtime_fingerprint())
    env["python"] = _platform.python_version()
    try:
        import numpy as _np

        env["numpy"] = _np.__version__
    except ImportError:  # header stays useful without numpy
        pass
    return env


@dataclass
class ModelArtifact:
    """One versioned, fingerprinted fit→serve handoff unit."""

    schema_version: int
    fingerprint: str  # blake2b hex of canonical-header-sans-fp + payload
    pipeline_digest: Optional[str]
    environment: Dict[str, Any]
    created_unix: float
    serve: Dict[str, Any] = field(default_factory=dict)
    pipeline: Optional[Pipeline] = None
    path: Optional[str] = None

    def header(self) -> Dict[str, Any]:
        return {
            "schema_version": self.schema_version,
            "fingerprint": self.fingerprint,
            "pipeline_digest": self.pipeline_digest,
            "environment": self.environment,
            "created_unix": self.created_unix,
            "serve": dict(self.serve),
        }


def _artifact_fingerprint(header_sans_fp: Dict[str, Any],
                          payload: bytes) -> str:
    """Integrity fingerprint over the WHOLE artifact: the canonical
    (sorted-key JSON) header minus the fingerprint field itself, plus
    the pickled payload. Covering the header means a flipped serve hint
    (feature_shape/dtype) or digest fails verification loudly at load,
    instead of a daemon warming a wrong-shaped ladder and 400ing every
    request. Canonical re-serialization is stable across a JSON
    round-trip (sort_keys + default ensure_ascii on both sides)."""
    h = hashlib.blake2b(digest_size=20)
    h.update(json.dumps(header_sans_fp, sort_keys=True).encode())
    h.update(payload)
    return h.hexdigest()


def save_artifact(
    pipeline: Pipeline,
    path: str,
    feature_shape: Optional[tuple] = None,
    dtype: Optional[str] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> ModelArtifact:
    """Serialize a fitted pipeline into a versioned, fingerprinted
    artifact file the serving daemon can load and hot-swap.

    ``feature_shape``/``dtype`` are optional serve hints (the per-row
    traffic signature) recorded in the header so a daemon can AOT-warm
    the successor's ladder without being told the shape again. Written
    atomically (tmp + ``os.replace``): a crash mid-save never leaves a
    half-artifact where a swap could pick it up."""
    _check_fitted(pipeline)
    payload = pickle.dumps(pipeline)
    serve: Dict[str, Any] = {}
    if feature_shape is not None:
        serve["feature_shape"] = [int(d) for d in feature_shape]
    if dtype is not None:
        serve["dtype"] = str(dtype)
    if extra:
        serve.update(extra)
    art = ModelArtifact(
        schema_version=ARTIFACT_SCHEMA_VERSION,
        fingerprint="",
        pipeline_digest=pipeline_digest(pipeline),
        environment=_artifact_environment(),
        # lint: ok(KL005) artifact provenance carries a real wall-clock timestamp
        created_unix=time.time(),
        serve=serve,
        pipeline=pipeline,
        path=path,
    )
    sans_fp = art.header()
    del sans_fp["fingerprint"]
    art.fingerprint = _artifact_fingerprint(sans_fp, payload)
    # Unique tmp name (not a fixed path+".tmp"): two concurrent saves to
    # the same destination must not interleave bytes into one tmp file,
    # and a failed write must not litter a stale tmp next to the target.
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp",
        dir=os.path.dirname(os.path.abspath(path)),
    )
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(_MAGIC)
            f.write(
                json.dumps(art.header(), sort_keys=True).encode() + b"\n"
            )
            f.write(payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return art


def _read_header(f, path: str) -> Dict[str, Any]:
    """Magic + header-line parse + validation, shared by the header-only
    reader and the full loader (one set of error messages; the file
    cursor is left at the payload)."""
    magic = f.read(len(_MAGIC))
    if magic != _MAGIC:
        raise ArtifactVersionError(
            f"{path}: not a keystone model artifact (bad magic; a bare "
            "save_pipeline pickle loads via load_pipeline instead)"
        )
    header_line = f.readline()
    try:
        header = json.loads(header_line)
    except ValueError as e:
        raise ArtifactVersionError(
            f"{path}: unreadable artifact header: {e}"
        ) from None
    if not isinstance(header, dict):
        raise ArtifactVersionError(f"{path}: artifact header is not a dict")
    return header


def read_artifact_header(path: str) -> Dict[str, Any]:
    """The artifact's JSON header alone — no unpickling, so an operator
    (or /healthz) can name a file's fingerprint without loading the
    model. Raises ArtifactVersionError on a non-artifact file."""
    with open(path, "rb") as f:
        return _read_header(f, path)


def load_artifact(
    path: str, expect_fingerprint: Optional[str] = None
) -> ModelArtifact:
    """Load + verify one artifact: schema version first, then the
    whole-artifact fingerprint (header + payload, before unpickling a
    single byte of the model), then the optional caller pin. Every
    mismatch is an ArtifactVersionError naming what disagreed."""
    with open(path, "rb") as f:
        header = _read_header(f, path)
        payload = f.read()
    version = header.get("schema_version")
    if version != ARTIFACT_SCHEMA_VERSION:
        raise ArtifactVersionError(
            f"{path}: artifact schema version {version!r} != supported "
            f"{ARTIFACT_SCHEMA_VERSION}; re-export the model with this "
            "release's save_artifact"
        )
    recorded = header.get("fingerprint")
    sans_fp = dict(header)
    sans_fp.pop("fingerprint", None)
    actual = _artifact_fingerprint(sans_fp, payload)
    if recorded != actual:
        raise ArtifactVersionError(
            f"{path}: artifact fingerprint {actual} does not match the "
            f"recorded {recorded!r} — the header or payload is corrupt "
            "or was modified after export"
        )
    if expect_fingerprint is not None and expect_fingerprint != recorded:
        raise ArtifactVersionError(
            f"{path}: artifact fingerprint {recorded} != required "
            f"{expect_fingerprint}"
        )
    pipeline = pickle.loads(payload)
    return ModelArtifact(
        schema_version=int(version),
        fingerprint=str(recorded),
        pipeline_digest=header.get("pipeline_digest"),
        environment=dict(header.get("environment") or {}),
        created_unix=float(header.get("created_unix") or 0.0),
        serve=dict(header.get("serve") or {}),
        pipeline=pipeline,
        path=path,
    )
