from keystone_tpu.workflow.graph import Graph, GraphId, NodeId, SourceId
from keystone_tpu.workflow.pipeline import (
    Estimator,
    FusedTransformer,
    LabelEstimator,
    Pipeline,
    PipelineDataset,
    Transformer,
)
from keystone_tpu.workflow.analysis import (
    Diagnostic,
    LintError,
    LintReport,
    lint_graph,
)
from keystone_tpu.workflow.executor import GraphExecutor, PipelineEnv
from keystone_tpu.workflow.functional import fitted_forward
from keystone_tpu.workflow.optimizer import (
    ChainFusionRule,
    EquivalentNodeMergeRule,
    Optimizer,
    Rule,
    default_optimizer,
)
from keystone_tpu.workflow.serialization import (
    ArtifactVersionError,
    ModelArtifact,
    load_artifact,
    load_pipeline,
    save_artifact,
    save_pipeline,
)
from keystone_tpu.workflow.online import (
    OnlineState,
    OnlineStateError,
    OnlineTrainer,
    supports_partial_fit,
)
from keystone_tpu.workflow.serving import (
    CompiledPipeline,
    DeadlineExceeded,
    PipelineService,
    QueueFullError,
    RowDependenceError,
    ServiceClosed,
    WorkerDiedError,
)

__all__ = [
    "Graph",
    "GraphId",
    "NodeId",
    "SourceId",
    "Transformer",
    "FusedTransformer",
    "Estimator",
    "LabelEstimator",
    "Pipeline",
    "PipelineDataset",
    "PipelineEnv",
    "GraphExecutor",
    "fitted_forward",
    "Optimizer",
    "Rule",
    "ChainFusionRule",
    "EquivalentNodeMergeRule",
    "default_optimizer",
    "save_pipeline",
    "load_pipeline",
    "save_artifact",
    "load_artifact",
    "ModelArtifact",
    "ArtifactVersionError",
    "OnlineState",
    "OnlineStateError",
    "OnlineTrainer",
    "supports_partial_fit",
    "Diagnostic",
    "LintError",
    "LintReport",
    "lint_graph",
    "CompiledPipeline",
    "PipelineService",
    "RowDependenceError",
    "QueueFullError",
    "DeadlineExceeded",
    "ServiceClosed",
    "WorkerDiedError",
]
