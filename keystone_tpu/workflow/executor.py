"""Graph executor + session environment.

Ref: src/main/scala/workflow/{GraphExecutor,PipelineEnv,Prefix}.scala
[unverified]. The executor walks the DAG in topological order, memoizing
values by *structural prefix hash* so that:

- duplicated subgraphs (created by composition's copy-on-instantiate) are
  computed once per execution;
- estimator fits are memoized across executions in ``PipelineEnv.fit_cache``
  (the reference's fitted-prefix state reuse);
- values marked by the auto-caching rule persist in ``node_cache``.

Where the reference's executor schedules Spark jobs per stage, ours executes
operators whose jittable chains were pre-fused into single XLA computations by
the optimizer.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

from keystone_tpu.workflow.graph import (
    Graph,
    GraphId,
    NodeId,
    SourceId,
    structural_digest,
    structural_hash,
)
from keystone_tpu.workflow.operators import (
    DelegatingOperator,
    EstimatorOperator,
    Operator,
    TransformerOperator,
)


class UnboundSourceError(RuntimeError):
    pass


def _span_shape(value) -> Any:
    """A JSON-able shape for a node-span attr: array shape, list length,
    or None — best-effort, never a failure."""
    shape = getattr(value, "shape", None)
    if shape is not None:
        try:
            return [int(s) for s in shape]
        except (TypeError, ValueError):
            return None
    if isinstance(value, (list, tuple)):
        return [len(value)]
    return None


def _no_sources(sid: SourceId):
    raise UnboundSourceError(
        f"graph has unbound source {sid!r}; apply the pipeline to data first"
    )


def _observed_execute(op, deps, tracer, profile):
    """Execute one node under the tracer and/or the resource profile.

    The profiled path blocks on array outputs so wall time covers device
    completion (dispatch vs wait attributed separately) and attributes
    cost-model FLOPs/bytes via the memoized abstract AOT compile — the
    node's VALUES are untouched, which is what keeps KEYSTONE_PROFILE=0
    and =1 fits bit-identical."""
    import time

    label = op.label()
    if profile is None:
        t0 = tracer.now()
        out = op.execute(deps)
        tracer.record(
            "node:" + label, "executor", t0,
            cache="miss", shape=_span_shape(out),
        )
        return out

    import jax

    from keystone_tpu.utils.metrics import node_cost_analysis, peak_hbm_bytes

    hbm0 = peak_hbm_bytes()
    t0 = time.perf_counter_ns()
    out = op.execute(deps)
    t_disp = time.perf_counter_ns()
    if isinstance(out, jax.Array):
        out.block_until_ready()
    end = time.perf_counter_ns()
    hbm1 = peak_hbm_bytes()
    cost = None
    if (
        isinstance(op, TransformerOperator)
        and deps
        and hasattr(deps[0], "shape")
        and hasattr(deps[0], "dtype")
    ):
        cost = node_cost_analysis(op.transformer, deps[0])
    profile.record_node(
        label,
        wall_ns=end - t0,
        dispatch_ns=t_disp - t0,
        flops=(cost or {}).get("flops"),
        bytes_accessed=(cost or {}).get("bytes_accessed"),
        out_nbytes=getattr(out, "nbytes", None),
        hbm_delta=(
            hbm1 - hbm0 if hbm0 is not None and hbm1 is not None else None
        ),
        cache="miss",
    )
    if tracer is not None:
        tracer.record(
            "node:" + label, "executor", t0, end,
            cache="miss", shape=_span_shape(out), profiled=True,
        )
    return out


class GraphExecutor:
    def __init__(self, env: "PipelineEnv"):
        self.env = env

    def execute_many(
        self, graph: Graph, targets: Sequence[GraphId]
    ) -> Dict[GraphId, Any]:
        """Evaluate all targets in one pass with shared memoization.

        The walk CUTS at persistent-cache hits: a node whose structural hash
        is already in the fit/node cache becomes a leaf and its upstream
        subgraph is never visited — cached values short-circuit
        recomputation, not just value storage.
        """
        from keystone_tpu.utils.metrics import active_profile, active_tracer

        # Resolved once per execution walk (the active_plan discipline):
        # the untraced/unprofiled walk pays one None check per node,
        # nothing more.
        tracer = active_tracer()
        profile = active_profile()
        for t in targets:
            if isinstance(t, SourceId):
                _no_sources(t)
        hmemo: Dict[GraphId, int] = {}
        dmemo: Dict[GraphId, Any] = {}

        def h_of(nid: GraphId) -> int:
            return structural_hash(graph, nid, _no_sources, hmemo)

        def d_of(nid: GraphId):
            if self.env.disk_cache is None:
                return None
            dk = structural_digest(graph, nid, dmemo)
            if dk is None:
                return None
            # Salt with the numeric regime: a fit computed under different
            # dtype/precision settings is a different artifact. Platform is
            # deliberately NOT included — CPU/TPU runs are treated as
            # numerically equivalent the way the reference treats local[n]
            # vs cluster (SURVEY.md §4 [unverified]).
            from keystone_tpu.config import config
            from keystone_tpu.workflow.fingerprint import digest_tree

            return digest_tree(
                (
                    "v1",
                    dk,
                    config.default_dtype,
                    config.accum_dtype,
                    config.solver_precision,
                    config.solver_storage_dtype,
                )
            )

        values: Dict[GraphId, Any] = {}
        by_hash: Dict[int, Any] = {}
        order: List[GraphId] = []
        seen = set()
        stack: List[tuple] = [(t, False) for t in targets]
        while stack:
            gid, processed = stack.pop()
            if processed:
                order.append(gid)
                continue
            if gid in seen or isinstance(gid, SourceId):
                continue
            seen.add(gid)
            op = graph.operators[gid]
            h = h_of(gid)
            hit = None
            if isinstance(op, EstimatorOperator) and h in self.env.fit_cache:
                hit = self.env.fit_cache[h][0]
            elif isinstance(op, EstimatorOperator):
                dk = d_of(gid)
                if dk is not None:
                    hit = self.env.disk_cache.get(dk)
                    if hit is not None:  # promote to the session cache too
                        self._cache_fit(graph, gid, h, op, hit)
            elif h in self.env.node_cache:
                hit = self.env.node_cache[h][0]
            if hit is not None:
                values[gid] = by_hash[h] = hit
                if tracer is not None:
                    tracer.instant(
                        "node:" + op.label(), "executor", cache="hit"
                    )
                if profile is not None:
                    profile.record_node(op.label(), cache="hit")
                continue  # leaf: do not descend into its dependencies
            stack.append((gid, True))
            for dep in graph.dependencies[gid]:
                if dep not in seen and isinstance(dep, NodeId):
                    stack.append((dep, False))

        for nid in order:
            h = h_of(nid)
            op = graph.operators[nid]
            if h in by_hash:
                values[nid] = by_hash[h]
                if tracer is not None:
                    tracer.instant(
                        "node:" + op.label(), "executor", cache="memo"
                    )
                if profile is not None:
                    profile.record_node(op.label(), cache="memo")
                # A cache node hashes identically to its dependency (it's an
                # identity), so it lands here — still persist its value.
                if getattr(op, "persist", False) and h not in self.env.node_cache:
                    self.env.node_cache[h] = (
                        values[nid],
                        self._prefix_pins(graph, nid),
                    )
                continue
            deps = [values[d] for d in graph.dependencies[nid]]
            if tracer is None and profile is None:
                out = op.execute(deps)
            else:
                out = _observed_execute(op, deps, tracer, profile)
            values[nid] = by_hash[h] = out
            if isinstance(op, EstimatorOperator):
                self._cache_fit(graph, nid, h, op, out)
                dk = d_of(nid)
                if dk is not None:
                    self.env.disk_cache.put(dk, out)
            if getattr(op, "persist", False):
                self.env.node_cache[h] = (out, self._prefix_pins(graph, nid))
        return values

    def _cache_fit(self, graph: Graph, nid: NodeId, h: int, op, out) -> None:
        """Cache a fitted transformer, scoped to the estimator's lifetime.

        The entry pins every prefix object except the estimator itself, which
        is held weakly with an eviction callback: when the user drops the
        estimator (and its pipelines), the entry — and the training data it
        pins — is freed, and the now-recyclable ids can never produce a stale
        hash hit because eviction precedes reuse.
        """
        import weakref

        estimator = op.estimator
        pins = tuple(
            p for p in self._prefix_pins(graph, nid) if p is not estimator
        )
        fit_cache = self.env.fit_cache
        try:
            keeper: Any = weakref.ref(
                estimator, lambda _ref, h=h: fit_cache.pop(h, None)
            )
        except TypeError:  # not weak-referenceable: pin strongly
            keeper = estimator
        fit_cache[h] = (out, pins, keeper)

    @staticmethod
    def _prefix_pins(graph: Graph, nid: NodeId) -> tuple:
        """Strong references to every object whose id() feeds the prefix hash
        of ``nid``. While a cache entry holds its pins, CPython cannot recycle
        those ids, so a hash hit always means the same live objects."""
        pins = []
        for n in graph.reachable([nid]):
            pins.extend(graph.operators[n].pinned_objects())
        return tuple(pins)

    def execute(self, graph: Graph, target: GraphId) -> Any:
        return self.execute_many(graph, [target])[target]

    def fit_estimators(self, graph: Graph, sink: GraphId) -> Graph:
        """Force every estimator reachable from ``sink`` and rewrite the graph
        so each DelegatingOperator becomes a concrete TransformerOperator.

        This is the `Pipeline.fit` lowering: the result graph is
        transformer-only on the inference path.
        """
        graph = self.env.optimizer.execute(graph, [sink])
        order = graph.reachable([sink])
        est_nodes = [
            n for n in order if isinstance(graph.operators[n], EstimatorOperator)
        ]
        if est_nodes:
            fitted = self.execute_many(graph, est_nodes)
        else:
            fitted = {}
        ops = dict(graph.operators)
        dps = dict(graph.dependencies)
        for nid in order:
            op = graph.operators[nid]
            if isinstance(op, DelegatingOperator):
                est_dep, input_dep = graph.dependencies[nid]
                # See through identity cache nodes between estimator and
                # delegating consumer.
                while (
                    est_dep in graph.operators
                    and getattr(graph.operators[est_dep], "persist", False)
                ):
                    est_dep = graph.dependencies[est_dep][0]
                if est_dep in fitted:
                    ops[nid] = TransformerOperator(fitted[est_dep])
                    dps[nid] = (input_dep,)
        # Prune: drops the now-unreferenced estimator nodes and their training
        # DatasetOperator subtrees so a fitted pipeline doesn't pin the
        # training set in memory.
        return Graph(ops, dps).pruned([sink])

    def serving_chain(self, graph: Graph, source: SourceId, sink: GraphId):
        """Lower a FITTED pipeline graph to the one transformer the serving
        layer AOT-compiles: optimize (fusing jittable chains), then require
        the source→sink path to be a linear chain of jittable
        TransformerOperators. Identity cache nodes are seen through;
        anything else (gather joins, unfitted estimators, host nodes) is
        refused with an error naming the offender — the serving engine
        compiles ONE program per bucket and cannot host-hop mid-chain.
        """
        from keystone_tpu.workflow.pipeline import FusedTransformer

        g = self.env.optimizer.execute(graph, [sink])
        chain: List[Any] = []
        gid = sink
        while gid != source:
            if isinstance(gid, SourceId):
                raise ValueError(
                    f"serve path ends at foreign source {gid!r}, not the "
                    "pipeline's own input"
                )
            op = g.operators[gid]
            deps = g.dependencies[gid]
            if getattr(op, "persist", False):  # identity Cache node
                gid = deps[0]
                continue
            if not isinstance(op, TransformerOperator):
                raise TypeError(
                    f"cannot compile {op.label()} for serving: the serve "
                    "path must be a fitted, linear transformer chain (fit "
                    "the pipeline first; gather/estimator/host nodes cannot "
                    "join the single-program bucketed executable)"
                )
            if not op.transformer.jittable:
                raise TypeError(
                    f"{type(op.transformer).__name__} is not jittable; the "
                    "AOT serving path compiles the whole chain as one XLA "
                    "program"
                )
            if len(deps) != 1:
                raise TypeError(
                    f"serve path node {op.label()} has {len(deps)} inputs; "
                    "bucketed serving requires a linear chain"
                )
            chain.append(op.transformer)
            gid = deps[0]
        if not chain:
            raise ValueError("pipeline has no transformers on the serve path")
        chain.reverse()
        return chain[0] if len(chain) == 1 else FusedTransformer(chain)


class PipelineEnv:
    """Session state: optimizer, executor, and persistent caches.

    Ref: workflow/PipelineEnv.scala [unverified].
    """

    _instance: Optional["PipelineEnv"] = None

    def __init__(self):
        from keystone_tpu.config import resolved_cache_dir
        from keystone_tpu.workflow.optimizer import default_optimizer

        self.optimizer = default_optimizer()
        self.executor = GraphExecutor(self)
        # structural hash of estimator node -> fitted Transformer
        self.fit_cache: Dict[int, Any] = {}
        # structural hash -> persisted value (auto-cache rule / Cacher nodes)
        self.node_cache: Dict[int, Any] = {}
        # Cross-process fitted-prefix store, keyed by content digest; the
        # env-presence-over-config precedence lives in config.py so the
        # os.environ read stays out of this module (keystone-lint KL003).
        cache_dir = resolved_cache_dir()
        self.disk_cache = None
        if cache_dir:
            from keystone_tpu.workflow.disk_cache import DiskFitCache

            try:
                self.disk_cache: Optional["DiskFitCache"] = DiskFitCache(
                    cache_dir
                )
            except OSError as e:  # uncreatable dir: degrade, never abort
                import logging

                logging.getLogger("keystone_tpu").warning(
                    "disk fit cache disabled: cannot create %s (%s)",
                    cache_dir,
                    e,
                )

    @classmethod
    def get(cls) -> "PipelineEnv":
        if cls._instance is None:
            cls._instance = PipelineEnv()
        return cls._instance

    @classmethod
    def reset(cls) -> None:
        cls._instance = None

    def clear_caches(self) -> None:
        """Drop all memoized fits, persisted values, and optimizer-held state
        (frees pinned data)."""
        self.fit_cache.clear()
        self.node_cache.clear()
        for _name, rules, _iters in getattr(self.optimizer, "batches", []):
            for rule in rules:
                clear = getattr(rule, "clear_cache", None)
                if clear is not None:
                    clear()

    def optimize_and_execute(self, graph: Graph, sink: GraphId) -> Any:
        g = self.optimizer.execute(graph, [sink])
        return self.executor.execute(g, sink)

    def execute(self, graph: Graph, sink: GraphId) -> Any:
        return self.executor.execute(graph, sink)
