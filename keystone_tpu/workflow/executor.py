"""Graph executor + session environment.

Ref: src/main/scala/workflow/{GraphExecutor,PipelineEnv,Prefix}.scala
[unverified]. The executor walks the DAG in topological order, memoizing
values by *structural prefix hash* so that:

- duplicated subgraphs (created by composition's copy-on-instantiate) are
  computed once per execution;
- estimator fits are memoized across executions in ``PipelineEnv.fit_cache``
  (the reference's fitted-prefix state reuse);
- values marked by the auto-caching rule persist in ``node_cache``.

Where the reference's executor schedules Spark jobs per stage, ours executes
operators whose jittable chains were pre-fused into single XLA computations by
the optimizer.
"""

from __future__ import annotations

import contextvars
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence

from keystone_tpu.workflow.graph import (
    Graph,
    GraphId,
    NodeId,
    SourceId,
    structural_digest,
    structural_hash,
)
from keystone_tpu.workflow.operators import (
    DelegatingOperator,
    EstimatorOperator,
    Operator,
    TransformerOperator,
)


class UnboundSourceError(RuntimeError):
    pass


def _span_shape(value) -> Any:
    """A JSON-able shape for a node-span attr: array shape, list length,
    or None — best-effort, never a failure."""
    shape = getattr(value, "shape", None)
    if shape is not None:
        try:
            return [int(s) for s in shape]
        except (TypeError, ValueError):
            return None
    if isinstance(value, (list, tuple)):
        return [len(value)]
    return None


def _no_sources(sid: SourceId):
    raise UnboundSourceError(
        f"graph has unbound source {sid!r}; apply the pipeline to data first"
    )


def _out_rows(value) -> Optional[int]:
    """Leading-axis row count of a node output (None when rowless) — the
    per-row-bytes denominator the resource planner sizes chunks with."""
    shape = getattr(value, "shape", None)
    if shape is not None and len(shape) >= 1:
        try:
            return int(shape[0])
        except (TypeError, ValueError):
            return None
    if isinstance(value, (list, tuple)):
        return len(value)
    return None


def _observed_execute(op, deps, tracer, profile, worker=None,
                      queue_wait_ns=None, digest=None):
    """Execute one node under the tracer and/or the resource profile.

    The profiled path blocks on array outputs so wall time covers device
    completion (dispatch vs wait attributed separately) and attributes
    cost-model FLOPs/bytes via the memoized abstract AOT compile — the
    node's VALUES are untouched, which is what keeps KEYSTONE_PROFILE=0
    and =1 fits bit-identical.

    ``worker`` / ``queue_wait_ns`` come from the parallel walk: which pool
    thread ran the node and how long it sat ready before a worker picked
    it up. The serial walk passes neither, so its spans and profile rows
    are unchanged. ``digest`` (the node's content-stable prefix digest,
    precomputed by the walk) additionally files the measurement under the
    profile's digest-keyed aggregates — the rows the profile store
    persists and the optimizer rules re-match."""
    import time

    label = op.label()
    extra = {}
    if worker is not None:
        extra["worker"] = worker
    if queue_wait_ns is not None:
        extra["queue_wait_ms"] = round(queue_wait_ns / 1e6, 4)
    if profile is None:
        t0 = tracer.now()
        out = op.execute(deps)
        tracer.record(
            "node:" + label, "executor", t0,
            cache="miss", shape=_span_shape(out), **extra,
        )
        return out

    import jax

    from keystone_tpu.utils.mesh import value_data_shards
    from keystone_tpu.utils.metrics import (
        node_cost_analysis,
        peak_hbm_bytes,
        profile_forced,
    )

    hbm0 = peak_hbm_bytes()
    t0 = time.perf_counter_ns()
    out = op.execute(deps)
    t_disp = time.perf_counter_ns()
    if isinstance(out, jax.Array):
        out.block_until_ready()
    end = time.perf_counter_ns()
    hbm1 = peak_hbm_bytes()
    if profile_forced() and not isinstance(op, EstimatorOperator):
        # Explicit profiling sessions (fit(profile=True) — the rows the
        # profile store persists for the optimizer) re-time on the warmed
        # path so recorded wall excludes one-time jit compile/tracing —
        # compile cost attributed as recompute cost would make every
        # trivial jittable node look cache-worthy (the sampled Profiler's
        # warmed re-time, applied to the measured walk). Non-estimator
        # operators are pure, so the extra execution cannot change state;
        # the FIRST output is still the one returned. Ambient
        # KEYSTONE_PROFILE=1 observation never pays the double execution.
        t0 = time.perf_counter_ns()
        warm = op.execute(deps)
        t_disp = time.perf_counter_ns()
        if isinstance(warm, jax.Array):
            warm.block_until_ready()
        end = time.perf_counter_ns()
    cost = None
    if (
        isinstance(op, TransformerOperator)
        and deps
        and hasattr(deps[0], "shape")
        and hasattr(deps[0], "dtype")
    ):
        cost = node_cost_analysis(op.transformer, deps[0])
    profile.record_node(
        label,
        wall_ns=end - t0,
        dispatch_ns=t_disp - t0,
        flops=(cost or {}).get("flops"),
        bytes_accessed=(cost or {}).get("bytes_accessed"),
        out_nbytes=getattr(out, "nbytes", None),
        hbm_delta=(
            hbm1 - hbm0 if hbm0 is not None and hbm1 is not None else None
        ),
        cache="miss",
        queue_wait_ns=queue_wait_ns,
        worker=worker,
        digest=digest,
        out_rows=_out_rows(out),
        out_shape=_span_shape(out),
        # Mesh-width provenance on every measured row: a 1-shard profile
        # is visibly 1-shard, and (with the store fingerprint's
        # device_count) can never size a wider mesh's plan.
        data_shards=value_data_shards(out),
    )
    if tracer is not None:
        tracer.record(
            "node:" + label, "executor", t0, end,
            cache="miss", shape=_span_shape(out), profiled=True, **extra,
        )
    return out


#: Thread-local flag marking "this thread is a parallel-walk worker": an
#: estimator fit that internally applies pipelines (fisher featurizers,
#: auto-cache profiling) re-enters ``execute_many`` on a pool thread, and
#: a nested walk must take the serial path instead of spawning a second
#: pool under the first (bounded concurrency stays bounded).
_walk_tls = threading.local()

_pool_lock = threading.Lock()
_shared_pool = None
_shared_pool_workers = 0


def _exec_pool(workers: int):
    """The process-wide executor worker pool, built lazily and reused
    across walks (the ``active_tracer()`` memo idiom): a streamed
    per-batch apply loop must not pay thread spawn/join on every walk.
    Rebuilt when the requested width changes; the old pool's threads
    drain without blocking the caller."""
    global _shared_pool, _shared_pool_workers
    from concurrent.futures import ThreadPoolExecutor

    with _pool_lock:
        if _shared_pool is None or _shared_pool_workers != workers:
            if _shared_pool is not None:
                _shared_pool.shutdown(wait=False)
            _shared_pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="keystone-exec"
            )
            _shared_pool_workers = workers
        return _shared_pool


class _ParallelWalk:
    """Dependency-counting ready-set scheduler over one executor walk.

    The serial walk's execution loop, parallelized: every node of the
    (already cache-cut) ``order`` becomes a task; a node dispatches onto
    a bounded ``ThreadPoolExecutor`` the moment its inputs are resolved,
    so independent branches — the ImageNet SIFT|LCS featurizer's two
    fisher fronts, parallel text encoders — run concurrently, and a
    host-bound node (native SIFT, JPEG decode, tokenize) stops blocking
    sibling-branch device work. Jittable device nodes stay non-blocking:
    ``op.execute`` rides JAX async dispatch, returning array futures the
    workers never materialize — a value is only consumed host-side at
    estimator fits and host transformers, exactly where the serial walk
    would block too.

    Semantics preserved bit-identically (the scheduler reorders only
    provably independent nodes; per-node math is untouched):

    - cache cuts: persistent-cache hits were already resolved as leaves
      by the discovery pass — this walk never sees their subgraphs;
    - structural dedup: the FIRST node (in topological order) with a
      given prefix hash is the hash's owner and executes; same-hash
      duplicates become memo tasks that wait for the owner and copy its
      value — two duplicates can never compute concurrently;
    - fit/persist cache writes happen under the walk lock, on the same
      paths the serial loop uses;
    - a fault on a worker thread cancels the remaining schedule and
      re-raises on the calling thread (chaos parity with serial).

    Shared state (``values``/``by_hash``/``pend`` and the session cache
    writes) is guarded by ``self._lock``; mutation outside it lives only
    in ``*_locked`` methods, and ``_run_node_worker`` is registered in
    keystone-lint's ``KNOWN_THREAD_TARGETS`` so KL001 covers the pool
    threads.
    """

    def __init__(self, executor, graph, order, values, by_hash, hmemo,
                 d_of, tracer, profile, workers, node_digests=None):
        self.ex = executor
        self.graph = graph
        self.values = values
        self.by_hash = by_hash
        self.hashes = hmemo
        self.tracer = tracer
        self.profile = profile
        self.workers = workers
        # Precomputed in the single-threaded build phase (like dks): the
        # shared digest memo is never touched from a worker thread.
        self.node_digests: Dict[NodeId, Any] = node_digests or {}
        # The build thread's context, copied into every pool task: the
        # profile_scope() contextvar (and anything else context-scoped)
        # must follow the walk onto its workers — without this, a
        # fit(profile=True) parallel walk would lose the forced scope on
        # pool threads while keeping it on the serial path.
        self._ctx = contextvars.copy_context()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pool = None
        self._error: Optional[BaseException] = None
        self._stop = False
        self._inflight = 0
        self._remaining = len(order)
        self._ready_ns: Dict[NodeId, int] = {}
        # Build phase (single-threaded): hash ownership, per-node pending
        # counts, and the dependent edges the completions will decrement.
        # Estimator disk-cache digests are precomputed HERE so the shared
        # digest memo is never touched from a worker thread.
        self.is_memo: set = set()
        self.dks: Dict[NodeId, Any] = {}
        self.pend: Dict[NodeId, int] = {}
        self.dependents: Dict[NodeId, List[NodeId]] = {}
        self.initial: List[NodeId] = []
        owner_of_hash: Dict[int, NodeId] = {}
        for nid in order:
            h = hmemo[nid]
            if h in by_hash:
                # Produced by a discovery-phase cache hit: a memo task
                # with no prerequisites (the value already exists).
                self.is_memo.add(nid)
                deps = set()
            elif h in owner_of_hash:
                # Duplicate: wait for the hash owner, then copy its
                # value off by_hash (the dependency edge IS the link —
                # no separate owner lookup exists at execute time).
                self.is_memo.add(nid)
                deps = {owner_of_hash[h]}
            else:
                owner_of_hash[h] = nid
                deps = {
                    d for d in graph.dependencies[nid]
                    if isinstance(d, NodeId) and d not in values
                }
                op = graph.operators[nid]
                if (
                    isinstance(op, EstimatorOperator)
                    and executor.env.disk_cache is not None
                ):
                    self.dks[nid] = d_of(nid)
            self.pend[nid] = len(deps)
            for d in deps:
                self.dependents.setdefault(d, []).append(nid)
            if not deps:
                self.initial.append(nid)

    def run(self) -> None:
        """Drive the schedule to completion on the shared bounded pool;
        block the caller until every node resolved (or re-raise the
        first worker fault once in-flight tasks drained). The exit wait
        covers BOTH completion shapes — every submitted task retires
        through ``_finish_locked`` before the loop can exit, so no task
        of this walk can still be running when run() returns."""
        pool = _exec_pool(self.workers)
        with self._lock:
            self._pool = pool
            for nid in self.initial:
                self._submit_locked(nid)
            while self._remaining and not (
                self._stop and self._inflight == 0
            ):
                self._cv.wait()
            self._pool = None
        if self._error is not None:
            raise self._error

    def _submit_locked(self, nid: NodeId) -> None:
        """Hand one ready node to the pool (caller holds the lock)."""
        import time

        if self._stop or self._pool is None:
            return
        self._ready_ns[nid] = time.perf_counter_ns()
        # submit BEFORE the in-flight increment: if the shared pool was
        # rebuilt under this walk (a width change from another thread),
        # submit raises without leaking a phantom in-flight count — the
        # raise surfaces as the walk's error instead of wedging run()'s
        # drain wait forever. The spawned task cannot observe the
        # bookkeeping early: its first action takes this same lock.
        # Each task runs under its own COPY of the walk's build-thread
        # context (a Context cannot be entered concurrently).
        self._pool.submit(
            self._ctx.copy().run, self._run_node_worker, nid
        )
        self._inflight += 1

    def _run_node_worker(self, nid: NodeId) -> None:
        """One pool task (a keystone-lint KNOWN_THREAD_TARGETS entry):
        execute one ready node outside the lock, publish its value, and
        schedule dependents that became ready. Any exception cancels the
        remaining schedule and surfaces on the calling thread."""
        import time

        with self._lock:
            if self._stop:
                # A sibling already faulted: tasks queued behind it must
                # not burn work (estimator fits, disk writes) on a walk
                # that is already doomed — the serial loop stops at the
                # first fault, so the parallel walk does too.
                self._finish_locked()
                return
        _walk_tls.active = True
        try:
            queue_wait_ns = time.perf_counter_ns() - self._ready_ns[nid]
            out = self._execute(nid, queue_wait_ns)
            with self._lock:
                self._publish_locked(nid, out)
                self._finish_locked()
        except BaseException as e:  # lint: broad-ok re-raised on the caller by run()
            with self._lock:
                if self._error is None:
                    self._error = e
                self._stop = True
                self._finish_locked()
        finally:
            _walk_tls.active = False

    def _finish_locked(self) -> None:
        """Retire this task from the in-flight count and wake the caller
        (caller holds the lock). ONE place decrements, so the
        publish-succeeded and fault paths can never double-count."""
        self._inflight -= 1
        self._cv.notify_all()

    def _execute(self, nid: NodeId, queue_wait_ns: int):
        """The per-node body of the serial loop, minus the shared-state
        writes (those happen in ``_publish_locked``). Runs on a pool
        thread with every dependency value already published."""
        graph = self.graph
        op = graph.operators[nid]
        h = self.hashes[nid]
        if nid in self.is_memo:
            out = self.by_hash[h]
            if self.tracer is not None:
                self.tracer.instant(
                    "node:" + op.label(), "executor", cache="memo"
                )
            if self.profile is not None:
                self.profile.record_node(op.label(), cache="memo")
            return out
        deps = [self.values[d] for d in graph.dependencies[nid]]
        if self.tracer is None and self.profile is None:
            out = op.execute(deps)
        else:
            out = _observed_execute(
                op, deps, self.tracer, self.profile,
                worker=threading.current_thread().name,
                queue_wait_ns=queue_wait_ns,
                digest=self.node_digests.get(nid),
            )
        if isinstance(op, EstimatorOperator):
            # Cross-process store: content-addressed, atomic put — safe
            # off the lock (hash ownership makes the key unique per walk).
            dk = self.dks.get(nid)
            if dk is not None:
                self.ex.env.disk_cache.put(dk, out)
        return out

    def _publish_locked(self, nid: NodeId, out) -> None:
        """Store one node's value, run the session-cache writes the
        serial loop does at this point, and wake newly-ready dependents
        (caller holds the lock)."""
        graph = self.graph
        op = graph.operators[nid]
        h = self.hashes[nid]
        self.values[nid] = out
        env = self.ex.env
        if nid not in self.is_memo:
            self.by_hash[h] = out
            if isinstance(op, EstimatorOperator):
                self.ex._cache_fit(graph, nid, h, op, out)
            if getattr(op, "persist", False):
                env.node_cache[h] = (out, self.ex._prefix_pins(graph, nid))
        elif getattr(op, "persist", False) and h not in env.node_cache:
            # A cache node hashes identically to its dependency (it's an
            # identity), so it lands on the memo path — still persist.
            env.node_cache[h] = (out, self.ex._prefix_pins(graph, nid))
        self._remaining -= 1
        for dep in self.dependents.get(nid, ()):
            self.pend[dep] -= 1
            if self.pend[dep] == 0:
                self._submit_locked(dep)


class GraphExecutor:
    def __init__(self, env: "PipelineEnv"):
        self.env = env

    def execute_many(
        self, graph: Graph, targets: Sequence[GraphId]
    ) -> Dict[GraphId, Any]:
        """Evaluate all targets in one pass with shared memoization.

        The walk CUTS at persistent-cache hits: a node whose structural hash
        is already in the fit/node cache becomes a leaf and its upstream
        subgraph is never visited — cached values short-circuit
        recomputation, not just value storage.
        """
        from keystone_tpu.utils.metrics import active_profile, active_tracer

        # Resolved once per execution walk (the active_plan discipline):
        # the untraced/unprofiled walk pays one None check per node,
        # nothing more.
        tracer = active_tracer()
        profile = active_profile()
        for t in targets:
            if isinstance(t, SourceId):
                _no_sources(t)
        hmemo: Dict[GraphId, int] = {}
        dmemo: Dict[GraphId, Any] = {}

        def h_of(nid: GraphId) -> int:
            return structural_hash(graph, nid, _no_sources, hmemo)

        def d_of(nid: GraphId):
            if self.env.disk_cache is None:
                return None
            dk = structural_digest(graph, nid, dmemo)
            if dk is None:
                return None
            # Salt with the numeric regime: a fit computed under different
            # dtype/precision settings is a different artifact. Platform is
            # deliberately NOT included — CPU/TPU runs are treated as
            # numerically equivalent the way the reference treats local[n]
            # vs cluster (SURVEY.md §4 [unverified]).
            from keystone_tpu.config import config
            from keystone_tpu.workflow.fingerprint import digest_tree

            return digest_tree(
                (
                    "v1",
                    dk,
                    config.default_dtype,
                    config.accum_dtype,
                    config.solver_precision,
                    config.solver_storage_dtype,
                )
            )

        values: Dict[GraphId, Any] = {}
        by_hash: Dict[int, Any] = {}
        order: List[GraphId] = []
        seen = set()
        stack: List[tuple] = [(t, False) for t in targets]
        while stack:
            gid, processed = stack.pop()
            if processed:
                order.append(gid)
                continue
            if gid in seen or isinstance(gid, SourceId):
                continue
            seen.add(gid)
            op = graph.operators[gid]
            h = h_of(gid)
            hit = None
            if isinstance(op, EstimatorOperator) and h in self.env.fit_cache:
                hit = self.env.fit_cache[h][0]
            elif isinstance(op, EstimatorOperator):
                dk = d_of(gid)
                if dk is not None:
                    hit = self.env.disk_cache.get(dk)
                    if hit is not None:  # promote to the session cache too
                        self._cache_fit(graph, gid, h, op, hit)
            elif h in self.env.node_cache:
                hit = self.env.node_cache[h][0]
            if hit is not None:
                values[gid] = by_hash[h] = hit
                if tracer is not None:
                    tracer.instant(
                        "node:" + op.label(), "executor", cache="hit"
                    )
                if profile is not None:
                    profile.record_node(op.label(), cache="hit")
                continue  # leaf: do not descend into its dependencies
            stack.append((gid, True))
            for dep in graph.dependencies[gid]:
                if dep not in seen and isinstance(dep, NodeId):
                    stack.append((dep, False))

        # Stage-parallel walk (KEYSTONE_EXEC_WORKERS / config.exec_workers,
        # resolved once per walk like the tracer): > 0 dispatches the
        # execution loop below onto a bounded worker pool instead —
        # identical per-node work, identical cache writes, bit-identical
        # values; only provably independent nodes reorder. 0 (default)
        # falls through to the legacy serial loop, byte for byte. A walk
        # re-entered from a pool thread (an estimator fitting sub-pipelines)
        # always runs serial so concurrency stays bounded by ONE pool.
        # Digest every node the walk will execute under a FORCED profile
        # scope (fit(profile=True) / profile_scope() — the rows the
        # profile store persists): the measured row's content-stable
        # key, shared with the disk cache's memo so dataset fingerprints
        # hash once. Ambient KEYSTONE_PROFILE=1 observation never pays
        # the digest walk — only forced sessions can save store entries,
        # so hashing each per-batch dataset there would buy nothing.
        node_digests: Dict[GraphId, Any] = {}
        if profile is not None:
            from keystone_tpu.utils.metrics import profile_forced

            if profile_forced():
                for nid in order:
                    node_digests[nid] = structural_digest(graph, nid, dmemo)

        if len(order) > 1 and not getattr(_walk_tls, "active", False):
            from keystone_tpu.config import config

            # Explicit setting wins — including an explicitly exported
            # KEYSTONE_EXEC_WORKERS=0 (the byte-identical serial pin);
            # only the UNSET default falls back to the profile-guided
            # session plan (PlanResourcesRule), which only exists after
            # a measured-profile hit. The env is read live so a late
            # export is honored, not the config-instantiation snapshot.
            from keystone_tpu.config import resolved_exec_workers

            env_workers = resolved_exec_workers()
            if env_workers is not None:
                workers = env_workers
            else:
                workers = config.exec_workers
                if not workers:
                    workers = int(
                        self.env.resource_plan.get("exec_workers", 0) or 0
                    )
            if workers and workers > 0:
                _ParallelWalk(
                    self, graph, order, values, by_hash, hmemo, d_of,
                    tracer, profile, workers, node_digests=node_digests,
                ).run()
                return values

        for nid in order:
            h = h_of(nid)
            op = graph.operators[nid]
            if h in by_hash:
                values[nid] = by_hash[h]
                if tracer is not None:
                    tracer.instant(
                        "node:" + op.label(), "executor", cache="memo"
                    )
                if profile is not None:
                    profile.record_node(op.label(), cache="memo")
                # A cache node hashes identically to its dependency (it's an
                # identity), so it lands here — still persist its value.
                if getattr(op, "persist", False) and h not in self.env.node_cache:
                    self.env.node_cache[h] = (
                        values[nid],
                        self._prefix_pins(graph, nid),
                    )
                continue
            deps = [values[d] for d in graph.dependencies[nid]]
            if tracer is None and profile is None:
                out = op.execute(deps)
            else:
                out = _observed_execute(
                    op, deps, tracer, profile,
                    digest=node_digests.get(nid),
                )
            values[nid] = by_hash[h] = out
            if isinstance(op, EstimatorOperator):
                self._cache_fit(graph, nid, h, op, out)
                dk = d_of(nid)
                if dk is not None:
                    self.env.disk_cache.put(dk, out)
            if getattr(op, "persist", False):
                self.env.node_cache[h] = (out, self._prefix_pins(graph, nid))
        return values

    def _cache_fit(self, graph: Graph, nid: NodeId, h: int, op, out) -> None:
        """Cache a fitted transformer, scoped to the estimator's lifetime.

        The entry pins every prefix object except the estimator itself, which
        is held weakly with an eviction callback: when the user drops the
        estimator (and its pipelines), the entry — and the training data it
        pins — is freed, and the now-recyclable ids can never produce a stale
        hash hit because eviction precedes reuse.
        """
        import weakref

        estimator = op.estimator
        pins = tuple(
            p for p in self._prefix_pins(graph, nid) if p is not estimator
        )
        fit_cache = self.env.fit_cache
        try:
            keeper: Any = weakref.ref(
                estimator, lambda _ref, h=h: fit_cache.pop(h, None)
            )
        except TypeError:  # not weak-referenceable: pin strongly
            keeper = estimator
        fit_cache[h] = (out, pins, keeper)

    @staticmethod
    def _prefix_pins(graph: Graph, nid: NodeId) -> tuple:
        """Strong references to every object whose id() feeds the prefix hash
        of ``nid``. While a cache entry holds its pins, CPython cannot recycle
        those ids, so a hash hit always means the same live objects."""
        pins = []
        for n in graph.reachable([nid]):
            pins.extend(graph.operators[n].pinned_objects())
        return tuple(pins)

    def execute(self, graph: Graph, target: GraphId) -> Any:
        return self.execute_many(graph, [target])[target]

    def fit_estimators(self, graph: Graph, sink: GraphId) -> Graph:
        """Force every estimator reachable from ``sink`` and rewrite the graph
        so each DelegatingOperator becomes a concrete TransformerOperator.

        This is the `Pipeline.fit` lowering: the result graph is
        transformer-only on the inference path.
        """
        # The resource plan the optimizer pass writes is scoped to THIS
        # fit's walk: a nested optimization (an estimator fitting a
        # sub-pipeline, an interleaved apply) saves the outer plan at
        # its own entry and restores it here on exit, so the outer
        # solve keeps reading the plan computed FOR it.
        prior_plan = dict(self.env.resource_plan)
        graph = self.env.optimizer.execute(graph, [sink])
        order = graph.reachable([sink])
        est_nodes = [
            n for n in order if isinstance(graph.operators[n], EstimatorOperator)
        ]
        try:
            if est_nodes:
                fitted = self.execute_many(graph, est_nodes)
            else:
                fitted = {}
        finally:
            self.env.resource_plan.clear()
            self.env.resource_plan.update(prior_plan)
        ops = dict(graph.operators)
        dps = dict(graph.dependencies)
        for nid in order:
            op = graph.operators[nid]
            if isinstance(op, DelegatingOperator):
                est_dep, input_dep = graph.dependencies[nid]
                # See through identity cache nodes between estimator and
                # delegating consumer.
                while (
                    est_dep in graph.operators
                    and getattr(graph.operators[est_dep], "persist", False)
                ):
                    est_dep = graph.dependencies[est_dep][0]
                if est_dep in fitted:
                    ops[nid] = TransformerOperator(fitted[est_dep])
                    dps[nid] = (input_dep,)
        # Prune: drops the now-unreferenced estimator nodes and their training
        # DatasetOperator subtrees so a fitted pipeline doesn't pin the
        # training set in memory.
        return Graph(ops, dps).pruned([sink])

    def serving_chain(self, graph: Graph, source: SourceId, sink: GraphId):
        """Lower a FITTED pipeline graph to the one transformer the serving
        layer AOT-compiles: optimize (fusing jittable chains), then require
        the source→sink path to be a linear chain of jittable
        TransformerOperators. Identity cache nodes are seen through;
        anything else (gather joins, unfitted estimators, host nodes) is
        refused with an error naming the offender — the serving engine
        compiles ONE program per bucket and cannot host-hop mid-chain.
        """
        from keystone_tpu.workflow.pipeline import FusedTransformer

        g = self.env.optimizer.execute(graph, [sink])
        chain: List[Any] = []
        gid = sink
        while gid != source:
            if isinstance(gid, SourceId):
                raise ValueError(
                    f"serve path ends at foreign source {gid!r}, not the "
                    "pipeline's own input"
                )
            op = g.operators[gid]
            deps = g.dependencies[gid]
            if getattr(op, "persist", False):  # identity Cache node
                gid = deps[0]
                continue
            if not isinstance(op, TransformerOperator):
                raise TypeError(
                    f"cannot compile {op.label()} for serving: the serve "
                    "path must be a fitted, linear transformer chain (fit "
                    "the pipeline first; gather/estimator/host nodes cannot "
                    "join the single-program bucketed executable)"
                )
            if not op.transformer.jittable:
                raise TypeError(
                    f"{type(op.transformer).__name__} is not jittable; the "
                    "AOT serving path compiles the whole chain as one XLA "
                    "program"
                )
            if len(deps) != 1:
                raise TypeError(
                    f"serve path node {op.label()} has {len(deps)} inputs; "
                    "bucketed serving requires a linear chain"
                )
            chain.append(op.transformer)
            gid = deps[0]
        if not chain:
            raise ValueError("pipeline has no transformers on the serve path")
        chain.reverse()
        return chain[0] if len(chain) == 1 else FusedTransformer(chain)


class PipelineEnv:
    """Session state: optimizer, executor, and persistent caches.

    Ref: workflow/PipelineEnv.scala [unverified].
    """

    _instance: Optional["PipelineEnv"] = None

    def __init__(self):
        from keystone_tpu.config import resolved_cache_dir
        from keystone_tpu.workflow.optimizer import default_optimizer

        self.optimizer = default_optimizer()
        self.executor = GraphExecutor(self)
        # structural hash of estimator node -> fitted Transformer
        self.fit_cache: Dict[int, Any] = {}
        # structural hash -> persisted value (auto-cache rule / Cacher nodes)
        self.node_cache: Dict[int, Any] = {}
        # Session-scoped profile-guided plan (workflow/rules.py
        # PlanResourcesRule): e.g. {"exec_workers": 4,
        # "solve_chunk_rows": 8192}. Consulted only where the explicit
        # config knob is unset, so a user setting always wins.
        self.resource_plan: Dict[str, Any] = {}
        # Cross-process fitted-prefix store, keyed by content digest; the
        # env-presence-over-config precedence lives in config.py so the
        # os.environ read stays out of this module (keystone-lint KL003).
        cache_dir = resolved_cache_dir()
        self.disk_cache = None
        if cache_dir:
            from keystone_tpu.workflow.disk_cache import DiskFitCache

            try:
                self.disk_cache: Optional["DiskFitCache"] = DiskFitCache(
                    cache_dir
                )
            except OSError as e:  # uncreatable dir: degrade, never abort
                import logging

                logging.getLogger("keystone_tpu").warning(
                    "disk fit cache disabled: cannot create %s (%s)",
                    cache_dir,
                    e,
                )

    @classmethod
    def get(cls) -> "PipelineEnv":
        if cls._instance is None:
            cls._instance = PipelineEnv()
        return cls._instance

    @classmethod
    def reset(cls) -> None:
        cls._instance = None

    def clear_caches(self) -> None:
        """Drop all memoized fits, persisted values, and optimizer-held state
        (frees pinned data)."""
        self.fit_cache.clear()
        self.node_cache.clear()
        self.resource_plan.clear()
        for _name, rules, _iters in getattr(self.optimizer, "batches", []):
            for rule in rules:
                clear = getattr(rule, "clear_cache", None)
                if clear is not None:
                    clear()

    def optimize_and_execute(self, graph: Graph, sink: GraphId) -> Any:
        save = self._profile_save_ctx(graph, sink)
        # Scope this pass's resource plan to this execution (see
        # fit_estimators): the pass clears-then-writes the plan, the
        # walk consumes it, and the OUTER pass's plan is restored on
        # exit so a nested optimization never retires a plan some
        # enclosing solve is still reading.
        prior_plan = dict(self.resource_plan)
        g = self.optimizer.execute(graph, [sink])
        try:
            out = self.executor.execute(g, sink)
        finally:
            self.resource_plan.clear()
            self.resource_plan.update(prior_plan)
        if save is not None:
            save()
        return out

    @staticmethod
    def _profile_save_ctx(graph: Graph, sink: GraphId):
        """When this execution is under a FORCED profile scope (an
        explicit ``profile_scope()`` / ``fit(profile=True)`` session —
        ambient KEYSTONE_PROFILE=1 deliberately does not write store
        entries per apply) and a profile store is configured, return a
        closure that persists the walk's measured delta under THIS
        graph's digest — so a profiled apply makes later applies of the
        same pipeline-over-data a measured-store hit too, completing the
        profile-once-optimize-forever workflow on the apply side."""
        from keystone_tpu.config import resolved_profile_store
        from keystone_tpu.utils.metrics import profile_forced

        if not profile_forced() or not resolved_profile_store():
            return None
        from keystone_tpu.utils.metrics import (
            resource_profile,
            runtime_fingerprint,
        )
        from keystone_tpu.workflow.profile_store import (
            ProfileStoreError,
            pipeline_profile_digest,
            save_profile,
        )

        digest = pipeline_profile_digest(graph, sink)
        if digest is None:
            return None
        mark = resource_profile.mark()
        dmark = resource_profile.mark_digests()

        def save():
            digests = resource_profile.digest_rows(since=dmark)
            if not digests:
                return  # nothing executed (full cache hit): keep the old entry
            try:
                save_profile(
                    digest, digests, resource_profile.rows(since=mark),
                    fingerprint=runtime_fingerprint(),
                )
            except ProfileStoreError as e:
                import logging

                logging.getLogger("keystone_tpu").warning(
                    "apply profile not saved: %s", e
                )

        return save

    def execute(self, graph: Graph, sink: GraphId) -> Any:
        return self.executor.execute(graph, sink)
