"""Content-stable signatures and digests — cross-process cache keys.

The in-process prefix memoization (workflow/graph.py structural_hash) keys on
Python ``hash()`` of signature trees, which is per-process (string hashing is
salted, and id()-based fallbacks are only meaningful while the object lives).
To persist fitted prefixes ACROSS processes — the reference's prefix-state
reuse surviving reruns (SURVEY.md §2.1 auto-caching + §5 checkpoint rows
[unverified]) — we need keys derived purely from content.

Two pieces:

- ``stable_value`` canonicalizes an arbitrary hyperparameter tree into
  primitives. Values it cannot stabilize become ``("unstable", id(v),
  UNSTABLE)`` — still unique in-process (so the session cache keeps working)
  but *poisoned* for persistence.
- ``digest_tree`` folds a canonical tree into a hex blake2b digest, returning
  ``None`` when the tree is poisoned. Operators fold dependency digests
  through ``prefix_digest`` exactly the way ``prefix_hash`` folds hashes, so
  fused and unfused chains produce identical digests.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Optional

import numpy as np


class _Unstable:
    """Singleton marking a signature subtree that has no content identity."""

    __slots__ = ()

    def __repr__(self) -> str:
        return "<UNSTABLE>"


UNSTABLE = _Unstable()


def _is_jax_array(v: Any) -> bool:
    # Lazy import keeps fingerprinting usable before any backend exists.
    jax = __import__("jax")
    return isinstance(v, jax.Array)


def array_fingerprint(a: np.ndarray) -> tuple:
    """Content identity of a numeric array: shape, dtype, blake2b of bytes.

    Above ``config.fingerprint_max_bytes`` the digest covers a deterministic
    sample (64 evenly-spaced 1 MiB chunks plus head and tail) instead of the
    full buffer — bounded cost for multi-GB fit inputs, at the engineering
    risk (same as the solver checkpoint fingerprints' row probes) that a
    change confined entirely to unsampled bytes goes unseen. Real data never
    changes that way; adversarial inputs shouldn't share a cache dir.
    """
    from keystone_tpu.config import config

    h = hashlib.blake2b(digest_size=16)
    h.update(repr(a.shape).encode())
    h.update(str(a.dtype).encode())
    limit = config.fingerprint_max_bytes
    if a.nbytes <= limit:
        c = np.ascontiguousarray(a)  # bounded by limit even when it copies
        h.update(memoryview(c).cast("B"))
        return ("ndarray", a.shape, str(a.dtype), h.hexdigest())
    # Over-limit: TWO independent deterministic samples, so a change must
    # dodge both lattices to collide. Pass 1 walks ~64 row-block chunks of
    # ~1 MiB via axis-0 slices (views; each chunk is made contiguous and
    # hashed through a hard per-chunk cap, so a handful of huge rows —
    # n0 < 64 with multi-MiB rows — can no longer turn the "bounded" path
    # into a full-buffer hash). Per-chunk byte counts fold into the digest.
    h.update(str(a.nbytes).encode())
    n0 = a.shape[0]
    row_bytes = max(a.nbytes // max(n0, 1), 1)
    rows_per = max(1, (1 << 20) // row_bytes)
    stride = max(n0 // 64, rows_per)
    cap = 1 << 20  # hashed bytes per chunk, regardless of row size
    budget = 96 << 20  # whole-call ceiling, small-n0 case included
    spent = 0
    starts = list(range(0, n0, stride))
    tail_start = max(n0 - rows_per, 0)
    if tail_start not in starts:
        starts.append(tail_start)
    for s in starts:
        if spent >= budget:
            break
        chunk = np.ascontiguousarray(a[s : s + rows_per])
        mv = memoryview(chunk).cast("B")[:cap]
        h.update(str(chunk.nbytes).encode())
        h.update(mv)
        spent += len(mv)
    # Pass 2: a strided ELEMENT probe across the whole array in logical
    # C-order (``a.flat`` fancy-indexing — a ~65k-element gather that works
    # for ANY memory layout and hashes every byte of each probed element),
    # at a step derived from a prime probe count so it stays incommensurate
    # with pass 1's row-block lattice. Logical order also keeps the digest
    # layout-independent: the same matrix C- or F-contiguous hashes equal.
    step = max(a.size // 65521, 1)
    idx = np.arange(0, a.size, step)
    probe = np.ascontiguousarray(a.flat[idx])
    h.update(b"p2" + str(step).encode())
    h.update(memoryview(probe).cast("B"))
    return ("ndarray-sampled", a.shape, str(a.dtype), h.hexdigest())


def text_fingerprint(seq) -> Optional[tuple]:
    """Content identity of a text corpus (list/tuple of str) — the dataset
    payload of every NLP pipeline. Full hash up to the size budget, then a
    strided item sample (same engineering tradeoff as array_fingerprint).
    None if any element isn't a str."""
    from keystone_tpu.config import config

    h = hashlib.blake2b(digest_size=16)
    n = len(seq)
    total = 0  # chars — a ≤4× under-count of UTF-8 bytes, used ONLY to
    for s in seq:  # pick full-vs-sampled mode, never as the work bound
        if not isinstance(s, str):
            return None
        total += len(s)
    h.update(str(n).encode())
    h.update(str(total).encode())  # total size is part of the identity
    limit = config.fingerprint_max_bytes
    if total <= limit:
        for s in seq:
            b = s.encode()
            h.update(str(len(b)).encode())
            h.update(b)
        return ("text", n, h.hexdigest())
    # Sampled mode: every sampled item contributes its exact byte length,
    # but hashed CONTENT is hard-capped (≤1 MiB per item, ≤64 MiB overall)
    # so corpus size never unbounds the first structural hash.
    step = max(1, n // 1024)
    budget = 64 << 20
    spent = 0
    for i in range(0, n, step):
        b = seq[i].encode()
        h.update(str(len(b)).encode())
        if spent < budget:
            take = min(len(b), budget - spent, 1 << 20)
            h.update(b[:take])
            spent += take
    return ("text-sampled", n, h.hexdigest())


def stable_value(v: Any) -> Any:
    """Canonicalize ``v`` into a tree of primitives; unknown objects keep
    their id (in-process uniqueness) but carry the UNSTABLE poison."""
    if v is None or isinstance(v, (bool, int, float, str, bytes, _Unstable)):
        return v
    if isinstance(v, type):
        return ("class", v.__module__, v.__qualname__)
    if isinstance(v, (tuple, list)):
        return ("seq", tuple(stable_value(x) for x in v))
    if isinstance(v, dict):
        if not all(isinstance(k, str) for k in v):
            return ("unstable", id(v), UNSTABLE)
        return (
            "dict",
            tuple((k, stable_value(v[k])) for k in sorted(v)),
        )
    if _is_jax_array(v):
        v = np.asarray(v)  # one host fetch, then content-addressed like numpy
    if isinstance(v, np.ndarray):
        if v.dtype.kind in "biufc":
            return array_fingerprint(v)
        return ("unstable", id(v), UNSTABLE)
    if isinstance(v, (np.integer, np.floating, np.bool_)):
        return ("npscalar", str(v.dtype), v.item())
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return (
            "dataclass",
            stable_value(type(v)),
            tuple(
                (f.name, stable_value(getattr(v, f.name)))
                for f in dataclasses.fields(v)
            ),
        )
    return ("unstable", id(v), UNSTABLE)


def is_stable(tree: Any) -> bool:
    if isinstance(tree, _Unstable):
        return False
    if isinstance(tree, tuple):
        return all(is_stable(x) for x in tree)
    return True


def _encode(v: Any, h) -> bool:
    """Fold ``v`` into hasher ``h`` with type tags; False when poisoned."""
    if isinstance(v, _Unstable):
        return False
    if v is None:
        h.update(b"N")
    elif isinstance(v, bool):
        h.update(b"b1" if v else b"b0")
    elif isinstance(v, int):
        h.update(b"i" + str(v).encode())
    elif isinstance(v, float):
        h.update(b"f" + repr(v).encode())
    elif isinstance(v, str):
        b = v.encode()
        h.update(b"s" + str(len(b)).encode() + b":" + b)
    elif isinstance(v, bytes):
        h.update(b"y" + str(len(v)).encode() + b":" + v)
    elif isinstance(v, tuple):
        h.update(b"T" + str(len(v)).encode() + b":")
        for x in v:
            if not _encode(x, h):
                return False
    elif isinstance(v, type):
        return _encode(stable_value(v), h)
    elif isinstance(v, np.ndarray):
        return _encode(array_fingerprint(v), h)
    elif isinstance(v, (np.integer, np.floating, np.bool_)):
        return _encode(stable_value(v), h)
    else:
        # Raw signature trees may carry objects stable_value knows about.
        sv = stable_value(v)
        if isinstance(sv, tuple) and sv and sv[0] == "unstable":
            return False
        return _encode(sv, h)
    return True


def digest_tree(tree: Any) -> Optional[str]:
    """Hex digest of a canonical tree, or None if any part is unstable."""
    h = hashlib.blake2b(digest_size=20)
    if not _encode(tree, h):
        return None
    return h.hexdigest()
