"""Learned serving-capacity model: the closing of ROADMAP cycle item 2.

The fit path learned to price itself (profile store → planner); this
module does the same for SERVING. A :class:`CapacityModel` is fitted
online from the journey records the daemon already emits — per-(tier,
bucket) latency quantiles from the accepted→…→resolved stamps, batch
device-time quantiles from the service's dispatch→deliver leg, a
per-tenant arrival-rate EWMA, and a decayed arrival histogram over the
bucket ladder (the observed traffic *mix*) — and consulted by three
hot-path consumers:

- **Predicted-deadline admission** (daemon.py): refuse a request whose
  predicted completion (current queue depth x modeled per-bucket batch
  latency) already breaches its deadline, as a counted fast-fail 429
  (``predicted_infeasible``) before any device work.
- **Traffic-aware autoscaling** (daemon.py ``_replan_loop``): re-size
  the replica pool and re-price the bucket ladder when the observed mix
  shifts past a threshold, decision-logged through the optimizer ring.
- **Deadline-aware cross-tenant micro-batching** (serving.py
  ``_loop``): coalesce compatible best-effort requests into the padding
  slack of gold-tier groups when the model predicts the combined batch
  still makes the gold deadline.

Cold contract: until ``min_samples`` journeys are observed the model
reports not-ready and EVERY consumer no-ops (counted as
``capacity.model_cold_skips``) — cold behavior is bit-identical to
``KEYSTONE_CAPACITY_MODEL=0`` (test-pinned).

Strict-accuracy guard: every refusal is recorded with its prediction
inputs and re-validated post-hoc against the model as it learns — a
refusal the matured model would call feasible is counted as a
``guard_violation`` (a model that refuses feasible work is a bug gate,
not a tuning knob).

Persistence rides the PR-19 telemetry JSONL segments: ``save()`` emits
one ``{"kind": "capacity"}`` snapshot record; ``load_capacity_model``
scans the telemetry directory for the newest snapshot for this daemon
and falls back to replaying the raw journey records, so a restarted
daemon starts warm instead of re-learning from zero.
"""

from __future__ import annotations

import glob
import json
import logging
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from keystone_tpu.utils.metrics import capacity_counters

logger = logging.getLogger("keystone_tpu")

#: Schema stamp on capacity snapshot records (forward-compat gate).
SNAPSHOT_SCHEMA = 1

#: Bounded per-key latency sample rings (quantiles over the newest N).
SAMPLE_CAP = 512

#: Arrival-rate EWMA smoothing (per observed inter-arrival gap).
EWMA_ALPHA = 0.2

#: Decay applied to the bucket arrival histogram per observation: the
#: mix tracks the recent window, not all of history.
MIX_DECAY = 0.995

#: Bounded ring of refusals awaiting post-hoc guard validation.
GUARD_CAP = 256
#: Quantile the admission prediction (and the guard's re-validation —
#: SAME constant, so pessimism beyond it still counts as a violation)
#: prices each flush at: a request admitted at the p50 boundary is late
#: half the time, so the estimate carries queue jitter.
ADMIT_Q = 0.75

#: Journey-replay bound at restore: a long-lived telemetry dir must not
#: turn daemon construction into an unbounded scan.
REPLAY_MAX_RECORDS = 20000


class _Ring:
    """Bounded sample ring with cached nearest-rank quantiles (the
    bench's ``lat_stats`` convention: q in [0, 1], newest SAMPLE_CAP
    samples)."""

    __slots__ = ("cap", "samples", "_i", "_sorted")

    def __init__(self, cap: int = SAMPLE_CAP):
        self.cap = int(cap)
        self.samples: List[float] = []
        self._i = 0
        self._sorted: Optional[List[float]] = None

    def add(self, v: float) -> None:
        v = float(v)
        if len(self.samples) < self.cap:
            self.samples.append(v)
        else:
            self.samples[self._i] = v
            self._i = (self._i + 1) % self.cap
        self._sorted = None

    def __len__(self) -> int:
        return len(self.samples)

    def quantile(self, q: float) -> Optional[float]:
        if not self.samples:
            return None
        if self._sorted is None:
            self._sorted = sorted(self.samples)
        s = self._sorted
        k = min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))
        return s[k]

    def state(self) -> List[float]:
        return list(self.samples)

    def restore(self, samples) -> None:
        self.samples = [float(v) for v in samples][-self.cap:]
        self._i = 0
        self._sorted = None


class CapacityModel:
    """Online per-(tier, bucket) latency/occupancy model (module
    docstring has the architecture and the cold/guard contracts).

    Thread-safe: observations arrive from ingress threads, the service's
    completion threads, and the re-plan loop concurrently; every public
    method takes the one internal lock and never calls out under it.
    """

    def __init__(self, name: str = "daemon",
                 min_samples: Optional[int] = None):
        from keystone_tpu.config import config

        self.name = str(name)
        self.min_samples = int(
            config.capacity_min_samples if min_samples is None
            else min_samples
        )
        self._lock = threading.Lock()
        # Per-(tier, bucket) end-to-end service ms (daemon journey leg:
        # submitted -> resolved; queue wait + device time as the tier
        # actually experienced it).
        self._lat: Dict[Tuple[str, int], _Ring] = {}
        # Per-bucket device-batch ms (service leg: launch -> delivered),
        # the admission/micro-batch prediction primitive.
        self._batch: Dict[int, _Ring] = {}
        # Per-tenant offered-rate EWMA (requests/s), from inter-arrival
        # gaps at admission time — refusals included: this is offered
        # load, not served load.
        self._rate: Dict[str, float] = {}
        self._last_arrival: Dict[str, float] = {}
        # Decayed arrival histogram over buckets: the traffic mix.
        self._mix: Dict[int, float] = {}
        # Observed rows-per-flush EWMA: the queue's real drain rate.
        # Flushes go out partially filled whenever the delay window
        # closes first, so pricing the wait as depth / max_rows (perfect
        # packing) systematically underestimates it under exactly the
        # load where admission control matters. None until the first
        # flush is observed (fall back to max_rows — the optimistic
        # cold default, consistent with the guard's admit bias).
        self._fill: Optional[float] = None
        # Signed prediction-bias EWMA (ms): observed minus predicted
        # over completed journeys that carried an admission prediction.
        # The flush-cost model prices device time only; ingress parse,
        # the flush delay window, and response writes are real wall
        # clock a tight deadline must also survive. Feeding realized
        # error back keeps the estimator mean-zero AT THE ADMITTED
        # MARGIN, whichever way it drifts (the guard applies the same
        # term, so the correction cannot smuggle in pessimism).
        self._bias: Optional[float] = None
        self._samples = 0
        self._started = time.monotonic()
        self._last_observe: Optional[float] = None
        # Strict-accuracy guard state: refusals awaiting post-hoc
        # validation, plus the violation count (the bug gate).
        self._refusals: List[Dict[str, Any]] = []
        #: Sample-count watermark of the EARLIEST pending re-validation:
        #: the per-observation hot path compares one int instead of
        #: scanning the whole refusal ring (None = nothing pending).
        self._guard_at: Optional[int] = None
        self.refusals = 0
        self.guard_checked = 0
        self.guard_violations = 0
        # Predicted-vs-observed p99 per (tier, bucket): the /stats
        # accuracy surface (prediction recorded at admit, observation at
        # finish).
        self._pred_p99: Dict[Tuple[str, int], _Ring] = {}

    # -- observation channels ---------------------------------------------

    def observe_arrival(self, tenant: str, now: Optional[float] = None
                        ) -> None:
        """One offered request from ``tenant`` (called at admission,
        before any accept/refuse decision)."""
        now = time.monotonic() if now is None else float(now)
        key = str(tenant)
        with self._lock:
            last = self._last_arrival.get(key)
            self._last_arrival[key] = now
            if last is None or now <= last:
                return
            rate = 1.0 / (now - last)
            prev = self._rate.get(key)
            self._rate[key] = (
                rate if prev is None
                else (1.0 - EWMA_ALPHA) * prev + EWMA_ALPHA * rate
            )

    def observe_journey(self, tier: str, tenant: str, rows: int,
                        bucket: Optional[int], service_ms: Optional[float],
                        outcome: str = "ok",
                        predicted_ms: Optional[float] = None) -> None:
        """One finished daemon journey: per-(tier, bucket) latency
        sample, mix histogram update, sample count, and a post-hoc pass
        over pending refusal validations."""
        b = int(bucket) if bucket else 0
        with self._lock:
            self._samples += 1
            self._last_observe = time.monotonic()
            decayed = {}
            for k, v in self._mix.items():
                v *= MIX_DECAY
                if v > 1e-3:
                    decayed[k] = v
            decayed[b] = decayed.get(b, 0.0) + 1.0
            self._mix = decayed
            if service_ms is not None and service_ms >= 0 and outcome == "ok":
                ring = self._lat.get((tier, b))
                if ring is None:
                    ring = self._lat[(tier, b)] = _Ring()
                ring.add(service_ms)
                if predicted_ms is not None:
                    pring = self._pred_p99.get((tier, b))
                    if pring is None:
                        pring = self._pred_p99[(tier, b)] = _Ring()
                    pring.add(predicted_ms)
                    err = float(service_ms) - float(predicted_ms)
                    self._bias = (
                        err if self._bias is None
                        else (1.0 - EWMA_ALPHA) * self._bias
                        + EWMA_ALPHA * err
                    )
            if self._guard_at is not None and self._samples >= self._guard_at:
                self._validate_refusals_locked()

    def observe_batch(self, bucket: Optional[int], rows: int,
                      device_ms: float) -> None:
        """One completed device batch from the service (launch ->
        delivered), keyed by the bucket rung it padded to."""
        if bucket is None or device_ms < 0:
            return
        with self._lock:
            ring = self._batch.get(int(bucket))
            if ring is None:
                ring = self._batch[int(bucket)] = _Ring()
            ring.add(float(device_ms))
            if rows > 0:
                self._fill = (
                    float(rows) if self._fill is None
                    else (1.0 - EWMA_ALPHA) * self._fill
                    + EWMA_ALPHA * float(rows)
                )

    # -- readiness ---------------------------------------------------------

    def ready(self) -> bool:
        """True once enough journeys were observed for predictions to be
        trustworthy; until then every consumer must no-op (the cold
        contract — bit-identical to model-off, counted)."""
        with self._lock:
            return self._samples >= self.min_samples

    def samples(self) -> int:
        with self._lock:
            return self._samples

    # -- prediction --------------------------------------------------------

    def _batch_ms_locked(self, bucket: int, q: float) -> Optional[float]:
        ring = self._batch.get(bucket)
        if ring is not None and len(ring):
            return ring.quantile(q)
        # Nearest observed rung, scaled by the row ratio (row-linear
        # device cost — the ladder's pricing assumption).
        best = None
        for b, r in self._batch.items():
            if not len(r):
                continue
            d = abs(math.log((b or 1) / max(bucket, 1)))
            if best is None or d < best[0]:
                best = (d, b, r)
        if best is not None:
            _, b, r = best
            v = r.quantile(q)
            if v is not None:
                return v * max(bucket, 1) / max(b, 1)
        return None

    def _drain_batches_locked(self, queue_depth: int, max_rows: int) -> int:
        """Flushes needed to drain ``queue_depth`` rows plus one for the
        request itself, at the OBSERVED rows-per-flush rate (partial
        flushes drain the queue slower than perfect ``max_rows`` packing
        would; cold fill falls back to ``max_rows`` — optimistic, so a
        cold-ish model under-refuses rather than over-refuses)."""
        mr = max(1, int(max_rows))
        fill = mr if self._fill is None else min(float(mr),
                                                 max(1.0, self._fill))
        return 1 + int(max(0, int(queue_depth)) / fill)

    def _lat_ms_locked(self, tier: str, bucket: int,
                       q: float) -> Optional[float]:
        ring = self._lat.get((tier, bucket))
        if ring is not None and len(ring):
            return ring.quantile(q)
        # Any bucket of this tier, then any tier at all.
        for (t, _b), r in self._lat.items():
            if t == tier and len(r):
                return r.quantile(q)
        for r in self._lat.values():
            if len(r):
                return r.quantile(q)
        return None

    def predict_completion_ms(self, tier: str, rows: int, queue_depth: int,
                              max_rows: int, bucket: Optional[int] = None
                              ) -> Optional[Dict[str, Any]]:
        """Predicted completion for a request arriving NOW: the queued
        rows ahead of it drain at the OBSERVED rows-per-flush rate (see
        ``_drain_batches_locked`` — partial flushes drain slower than
        perfect ``max_rows`` packing), each flush costing the modeled
        per-bucket batch latency at ``ADMIT_Q`` (p75 — see the
        constant: the p50 boundary is a coin flip, and the guard
        re-validates refusals at the same quantile). None when the
        model is cold or has no usable latency data yet.

        The batch cost is keyed by the request's EFFECTIVE flush bucket:
        a request joining a non-empty queue coalesces with the rows
        ahead of it, so its own flush fills toward ``max_rows`` and its
        device cost is the full bucket's — pricing a 1-row request in a
        deep queue at the solo 1-row rung would systematically
        underestimate exactly when admission control matters most."""
        rows = max(1, int(rows))
        mr = max(1, int(max_rows))
        eff = min(mr, rows + max(0, int(queue_depth)))
        b = max(int(bucket) if bucket else 0, eff)
        with self._lock:
            if self._samples < self.min_samples:
                return None
            batch_ms = self._batch_ms_locked(b, ADMIT_Q)
            if batch_ms is None:
                lat = self._lat_ms_locked(tier, b, ADMIT_Q)
                if lat is None:
                    return None
                batch_ms = lat
            batches_ahead = self._drain_batches_locked(queue_depth, mr)
            bias = self._bias or 0.0
            predicted = batches_ahead * batch_ms + bias
            return {
                "predicted_ms": float(predicted),
                "batch_ms": float(batch_ms),
                "batches_ahead": int(batches_ahead),
                "bias_ms": float(bias),
                "bucket": b,
                "queue_depth": int(queue_depth),
            }

    def predict_batch_ms(self, bucket: int, q: float = 0.99
                         ) -> Optional[float]:
        """Modeled device-batch latency at a rung (micro-batching's
        feasibility primitive; p99 by default — a gold deadline must
        survive the combined batch's tail, not its median)."""
        with self._lock:
            if self._samples < self.min_samples:
                return None
            return self._batch_ms_locked(int(bucket), q)

    # -- strict-accuracy guard --------------------------------------------

    def note_refusal(self, tier: str, rows: int, queue_depth: int,
                     max_rows: int, deadline_ms: float, predicted_ms: float,
                     trace_id: Optional[str] = None,
                     bucket: Optional[int] = None) -> None:
        """Record one predicted-infeasible refusal for post-hoc
        validation (bounded ring; validated as observations arrive).
        ``bucket`` is the effective flush bucket the prediction priced
        (so the guard re-validates the same estimate, not a different
        one)."""
        with self._lock:
            self.refusals += 1
            check_at = max(self._samples + self.min_samples,
                           self._samples * 2)
            self._refusals.append({
                "tier": str(tier),
                "rows": int(rows),
                "queue_depth": int(queue_depth),
                "max_rows": int(max_rows),
                "deadline_ms": float(deadline_ms),
                "predicted_ms": float(predicted_ms),
                # Bias AS OF the refusal: the guard re-validates with
                # maturer QUANTILES but this frozen bias — the live bias
                # tracks the operating regime, and a refusal that
                # shallowed the queue must not be judged against the
                # healthy regime it created (the admission paradox).
                "bias_ms": float(self._bias or 0.0),
                "bucket": int(bucket) if bucket else None,
                "trace_id": trace_id,
                "samples_at": self._samples,
                "check_at": check_at,
            })
            if len(self._refusals) > GUARD_CAP:
                del self._refusals[: len(self._refusals) - GUARD_CAP]
            if self._guard_at is None or check_at < self._guard_at:
                self._guard_at = check_at

    def _validate_refusals_locked(self) -> None:
        """Re-run each pending refusal's prediction against the model as
        it stands NOW: once fresh observations have doubled the evidence
        since the refusal, a prediction that flipped to feasible counts
        as a guard violation — the refusal denied work the model itself
        now calls servable."""
        if not self._refusals:
            self._guard_at = None
            return
        keep = []
        for ref in self._refusals:
            if self._samples < ref.get("check_at", max(
                    ref["samples_at"] + self.min_samples,
                    ref["samples_at"] * 2)):
                keep.append(ref)
                continue
            self.guard_checked += 1
            b = ref.get("bucket") or min(
                max(1, ref["max_rows"]),
                max(1, ref["rows"]) + max(0, ref["queue_depth"]),
            )
            batch_ms = self._batch_ms_locked(b, ADMIT_Q)
            if batch_ms is None:
                batch_ms = self._lat_ms_locked(ref["tier"], b, ADMIT_Q)
            if batch_ms is None:
                continue
            batches = self._drain_batches_locked(
                ref["queue_depth"], ref["max_rows"])
            predicted_now = (batches * batch_ms
                             + float(ref.get("bias_ms") or 0.0))
            if predicted_now <= ref["deadline_ms"]:
                self.guard_violations += 1
                capacity_counters.bump("guard_violations")
                logger.warning(
                    "capacity model %s: STRICT-ACCURACY GUARD — refusal "
                    "(trace %s, tier %s, depth %d, predicted %.1fms > "
                    "deadline %.1fms) would be FEASIBLE under the matured "
                    "model (%.1fms); the model refused servable work",
                    self.name, ref["trace_id"], ref["tier"],
                    ref["queue_depth"], ref["predicted_ms"],
                    ref["deadline_ms"], predicted_now,
                )
        self._refusals = keep
        self._guard_at = (
            min(ref.get("check_at", 0) for ref in keep) if keep else None
        )

    # -- traffic mix / rates ----------------------------------------------

    def traffic_mix(self) -> Dict[int, float]:
        """Observed arrival mix over buckets, normalized to fractions
        (decayed — the recent window, not all history)."""
        with self._lock:
            total = sum(self._mix.values())
            if total <= 0:
                return {}
            return {b: v / total for b, v in sorted(self._mix.items())}

    def arrival_rate(self, tenant: Optional[str] = None) -> float:
        """EWMA offered rate (requests/s): one tenant's, or the sum."""
        with self._lock:
            if tenant is not None:
                return float(self._rate.get(str(tenant), 0.0))
            return float(sum(self._rate.values()))

    @staticmethod
    def mix_shift(a: Dict[int, float], b: Dict[int, float]) -> float:
        """Total-variation distance between two bucket mixes in [0, 1]
        (the re-plan trigger metric)."""
        keys = set(a) | set(b)
        return 0.5 * sum(abs(a.get(k, 0.0) - b.get(k, 0.0)) for k in keys)

    # -- observability -----------------------------------------------------

    def stats(self, redact_tenants: bool = False) -> Dict[str, Any]:
        """The /stats ``capacity`` payload: freshness, per-bucket
        predicted-vs-observed p99, guard accounting. Tenant names follow
        the SLO redaction contract — anonymous callers see rates
        collapsed under ``"*"``."""
        with self._lock:
            per_bucket: Dict[str, Any] = {}
            for (tier, b), ring in sorted(self._lat.items()):
                key = f"{tier}:{b}"
                pred = self._pred_p99.get((tier, b))
                per_bucket[key] = {
                    "observed_p99_ms": ring.quantile(0.99),
                    "observed_p50_ms": ring.quantile(0.5),
                    "predicted_p99_ms": (
                        pred.quantile(0.99) if pred is not None and len(pred)
                        else None
                    ),
                    "samples": len(ring),
                }
            batch = {
                str(b): {"p50_ms": r.quantile(0.5), "p99_ms": r.quantile(0.99),
                         "samples": len(r)}
                for b, r in sorted(self._batch.items())
            }
            if redact_tenants:
                rates = {"*": float(sum(self._rate.values()))}
            else:
                rates = {k: float(v) for k, v in sorted(self._rate.items())}
            total = sum(self._mix.values())
            return {
                "samples": self._samples,
                "min_samples": self.min_samples,
                "ready": self._samples >= self.min_samples,
                "age_s": time.monotonic() - self._started,
                "staleness_s": (
                    time.monotonic() - self._last_observe
                    if self._last_observe is not None else None
                ),
                "per_bucket": per_bucket,
                "batch_ms": batch,
                "fill_rows": self._fill,
                "bias_ms": self._bias,
                "arrival_rate_per_s": rates,
                "traffic_mix": {
                    str(b): v / total for b, v in sorted(self._mix.items())
                } if total > 0 else {},
                "refusals": self.refusals,
                "guard_checked": self.guard_checked,
                "guard_violations": self.guard_violations,
            }

    # -- persistence (PR-19 telemetry segments) ----------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The durable model state (everything restore() needs; the
        monotonic-clock fields — arrival stamps, freshness — are
        process-local and deliberately NOT persisted)."""
        with self._lock:
            return {
                "schema": SNAPSHOT_SCHEMA,
                "samples": self._samples,
                "min_samples": self.min_samples,
                "lat": {
                    f"{t}:{b}": r.state()
                    for (t, b), r in self._lat.items()
                },
                "batch": {str(b): r.state() for b, r in self._batch.items()},
                "rate": {k: float(v) for k, v in self._rate.items()},
                "mix": {str(b): float(v) for b, v in self._mix.items()},
                "fill": self._fill,
                "bias": self._bias,
            }

    def restore(self, snap: Dict[str, Any]) -> bool:
        """Load a snapshot() payload; False (and untouched state) on a
        schema/shape mismatch — a corrupt segment must not poison a
        fresh model."""
        try:
            if int(snap.get("schema", -1)) != SNAPSHOT_SCHEMA:
                return False
            lat = {}
            for key, samples in dict(snap.get("lat", {})).items():
                tier, _, b = key.rpartition(":")
                ring = _Ring()
                ring.restore(samples)
                lat[(tier, int(b))] = ring
            batch = {}
            for b, samples in dict(snap.get("batch", {})).items():
                ring = _Ring()
                ring.restore(samples)
                batch[int(b)] = ring
            rate = {str(k): float(v)
                    for k, v in dict(snap.get("rate", {})).items()}
            mix = {int(b): float(v)
                   for b, v in dict(snap.get("mix", {})).items()}
            fill_raw = snap.get("fill")
            fill = None if fill_raw is None else float(fill_raw)
            bias_raw = snap.get("bias")
            bias = None if bias_raw is None else float(bias_raw)
            samples = int(snap["samples"])
        except (KeyError, TypeError, ValueError):
            return False
        with self._lock:
            self._lat = lat
            self._batch = batch
            self._rate = rate
            self._mix = mix
            self._fill = fill
            self._bias = bias
            self._samples = samples
        return True

    def save(self, telemetry, service: Optional[str] = None) -> None:
        """Emit one durable snapshot record onto the telemetry log's
        bounded queue (never blocks; drops are counted by the log)."""
        if telemetry is None:
            return
        telemetry.emit({
            "kind": "capacity",
            "service": service or f"daemon-{self.name}",
            "pid": telemetry.pid,
            "model": self.snapshot(),
        })

    def replay_journey(self, journey: Dict[str, Any]) -> None:
        """Warm from one exported journey record (the restore fallback:
        no snapshot found, raw journeys replayed instead)."""
        meta = journey.get("meta") or {}
        phases = {
            p.get("phase"): p.get("t_ns")
            for p in journey.get("phases", ())
            if isinstance(p, dict)
        }
        t_sub, t_res = phases.get("submitted"), phases.get("resolved")
        service_ms = (
            (t_res - t_sub) / 1e6
            if t_sub is not None and t_res is not None else None
        )
        self.observe_journey(
            tier=str(meta.get("tier", "best_effort")),
            tenant=str(meta.get("tenant", "anonymous")),
            rows=int(journey.get("rows") or 1),
            bucket=journey.get("bucket"),
            service_ms=service_ms,
            outcome=str(journey.get("outcome") or "ok"),
        )


def load_capacity_model(directory: Optional[str], name: str,
                        min_samples: Optional[int] = None) -> CapacityModel:
    """Build a CapacityModel, warm-started from the telemetry segments
    in ``directory`` when possible: the NEWEST ``{"kind": "capacity"}``
    snapshot for ``daemon-{name}`` wins; with no snapshot, the raw
    journey records for that daemon are replayed (bounded). Unreadable
    files and undecodable lines are skipped — restore is best-effort by
    contract; the model relearns whatever the segments failed to carry."""
    model = CapacityModel(name=name, min_samples=min_samples)
    if not directory or not os.path.isdir(directory):
        return model
    service = f"daemon-{name}"
    best_snap: Optional[Dict[str, Any]] = None
    journeys: List[Dict[str, Any]] = []
    paths = sorted(
        glob.glob(os.path.join(directory, "keystone_telemetry_*.jsonl")),
        key=lambda p: (os.path.getmtime(p), p),
    )
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                for line in fh:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail line of a crashed writer
                    if rec.get("service") != service:
                        continue
                    kind = rec.get("kind")
                    if kind == "capacity" and isinstance(
                        rec.get("model"), dict
                    ):
                        best_snap = rec["model"]  # newest-by-order wins
                    elif kind == "journey" and isinstance(
                        rec.get("journey"), dict
                    ):
                        journeys.append(rec["journey"])
                        if len(journeys) > REPLAY_MAX_RECORDS:
                            del journeys[: len(journeys) // 2]
        except OSError:
            continue
    if best_snap is not None and model.restore(best_snap):
        logger.info(
            "capacity model %s: restored snapshot (%d samples) from "
            "telemetry segments in %s", name, model.samples(), directory,
        )
        return model
    for j in journeys:
        model.replay_journey(j)
    if journeys:
        logger.info(
            "capacity model %s: warmed from %d exported journey record(s) "
            "in %s (no snapshot found)", name, len(journeys), directory,
        )
    return model
