"""Rule-based graph optimizer.

Ref: src/main/scala/workflow/Optimizer.scala — Catalyst-style batches of
rewrite rules run to fixed point [unverified]. The default pipeline here:

1. ``EquivalentNodeMergeRule`` — dedups structurally identical nodes (restores
   sharing lost to copy-on-instantiate composition).
2. ``ChainFusionRule`` — the TPU-specific lowering: maximal chains of jittable
   transformers become ONE ``FusedTransformer`` whose batch function is a
   single XLA computation. This replaces the reference's per-stage RDD
   execution with whole-chain compilation, letting XLA fuse elementwise work
   into the matmuls/convs around it.

Node-level solver selection and the auto-caching rule plug in as additional
rules (see workflow/rules.py as they land).
"""

from __future__ import annotations

import contextvars
from typing import Dict, List, Optional, Sequence, Tuple

from keystone_tpu.config import config
from keystone_tpu.workflow.graph import Graph, GraphId, NodeId, SourceId
from keystone_tpu.workflow.operators import TransformerOperator
from keystone_tpu.workflow.pipeline import FusedTransformer

#: The content digest of the pipeline AS THE USER WROTE IT, captured at
#: optimizer entry BEFORE any rule rewrites the graph (node-level solver
#: swaps change node digests, so a rule computing the key mid-pass would
#: never match what Pipeline.fit(profile=True) stored). Rules read it via
#: ``active_profile_key()``.
_profile_key: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "keystone_profile_key", default=None
)


def active_profile_key() -> Optional[str]:
    """The measured-profile store key of the pipeline currently being
    optimized (None outside an optimizer pass, when no store is
    configured, or when the pipeline has no content identity)."""
    return _profile_key.get()


class Rule:
    def apply(self, graph: Graph, targets: Sequence[GraphId]) -> Graph:
        raise NotImplementedError


class EquivalentNodeMergeRule(Rule):
    """Merge nodes with identical (operator signature, dependencies).

    Ref: workflow/EquivalentNodeMergeRule.scala [unverified].
    """

    def apply(self, graph: Graph, targets: Sequence[GraphId]) -> Graph:
        order = graph.reachable(targets)
        canon: Dict[Tuple, NodeId] = {}
        remap: Dict[GraphId, GraphId] = {}
        ops = {}
        dps = {}
        targets_set = set(targets)
        for nid in order:
            op = graph.operators[nid]
            deps = tuple(remap.get(d, d) for d in graph.dependencies[nid])
            key = (op.signature(), deps)
            if key in canon and nid not in targets_set:
                remap[nid] = canon[key]
            else:
                canon.setdefault(key, nid)
                ops[nid] = op
                dps[nid] = deps
        # Always rebuild: this also prunes nodes unreachable from the targets
        # (orphans left by composition's copy-on-instantiate).
        return Graph(ops, dps)


class ChainFusionRule(Rule):
    """Fuse maximal single-consumer chains of jittable transformers.

    Fused transformers are memoized on the identity of their stage tuple so
    re-optimizing a copy of the same logical chain (every ``apply`` creates a
    fresh graph copy) reuses the same FusedTransformer object — and therefore
    its already-compiled jit cache. Without this, every ``get()`` would
    re-trace and re-compile the chain.
    """

    def __init__(self):
        # stage-id tuple -> FusedTransformer; values hold the stages strongly,
        # so the id keys can never alias recycled objects.
        self._fuse_cache: Dict[Tuple[int, ...], FusedTransformer] = {}

    def clear_cache(self) -> None:
        self._fuse_cache.clear()

    def _fused(self, stages: List) -> FusedTransformer:
        key = tuple(id(s) for s in stages)
        fused = self._fuse_cache.get(key)
        if fused is None:
            while len(self._fuse_cache) > 1024:
                # Bound the memo by evicting the OLDEST entry (dict keeps
                # insertion order): wholesale clearing would force live hot
                # pipelines to re-fuse (new identity, recompile, cache-miss
                # cascade); dropping one cold entry degrades gracefully.
                self._fuse_cache.pop(next(iter(self._fuse_cache)))
            fused = FusedTransformer(stages)
            self._fuse_cache[key] = fused
        return fused

    def apply(self, graph: Graph, targets: Sequence[GraphId]) -> Graph:
        if not config.fuse_chains:
            return graph
        targets_set = set(targets)
        cons = graph.consumers(targets)
        order = graph.reachable(targets)

        def fusable(gid: GraphId) -> bool:
            if not isinstance(gid, NodeId):
                return False
            op = graph.operators.get(gid)
            return (
                isinstance(op, TransformerOperator) and op.transformer.jittable
            )

        chain_of: Dict[NodeId, List[NodeId]] = {}
        for nid in order:
            if not fusable(nid):
                continue
            dep = graph.dependencies[nid][0]
            if (
                fusable(dep)
                and len(cons.get(dep, ())) == 1
                and dep not in targets_set
                and dep in chain_of
            ):
                chain_of[nid] = chain_of.pop(dep) + [nid]
            else:
                chain_of[nid] = [nid]

        changed = False
        ops = dict(graph.operators)
        dps = dict(graph.dependencies)
        for tail, chain in chain_of.items():
            if len(chain) < 2:
                continue
            changed = True
            stages = [graph.operators[c].transformer for c in chain]
            ops[tail] = TransformerOperator(self._fused(stages))
            dps[tail] = graph.dependencies[chain[0]]
            for c in chain[:-1]:
                ops.pop(c, None)
                dps.pop(c, None)
        return Graph(ops, dps) if changed else graph


class Optimizer:
    """Batches of rules, each run to fixed point (bounded)."""

    def __init__(self, batches: Sequence[Tuple[str, Sequence[Rule], int]]):
        self.batches = list(batches)

    def execute(self, graph: Graph, targets: Sequence[GraphId]) -> Graph:
        token = _profile_key.set(self._profile_key_of(graph, targets))
        try:
            for _name, rules, max_iters in self.batches:
                for _ in range(max_iters):
                    before = (graph.operators, graph.dependencies)
                    for rule in rules:
                        graph = rule.apply(graph, targets)
                    if (graph.operators, graph.dependencies) == before:
                        break
            return graph
        finally:
            _profile_key.reset(token)

    @staticmethod
    def _profile_key_of(
        graph: Graph, targets: Sequence[GraphId]
    ) -> Optional[str]:
        """The store key for this pass — computed only when a profile
        store is configured AND a consuming rule is enabled (the digest
        walks the whole graph, fingerprinting bound data; a per-batch
        apply pass with auto-cache and the planner both off must not
        pay it)."""
        from keystone_tpu.config import config, resolved_profile_store

        if not targets or not resolved_profile_store():
            return None
        if not (config.auto_cache or config.plan_resources):
            return None
        from keystone_tpu.workflow.profile_store import (
            pipeline_profile_digest,
        )

        return pipeline_profile_digest(graph, targets[0])


def default_optimizer() -> Optimizer:
    from keystone_tpu.workflow.rules import (
        AutoCacheRule,
        NodeOptimizationRule,
        PlanResourcesRule,
    )

    batches: List[Tuple[str, List[Rule], int]] = [
        ("dedup", [EquivalentNodeMergeRule()], 3),
        ("node-level", [NodeOptimizationRule()], 1),
        # Profile-guided resource planning (exec workers / solve chunk
        # rows): acts only on a measured-profile hit; gated per-apply on
        # config.plan_resources like auto-cache below.
        ("plan", [PlanResourcesRule(only_if_enabled=True)], 1),
        # Gated per-apply on config.auto_cache (see AutoCacheRule), so the
        # flag works whenever it's flipped, not only before env creation.
        ("auto-cache", [AutoCacheRule(only_if_enabled=True)], 1),
        ("fusion", [ChainFusionRule()], 1),
    ]
    return Optimizer(batches)
