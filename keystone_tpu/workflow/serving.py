"""Shape-stable serving: bucketed batch apply with AOT warmup, served
from a multi-device replica pool with pipelined dispatch.

Fitted pipelines are *applied* far more often than they are fit, and the
north-star workload is request traffic whose batch sizes vary per call. A
bare ``jax.jit`` recompiles the whole fused chain for every distinct row
count, so a mixed-size trace compiles forever and never reaches steady
state. The standard TPU answer is statically bounded shapes: round every
batch up a small bucket ladder, pad with rows that cannot affect the real
outputs, run ONE ahead-of-time compiled executable per bucket, and slice
the result (arXiv:1810.09868 AOT compilation; arXiv:2206.14148 bounded
shapes).

The training side already spans the whole local mesh; serving does too:

- **Replica pool** — ``CompiledPipeline`` AOT-warms the bucket ladder
  once per device (``devices=`` / ``KEYSTONE_SERVE_DEVICES``, default all
  local devices), each replica owning its own compiled executables. One
  controller dispatches to many devices (arXiv:2112.09017's
  single-controller pattern); the offline batch path maps batches over
  the same pool (the arXiv:2403.07128 map-over-devices shape).
- **Pipelined dispatch** — the micro-batcher's dispatcher picks the
  least-outstanding replica (round-robin on ties) and launches the
  device call WITHOUT waiting for it: JAX async dispatch returns as soon
  as the work is enqueued, so replica B computes while replica A's
  results are still materializing. A bounded in-flight window
  (``KEYSTONE_SERVE_INFLIGHT``, default 2 per replica) stops the
  dispatcher from running unboundedly ahead; result slicing and future
  resolution happen on per-replica completion threads, off the dispatch
  critical path. A dead replica's in-flight groups re-dispatch to the
  survivors (fault site ``replica_death``); with ``devices=1`` and
  window 1 the flush loop is exactly the pre-replica serial path.
- **Oversize sharding** — batches beyond the top bucket shard across
  replicas instead of chunking serially through one device.

Three layers, outermost first:

- ``PipelineService`` — a micro-batcher: concurrent ``submit()`` calls
  coalesce into one bucketed device call (the serving analog of the
  reference's per-partition map — amortize dispatch across requests).
- ``CompiledPipeline`` — the per-process serving engine: bucket ladder,
  mask-safe padding, AOT warmup of every bucket on every replica before
  first traffic, donated input buffers on the hot call, host-in/host-out
  so the steady state issues NO jax operations beyond the pre-compiled
  executables (zero steady-state recompiles, measured by
  tools/bench_serve.py).
- ``bucketed_call`` — the in-graph wiring: ``Transformer.batch_call``
  routes through it when ``config.serve_buckets`` is non-empty (env
  ``KEYSTONE_SERVE_BUCKETS``), so executor-driven applies and
  ``Pipeline.apply_batches`` loops see a bounded shape set too.

Padding is only sound for transformers whose output row i depends on
input row i alone AND whose output row count equals the input row count —
the ``Transformer.row_independent`` flag. Ops that couple rows (batch
statistics at apply time) or fan rows out (``Windower``,
``CenterCornerPatcher``) set it False and the bucketed path refuses them
with ``RowDependenceError`` instead of silently corrupting outputs.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from keystone_tpu.config import config, pow2_ladder, resolved_serve_buckets
from keystone_tpu.utils.flight_recorder import FlightRecorder, next_request_id
from keystone_tpu.utils.metrics import (
    LatencyHistogram,
    active_tracer,
    capacity_counters,
    metrics_registry,
    reliability_counters,
    serving_counters,
)
from keystone_tpu.utils.reliability import (
    DeadlineExceeded,
    QueueFullError,
    ServiceClosed,
    WorkerDiedError,
    active_plan,
)

logger = logging.getLogger("keystone_tpu")

# Registry-backed serving health metrics (utils/metrics.MetricsRegistry):
# per-device-call and end-to-end submit latency histograms. Always on —
# one clock read and a locked bucket increment per REQUEST (not per row),
# noise against a device call — so `MetricsRegistry.snapshot()` reports
# serving p50/p95/p99 without anyone having had to pre-arm tracing before
# the incident. These two are deliberately PROCESS-WIDE aggregates (every
# engine/service records into them); per-instance metrics — queue depth,
# in-flight, per-replica outstanding, dispatch balance, request outcomes —
# are namespaced ``base[instance]`` so two services in one process never
# overwrite each other's readings.
request_latency = metrics_registry.histogram("serve.request_latency")
e2e_latency = metrics_registry.histogram("serve.e2e_latency")
#: Stall-watchdog firings, keyed by service name: a non-empty pending
#: queue that made no dispatch progress past KEYSTONE_WATCHDOG_MS.
stall_counters = metrics_registry.counters("serve.stalls")

#: Samples the always-on e2e histogram needs before the auto (running
#: p99) tail-sampling threshold engages — below this, "p99" is noise.
TAIL_MIN_COUNT = 32

#: Process-wide instance sequencers behind the per-instance metric names.
_engine_seq = itertools.count()
_service_seq = itertools.count()


class RowDependenceError(TypeError):
    """Raised when bucketed (padded) apply is requested for a transformer
    whose batch output depends on other rows — padding would change the
    real outputs, so it is refused rather than risked."""


#: The serving precision ladder (config.serve_precision /
#: CompiledPipeline(precision=)): storage/accumulate mode per rung.
#: "f32" keeps today's byte-identical path; "f32h" traces under matmul
#: precision HIGH (3-pass); "bf16" casts the request batch to bfloat16 at
#: the chain boundary and traces matmuls at DEFAULT precision (one MXU
#: bf16 pass, f32 accumulation — the tests/test_bf16_mode.py contract).
SERVE_PRECISIONS = ("f32", "f32h", "bf16")


class PrecisionQualityError(ValueError):
    """A non-f32 serving precision failed its per-pipeline quality gate:
    the evaluation metric drifted beyond the declared tolerance of the
    f32 oracle. The message names the metric and the measured delta —
    the knob refuses rather than silently serving degraded answers."""


#: Declared default tolerances per quality metric: how far below the f32
#: oracle a reduced-precision serving mode may score before the knob
#: refuses. Override per pipeline via ``qualify(tolerance=)``.
PRECISION_QUALITY_TOLERANCES = {
    "multiclass": 0.01,   # top-1 accuracy (or oracle agreement) drop
    "binary": 0.01,       # accuracy drop
    "map": 0.01,          # mean-average-precision drop
}


def precision_quality_delta(oracle_out, out, y=None, metric="multiclass"):
    """Quality drop of reduced-precision serving outputs vs the f32
    oracle's, measured with the evaluation/ metric the pipeline is
    actually judged by. Returns ``(metric_name, delta, oracle_score,
    score)`` — positive delta = the precision mode scores WORSE.

    - ``multiclass``: top-1 accuracy against ``y`` when labels are
      given (``MulticlassClassifierEvaluator``); without labels, 1 -
      argmax agreement with the oracle (the oracle's predictions ARE the
      reference).
    - ``binary``: accuracy of ``scores > 0`` (column 0 when 2-D)
      against ``y`` resp. the oracle's own thresholded predictions.
    - ``map``: VOC mean average precision over multilabel ``y``
      (labels required — AP is undefined without positives).
    """
    o = np.asarray(oracle_out)
    p = np.asarray(out)
    if o.shape != p.shape:
        raise ValueError(
            f"oracle/serving output shapes differ: {o.shape} vs {p.shape}"
        )
    if metric == "multiclass":
        from keystone_tpu.evaluation import MulticlassClassifierEvaluator

        op_, pp = o.argmax(axis=-1), p.argmax(axis=-1)
        classes = int(o.shape[-1])
        ev = MulticlassClassifierEvaluator(classes)
        if y is not None:
            ref = ev.evaluate(op_, y).total_accuracy
            got = ev.evaluate(pp, y).total_accuracy
        else:
            ref = 1.0
            got = ev.evaluate(pp, op_).total_accuracy
        return "multiclass_accuracy", ref - got, ref, got
    if metric == "binary":
        from keystone_tpu.evaluation import BinaryClassifierEvaluator

        os_ = o[:, 0] if o.ndim == 2 else o
        ps = p[:, 0] if p.ndim == 2 else p
        ref_pred, got_pred = os_ > 0, ps > 0
        if y is not None:
            ref = BinaryClassifierEvaluator.evaluate(ref_pred, y).accuracy
            got = BinaryClassifierEvaluator.evaluate(got_pred, y).accuracy
        else:
            ref = 1.0
            got = BinaryClassifierEvaluator.evaluate(
                got_pred, ref_pred
            ).accuracy
        return "binary_accuracy", ref - got, ref, got
    if metric == "map":
        from keystone_tpu.evaluation import MeanAveragePrecisionEvaluator

        if y is None:
            raise ValueError(
                "metric='map' needs multilabel ground truth y"
            )
        ev = MeanAveragePrecisionEvaluator(int(o.shape[-1]))
        ref = ev.evaluate(o, y)["map"]
        got = ev.evaluate(p, y)["map"]
        return "map", ref - got, ref, got
    raise ValueError(
        f"unknown quality metric {metric!r}; expected one of "
        f"{tuple(PRECISION_QUALITY_TOLERANCES)}"
    )


def check_precision_quality(
    oracle_out, out, y=None, metric="multiclass",
    tolerance: Optional[float] = None, precision: str = "?",
) -> dict:
    """THE per-pipeline quality gate of the precision ladder: compare
    reduced-precision serving outputs against the f32 oracle's with the
    declared evaluation metric and raise a typed
    ``PrecisionQualityError`` — naming the metric and the delta — when
    the drop exceeds the declared tolerance. Returns the report dict
    (metric, scores, delta, tolerance) on a pass."""
    if tolerance is None:
        if metric not in PRECISION_QUALITY_TOLERANCES:
            raise ValueError(
                f"unknown quality metric {metric!r}; expected one of "
                f"{tuple(PRECISION_QUALITY_TOLERANCES)}"
            )
        tolerance = PRECISION_QUALITY_TOLERANCES[metric]
    name, delta, ref, got = precision_quality_delta(
        oracle_out, out, y=y, metric=metric
    )
    report = {
        "metric": name,
        "precision": precision,
        "oracle_score": round(float(ref), 6),
        "score": round(float(got), 6),
        "quality_delta": round(float(delta), 6),
        "tolerance": float(tolerance),
        "within_tolerance": bool(delta <= tolerance),
    }
    if delta > tolerance:
        raise PrecisionQualityError(
            f"serve_precision={precision} refused: {name} dropped "
            f"{delta:.6f} below the f32 oracle ({ref:.6f} -> {got:.6f}), "
            f"beyond the declared tolerance {tolerance:g}. Serve this "
            "pipeline at f32, or raise the tolerance explicitly if the "
            "trade is intended."
        )
    return report


# ---------------------------------------------------------------------------
# Ladder helpers
# ---------------------------------------------------------------------------


def ladder_is_pinned(buckets: Optional[Sequence[int]] = None) -> bool:
    """Whether the ladder came from an explicit source the HBM planner
    must not touch: a ``buckets=`` argument, a live-exported
    KEYSTONE_SERVE_BUCKETS (the env-pins convention — presence wins),
    or a programmatic ``config.serve_buckets``. Only the unset default
    (the pow-2 ladder) is the planner's to size."""
    return (
        buckets is not None
        or resolved_serve_buckets() is not None
        or bool(config.serve_buckets)
    )


def resolve_ladder(
    buckets: Optional[Sequence[int]] = None, max_batch: Optional[int] = None
) -> Tuple[int, ...]:
    """The bucket ladder to serve with: explicit ``buckets`` > a
    live-exported ``KEYSTONE_SERVE_BUCKETS`` > ``config.serve_buckets``
    > pow-2 up to ``max_batch`` / ``config.serve_max_batch``. Always
    sorted, deduplicated, positive. An unpinned (pow-2 default) ladder
    is additionally auto-sized against the HBM budget at engine warmup
    (``CompiledPipeline`` + ``rules.plan_serve_ladder``); a pinned one
    never is — see ``ladder_is_pinned``."""
    if buckets is None:
        env = resolved_serve_buckets()
        if env is not None:
            buckets = env
    if buckets is None and config.serve_buckets:
        buckets = config.serve_buckets
    if buckets is None:
        # `is None`, not truthiness: an explicit max_batch=0 must hit
        # pow2_ladder's ValueError, not silently become the config default.
        ladder = pow2_ladder(
            config.serve_max_batch if max_batch is None else max_batch
        )
    else:
        ladder = tuple(sorted({int(b) for b in buckets}))
        if max_batch is not None:
            ladder = tuple(b for b in ladder if b <= max_batch)
            if not ladder or ladder[-1] < max_batch:
                ladder = ladder + (int(max_batch),)
    if not ladder or ladder[0] <= 0:
        raise ValueError(f"bucket ladder must be positive ints, got {ladder}")
    return ladder


def bucket_for(n: int, ladder: Sequence[int]) -> Optional[int]:
    """Smallest bucket >= n, or None when n exceeds the ladder (the caller
    chunks)."""
    for b in ladder:
        if n <= b:
            return b
    return None


def resolve_serve_devices(devices=None) -> tuple:
    """The replica pool's devices: an explicit jax-device sequence, an int
    replica count (prefix of the local devices), or None →
    ``config.serve_devices`` (env ``KEYSTONE_SERVE_DEVICES``; 0 = every
    local device)."""
    if devices is None:
        devices = config.serve_devices
    if isinstance(devices, int):
        local = jax.local_devices()
        if devices == 0:
            return tuple(local)
        if devices < 1:
            raise ValueError(
                f"devices must be >= 1 (or 0 = all local), got {devices}"
            )
        if devices > len(local):
            raise ValueError(
                f"devices={devices} exceeds the {len(local)} local devices"
            )
        return tuple(local[:devices])
    devs = tuple(devices)
    if not devs:
        raise ValueError("devices sequence must not be empty")
    return devs


def _least_outstanding(n, cursor, outstanding, eligible=None):
    """THE dispatch policy, shared by the engine (direct calls, oversize
    sharding) and the service (slot-capped, dead-skipping): the index
    minimizing ``outstanding(i)`` among ``eligible(i)`` replicas, scanned
    round-robin from ``cursor`` so ties rotate and a sequential caller
    still covers the whole pool. None when nothing is eligible."""
    best = None
    for k in range(n):
        i = (cursor + k) % n
        if eligible is not None and not eligible(i):
            continue
        if best is None or outstanding(i) < outstanding(best):
            best = i
    return best


def _jit_cache_size(jit_fn) -> int:
    """Compiled-entry count of a jitted callable, for compile observability
    on the batch_call path (0 where the runtime doesn't expose it)."""
    try:
        return jit_fn._cache_size()
    except (AttributeError, TypeError):  # runtime-private API, may not exist
        return 0


def _stages(transformer) -> list:
    from keystone_tpu.workflow.pipeline import FusedTransformer

    if isinstance(transformer, FusedTransformer):
        return list(transformer.stages)
    return [transformer]


def _row_coupled_stages(transformer) -> list:
    """Names of stages whose output rows depend on other rows — the ONE
    definition of pad-unsafety both the explicit engine and the implicit
    batch_call knob consult."""
    return [
        type(s).__name__
        for s in _stages(transformer)
        if not getattr(s, "row_independent", True)
    ]


def check_row_independent(transformer) -> None:
    """Raise RowDependenceError naming every offending stage."""
    bad = _row_coupled_stages(transformer)
    if bad:
        raise RowDependenceError(
            f"cannot pad batches through {', '.join(bad)}: the stage's "
            "batch output depends on other rows (row_independent=False), "
            "so bucketed serving would change real outputs. Serve it "
            "per-shape (unset KEYSTONE_SERVE_BUCKETS / serve_buckets) or "
            "keep the row-coupled stage off the bucketed path."
        )


# ---------------------------------------------------------------------------
# In-graph bucketing (Transformer.batch_call wiring)
# ---------------------------------------------------------------------------


# Row-coupled transformer classes we have already warned about falling back
# to per-shape jit under the global bucketing knob (warn once per class, not
# once per batch).
_fallback_warned: set = set()


def bucketed_call(transformer, X):
    """Bucket-pad-run-slice on device, through the transformer's own
    per-shape jit cache — which now only ever sees ladder shapes, so the
    compile set is bounded by the ladder instead of the request mix.

    Used by ``Transformer.batch_call`` when ``config.serve_buckets`` is
    set. Stays device-in/device-out (this runs mid-graph, feeding further
    device ops); the tiny pad/slice ops compile once per (bucket, n) pair
    and then also reach steady state.

    Row-coupled transformers (``row_independent=False``) cannot be padded;
    here — the IMPLICIT, process-wide knob — they fall back to today's
    per-shape jit with a one-time warning, so flipping
    KEYSTONE_SERVE_BUCKETS never crashes a working pipeline (e.g. the
    ImageNet TTA view expansion mid-graph). The EXPLICIT serving engine
    (``CompiledPipeline``), where the user asked for bucketed execution by
    name, refuses them with ``RowDependenceError`` instead.
    """
    import logging

    import jax.numpy as jnp

    bad = _row_coupled_stages(transformer)
    if bad:
        key = tuple(bad)
        if key not in _fallback_warned:
            _fallback_warned.add(key)
            logging.getLogger("keystone_tpu").warning(
                "serve_buckets: %s is not row-independent; padding refused, "
                "falling back to per-shape jit (this path can recompile per "
                "batch size)",
                ", ".join(bad),
            )
        return transformer._jitted()(X)
    ladder = resolve_ladder()
    # Normalize to a jax array up front: a numpy batch and an equal-shape
    # device array key DIFFERENT jit-cache entries, which would double the
    # compile set per bucket.
    X = jnp.asarray(X)
    n = int(X.shape[0])
    if n == 0:
        return transformer._jitted()(X)
    jit_fn = transformer._jitted()
    max_b = ladder[-1]
    outs = []
    for start in range(0, n, max_b):
        chunk = X[start : min(start + max_b, n)]
        m = int(chunk.shape[0])
        b = bucket_for(m, ladder)
        if m != b:
            pad = jnp.broadcast_to(chunk[-1:], (b - m,) + chunk.shape[1:])
            chunk = jnp.concatenate([chunk, pad], axis=0)
        cache_before = _jit_cache_size(jit_fn)
        out = jit_fn(chunk)
        if _jit_cache_size(jit_fn) > cache_before:
            serving_counters.record_compile(b)  # cold ladder bucket
        serving_counters.record_call(b, m)
        if m != b:
            out = jax.tree_util.tree_map(lambda a: a[:m], out)
        outs.append(out)
    if len(outs) == 1:
        return outs[0]
    return jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=0), *outs
    )


# ---------------------------------------------------------------------------
# CompiledPipeline — AOT-warmed bucketed serving engine over a replica pool
# ---------------------------------------------------------------------------


def _serving_transformer(target):
    """Lower a Pipeline / Transformer to ``(transformer,
    measured_bytes_per_row)``: the single jittable transformer the
    serving engine compiles (fitting estimators and fusing the chain),
    plus — when the pipeline has a measured profile in the store — the
    summed per-row activation bytes of its recorded nodes, the
    measured-provenance input to the HBM ladder planner (None when no
    usable profile exists; the planner falls back to the abstract AOT
    ``memory_analysis`` estimate)."""
    from keystone_tpu.workflow.executor import PipelineEnv
    from keystone_tpu.workflow.pipeline import Pipeline, Transformer

    if isinstance(target, Pipeline):
        fitted = target.fit()
        chain = PipelineEnv.get().executor.serving_chain(
            fitted.graph, fitted.source, fitted.sink
        )
        return chain, _measured_bytes_per_row(fitted)
    if isinstance(target, Transformer):
        if not target.jittable:
            raise TypeError(
                f"{type(target).__name__} is not jittable; the AOT serving "
                "path compiles the whole chain as one XLA program"
            )
        return target, None
    raise TypeError(f"cannot serve a {type(target).__name__}")


def _measured_bytes_per_row(fitted) -> Optional[float]:
    """Per-row activation bytes of a fitted pipeline from its stored
    measured profile: the sum of ``out_bytes / out_rows`` over every
    recorded node — a conservative all-activations-resident price (the
    high-water is at most this), matched by the same
    ``pipeline_profile_digest`` key the optimizer rules consume. None
    when no store is configured, no entry matches, or no row carries
    usable bytes/rows."""
    from keystone_tpu.workflow.profile_store import (
        lookup_measured,
        pipeline_profile_digest,
    )

    prof = lookup_measured(
        pipeline_profile_digest(fitted.graph, fitted.sink)
    )
    if prof is None:
        return None
    total = 0.0
    for entry in prof.digests.values():
        rows = int(entry.get("out_rows") or 0)
        nbytes = int(entry.get("out_bytes") or 0)
        if rows > 0 and nbytes > 0:
            total += nbytes / rows
    return total or None


class _Replica:
    """One device's slice of the serving pool: its own AOT-compiled
    executables plus launch accounting (outstanding = launched chunks not
    yet materialized; dispatches = the balance evidence)."""

    __slots__ = ("index", "device", "executables", "outstanding",
                 "dispatches")

    def __init__(self, index: int, device):
        self.index = index
        self.device = device
        self.executables: dict = {}
        self.outstanding = 0
        self.dispatches = 0


class _Launched:
    """A chunk in flight on one replica: the un-materialized device output
    and everything the completion side needs to slice and attribute it
    (including the request ids riding in the chunk, so the cross-thread
    ``serve.device`` span links back to each request's journey)."""

    __slots__ = ("replica", "out", "m", "b", "t0", "req_ids")

    def __init__(self, replica, out, m, b, t0, req_ids):
        self.replica = replica
        self.out = out
        self.m = m
        self.b = b
        self.t0 = t0
        self.req_ids = req_ids


class _AsyncResult:
    """Handle for an asynchronously served batch: chunks launch up to a
    bounded window ahead (riding JAX async dispatch), ``wait()``
    materializes them in source order and concatenates. With one replica
    and window 1 this is exactly the serial launch→materialize loop."""

    __slots__ = ("_cp", "_X", "_pin", "_window", "_starts", "_next",
                 "_launched", "_outs", "_result", "_done", "_exc", "_t0",
                 "_req_ids")

    def __init__(self, cp: "CompiledPipeline", X: np.ndarray,
                 pin: Optional[int], window: int, t0: float,
                 req_ids: Optional[Sequence[int]] = None):
        self._cp = cp
        self._X = X
        self._pin = pin
        self._t0 = t0
        self._req_ids = tuple(req_ids) if req_ids else None
        self._window = max(1, int(window))
        self._starts = list(range(0, X.shape[0], cp.max_batch))
        self._next = 0
        self._launched: deque = deque()
        self._outs: list = []
        self._result = None
        self._done = False
        self._exc: Optional[BaseException] = None
        self._fill()

    def _fill(self) -> None:
        while (
            self._next < len(self._starts)
            and len(self._launched) < self._window
        ):
            s = self._starts[self._next]
            chunk = self._X[s : s + self._cp.max_batch]
            self._launched.append(
                self._cp._launch_chunk(chunk, self._pin, self._req_ids)
            )
            self._next += 1

    def wait(self):
        """Block until every chunk has materialized; returns the host
        (numpy) result sliced to the real row count. Idempotent: repeat
        calls return the same result — or re-raise the same failure."""
        if self._done:
            if self._exc is not None:
                raise self._exc
            return self._result
        try:
            while self._launched:
                self._outs.append(
                    self._cp._complete_chunk(self._launched.popleft())
                )
                self._fill()
        except BaseException as e:
            # A failed chunk must not leak the OTHER launched chunks'
            # replica slots — least-outstanding dispatch would forever
            # see the replica as busy.
            self.abandon()
            self._exc = e
            raise
        if len(self._outs) == 1:
            self._result = self._outs[0]
        else:
            self._result = jax.tree_util.tree_map(
                lambda *xs: np.concatenate(xs, axis=0), *self._outs
            )
        self._outs = []
        self._X = None  # free the input batch
        self._done = True
        # Every engine-served batch lands in the always-on histogram —
        # the pipelined service path included, which never goes through
        # __call__. Boundaries: call_async entry (post-warmup) →
        # materialized, matching what an external caller times around a
        # synchronous cp(X).
        request_latency.record(time.perf_counter() - self._t0)
        return self._result

    def abandon(self) -> None:
        """Discard the result WITHOUT materializing: releases the replica
        slots of launched-but-unfinished chunks (the device work itself
        is dropped — safe, the serve chain is pure). Used when the owner
        of this handle dies (replica death, close) so the engine's
        least-outstanding accounting doesn't leak busy slots forever.
        Not thread-safe against a concurrent ``wait()`` — only the
        handle's owner may call it."""
        if self._done:
            return
        while self._launched:
            self._cp._release_slot(self._launched.popleft())
        self._outs = []
        self._X = None
        self._result = None
        self._done = True

    def __del__(self):
        # A dropped handle (caller raised between call_async and wait, or
        # just discarded it) must not leak its replica slots when the GC
        # collects it. Idempotent via _done; errors at interpreter
        # teardown are swallowed.
        try:
            self.abandon()
        except Exception:  # lint: broad-ok GC/teardown finalizer: anything may be half-torn-down
            pass


class CompiledPipeline:
    """A fitted pipeline compiled for shape-stable serving on a pool of
    device replicas.

    - Rounds incoming batches up the bucket ladder, pads with mask-safe
      rows (the last real row, replicated — numerically inert for
      row-independent chains and immune to 0-row pathologies like
      divide-by-norm), runs the bucket's pre-compiled executable, slices.
    - ``warmup()`` AOT-compiles the WHOLE ladder — on EVERY replica — via
      ``jit(...).lower(spec).compile()`` before first traffic, lowering
      each replica's executables against its own device sharding.
    - Donates the padded input buffer on the hot call (we own it — it was
      built by padding — so donation is always safe; auto-disabled on CPU
      where XLA ignores it).
    - Host-in/host-out: padding is numpy, results come back as numpy. The
      steady state therefore issues zero jax tracing/compile work — only
      pre-compiled executable calls. Oversize batches shard across the
      replica pool (least-outstanding, round-robin on ties) instead of
      chunking serially through one device.
    - ``call_async()`` returns a handle without waiting for the device —
      the dispatch primitive the micro-batcher and the offline
      ``apply_batches`` data-parallel path pipeline on.
    """

    def __init__(
        self,
        target,
        buckets: Optional[Sequence[int]] = None,
        max_batch: Optional[int] = None,
        donate: Optional[bool] = None,
        devices=None,
        inflight: Optional[int] = None,
        name: Optional[str] = None,
        precision: Optional[str] = None,
    ):
        self.transformer, self._measured_bpr = _serving_transformer(target)
        check_row_independent(self.transformer)
        self.ladder = resolve_ladder(buckets, max_batch)
        self.max_batch = self.ladder[-1]
        # An explicit ladder (buckets=, KEYSTONE_SERVE_BUCKETS, or
        # config.serve_buckets) is a pin the HBM planner never touches;
        # only the unset pow-2 default is the planner's to size — at
        # warmup, when the traffic signature prices the rungs.
        self._ladder_pinned = ladder_is_pinned(buckets)
        self._base_ladder = self.ladder  # the pre-plan candidate rungs
        self._planned: Optional[dict] = None
        if donate is None:
            donate = jax.default_backend() != "cpu"
        self.donate = bool(donate)
        # `is None`, not truthiness: the config default is the knob.
        self.precision = (
            config.serve_precision if precision is None else str(precision)
        )
        if self.precision not in SERVE_PRECISIONS:
            raise ValueError(
                f"serve precision must be one of {SERVE_PRECISIONS}, got "
                f"{self.precision!r}"
            )
        self._jit = jax.jit(
            self._serve_fn(),
            donate_argnums=(0,) if self.donate else (),
        )
        self.devices = resolve_serve_devices(devices)
        self.replicas = [
            _Replica(i, d) for i, d in enumerate(self.devices)
        ]
        # `is None`, not truthiness: an explicit inflight=0 must error.
        self.inflight = int(
            config.serve_inflight if inflight is None else inflight
        )
        if self.inflight < 1:
            raise ValueError(f"inflight must be >= 1, got {self.inflight}")
        # Auto names are process-unique; registry entries live for the
        # process, so a caller that constructs engines repeatedly should
        # pass a stable ``name`` (an explicit aggregation key — same name
        # = shared dispatch counters/gauges) to bound metric cardinality.
        self.name = name or f"cp{next(_engine_seq)}"
        self.feature_shape: Optional[Tuple[int, ...]] = None
        self._dtype = None
        self.compile_count = 0
        # Per-ENGINE bucket attribution (serving_counters keeps the
        # process-wide view): two engines in one process must not read
        # each other's compiles off their own stats().
        self.compiles_by_bucket: dict = {}
        self.warmup_seconds: Optional[float] = None
        self._lock = threading.Lock()
        self._rr = 0  # round-robin cursor breaking least-outstanding ties
        # Per-instance registry metrics: dispatch balance across the pool
        # and each replica's outstanding-launch gauge, tagged with the
        # engine name and device id so multiple engines coexist.
        self._dispatch_counters = metrics_registry.counters(
            f"serve.dispatch[{self.name}]"
        )
        self._out_gauges = [
            metrics_registry.gauge(f"serve.outstanding[{self.name}:d{d.id}]")
            for d in self.devices
        ]
        # Resolved ONCE per engine (the active_plan discipline): tracing
        # disabled = a None check on the hot call, nothing more.
        self._tracer = active_tracer()

    @property
    def dtype(self):
        return self._dtype

    def _serve_fn(self):
        """The function every bucket executable compiles, at the engine's
        precision. ``f32`` returns ``apply_batch`` ITSELF — the
        pre-precision-ladder path, byte for byte, so the default mode is
        bit-identical by construction, not by test. ``f32h`` traces the
        chain under matmul precision HIGH (3-pass bf16 emulation; a
        numeric no-op on CPU). ``bf16`` casts the request batch to
        bfloat16 at the chain boundary (bf16 storage — on the MXU every
        matmul then runs its native one-pass bf16 multiply with f32
        accumulation, matmul precision DEFAULT) while fitted weights
        stay f32; any bf16 output leaf is cast back to the request dtype
        so downstream consumers see the same signature as f32 serving."""
        apply_batch = self.transformer.apply_batch
        if self.precision == "f32":
            return apply_batch
        if self.precision == "f32h":
            def serve_f32h(X):
                with jax.default_matmul_precision("high"):
                    return apply_batch(X)
            return serve_f32h

        def serve_bf16(X):
            import jax.numpy as jnp

            dt = X.dtype
            with jax.default_matmul_precision("default"):
                out = apply_batch(X.astype(jnp.bfloat16))
            return jax.tree_util.tree_map(
                lambda a: a.astype(dt) if a.dtype == jnp.bfloat16 else a,
                out,
            )
        return serve_bf16

    # -- warmup ------------------------------------------------------------

    def warmup(
        self, example: Union[Tuple[int, ...], Any], dtype=None,
        replica: Optional[int] = None,
    ) -> "CompiledPipeline":
        """AOT-compile every bucket, on every replica, before first
        traffic.

        ``example`` is either the per-row feature shape (a tuple of ints)
        or a sample batch (leading axis = rows) whose ``shape[1:]``/dtype
        are taken. Idempotent per (shape, dtype): re-warming compiles only
        missing buckets. ``replica=i`` warms ONE replica's ladder — the
        hot-swap handoff warms a successor engine replica-by-replica so
        the outgoing generation keeps answering on the devices not yet
        handed over.
        """
        if isinstance(example, tuple) and all(
            isinstance(d, int) for d in example
        ):
            feature_shape = example
            dt = np.dtype(dtype or config.default_dtype)
        else:
            arr = np.asarray(example)
            if arr.ndim < 1:
                raise ValueError(
                    "warmup example must be a feature-shape tuple or a "
                    "sample batch with a leading row axis"
                )
            feature_shape = arr.shape[1:]
            dt = np.dtype(dtype) if dtype is not None else arr.dtype
        # A float64 host batch must not lower an f64 executable under
        # x64-disabled jax; serve at the dtype jax would compute in.
        dt = np.dtype(jax.dtypes.canonicalize_dtype(dt))
        with self._lock:
            if (
                self.feature_shape is not None
                and (self.feature_shape, self._dtype) != (feature_shape, dt)
            ):
                # New traffic signature: previous executables can't serve
                # it — and the ladder plan was priced at the old shape, so
                # the candidate rungs go back through the planner too.
                for r in self.replicas:
                    r.executables.clear()
                self._planned = None
                self.ladder = self._base_ladder
                self.max_batch = self.ladder[-1]
            self.feature_shape, self._dtype = feature_shape, dt
            # Size the ladder against the HBM budget BEFORE any rung
            # compiles (arXiv:2206.14148: plan memory, don't react): only
            # now is the traffic signature known, so per-rung bytes can
            # be priced. Pinned ladders and a disabled planner skip this.
            self._plan_ladder_locked()
            t0 = time.perf_counter()
            targets = (
                self.replicas if replica is None
                else [self.replicas[replica]]
            )
            for r in targets:
                for b in self.ladder:
                    if b not in r.executables:
                        self._compile_bucket_locked(r, b)
            elapsed = time.perf_counter() - t0
            if replica is None:
                self.warmup_seconds = elapsed
            else:  # per-replica warms accumulate into the total
                self.warmup_seconds = (self.warmup_seconds or 0.0) + elapsed
        return self

    def _compile_bucket_locked(self, replica: _Replica, b: int):
        """Lower + compile one bucket's executable for one replica's
        device (caller holds the lock or is single-threaded setup code)."""
        spec = jax.ShapeDtypeStruct(
            (b,) + self.feature_shape,
            self._dtype,
            sharding=jax.sharding.SingleDeviceSharding(replica.device),
        )
        replica.executables[b] = self._jit.lower(spec).compile()
        self.compile_count += 1
        self.compiles_by_bucket[b] = self.compiles_by_bucket.get(b, 0) + 1
        serving_counters.record_compile(b)
        return replica.executables[b]

    # -- HBM ladder planning -----------------------------------------------

    def _plan_ladder_locked(self) -> None:
        """Auto-size the bucket ladder against the HBM budget (caller
        holds the lock; the traffic signature is set). One plan per
        signature; every trim is a counted ``serve_plan`` registry
        decision plus an optimizer decision-ring entry — never silent.
        Pinned ladders (explicit buckets / KEYSTONE_SERVE_BUCKETS /
        config.serve_buckets) and a disabled planner
        (``config.plan_resources``) are recorded and left untouched."""
        from keystone_tpu.utils.metrics import serve_plan_counters

        if self._planned is not None:
            return
        if self._ladder_pinned:
            serve_plan_counters.bump("ladders_pinned")
            self._planned = {"enabled": False, "reason": "ladder pinned"}
            return
        if not config.plan_resources:
            self._planned = {
                "enabled": False, "reason": "config.plan_resources off",
            }
            return
        bpr, provenance = self._bytes_per_row_locked()
        if bpr is None:
            from keystone_tpu.workflow.rules import record_decision

            serve_plan_counters.bump("plans_unpriced")
            record_decision(
                rule="PlanServeLadder", node=self.name,
                action="serve_buckets=unplanned", provenance="model",
                reason=(
                    "no measured profile and no abstract memory estimate "
                    "— the hand-picked ladder serves as-is"
                ),
            )
            self._planned = {"enabled": False, "reason": "unpriced"}
            return
        from keystone_tpu.workflow.rules import plan_serve_ladder

        kept, _trimmed, info = plan_serve_ladder(
            self._base_ladder, bpr, len(self.replicas),
            provenance=provenance, node=self.name,
        )
        self.ladder = kept
        self.max_batch = kept[-1]
        self._planned = dict(info, enabled=True)

    @property
    def base_ladder(self) -> Tuple[int, ...]:
        """The pre-plan candidate rungs: the ladder as resolved at
        construction, BEFORE HBM planning or capacity re-pricing. Every
        re-plan (``reprice_ladder``) selects from these, so a rung dropped
        for today's traffic mix can come back when the mix shifts again."""
        return tuple(self._base_ladder)

    def reprice_ladder(self, ladder) -> bool:
        """Re-price the active bucket ladder from a new candidate rung set
        (the capacity re-plan consumer: the daemon feeds the rungs the
        OBSERVED traffic mix actually uses, always including the top
        rung). Candidates route back through the HBM planner
        (``rules.plan_serve_ladder``) when planning is enabled, then any
        missing bucket AOT-compiles on every replica before this returns —
        an in-flight dispatch never sees an unwarmed rung. Old executables
        are kept: an idle rung costs host memory, not correctness, and a
        mix that shifts back re-uses them without a recompile. Refuses
        (returns False) on a pinned ladder, an unwarmed engine, or a no-op
        candidate set; never touches ``base_ladder``."""
        wanted = tuple(sorted({int(b) for b in ladder}))
        if not wanted or wanted[0] <= 0:
            raise ValueError(
                f"bucket ladder must be positive ints, got {ladder!r}"
            )
        with self._lock:
            if self._ladder_pinned or self.feature_shape is None:
                return False
            if wanted == tuple(self.ladder):
                return False
            kept = wanted
            if config.plan_resources:
                bpr, provenance = self._bytes_per_row_locked()
                if bpr is not None:
                    from keystone_tpu.workflow.rules import plan_serve_ladder

                    kept, _trimmed, info = plan_serve_ladder(
                        wanted, bpr, len(self.replicas),
                        provenance=provenance, node=self.name,
                    )
                    self._planned = dict(info, enabled=True)
            self.ladder = tuple(kept)
            self.max_batch = self.ladder[-1]
            for r in self.replicas:
                for b in self.ladder:
                    if b not in r.executables:
                        self._compile_bucket_locked(r, b)
        return True

    def _bytes_per_row_locked(self):
        """Per-row resident bytes of one serve call, provenance-laddered
        like every planner price (measured → model): the stored measured
        profile's summed activation bytes/row when the pipeline has one,
        else the abstract AOT ``memory_analysis`` of the SMALLEST rung
        (argument + output + temp bytes — an executable the warmup would
        compile anyway, and ``node_cost_analysis`` memoizes it), else an
        ``eval_shape`` input+output estimate (no compile). ``(None,
        provenance)`` when nothing can price it."""
        if self._measured_bpr:
            return float(self._measured_bpr), "measured"
        from keystone_tpu.utils.metrics import node_cost_analysis

        b0 = self._base_ladder[0]
        spec = jax.ShapeDtypeStruct(
            (b0,) + self.feature_shape, self._dtype
        )
        est = node_cost_analysis(self.transformer, spec) or {}
        total = sum(
            est.get(k) or 0.0
            for k in ("argument_bytes", "output_bytes", "temp_bytes")
        )
        if total > 0:
            return total / b0, "model"
        try:
            out = jax.eval_shape(self.transformer.apply_batch, spec)
            out_bytes = sum(
                int(np.prod(leaf.shape[1:], dtype=np.int64))
                * np.dtype(leaf.dtype).itemsize
                for leaf in jax.tree_util.tree_leaves(out)
                if getattr(leaf, "shape", None)
            )
            in_bytes = (
                int(np.prod(self.feature_shape, dtype=np.int64))
                * self._dtype.itemsize
            )
            return float(in_bytes + out_bytes), "model"
        except Exception:  # lint: broad-ok abstract eval is best-effort; unpriced, not fatal
            return None, "model"

    # -- precision quality gate --------------------------------------------

    def qualify(
        self,
        X,
        y=None,
        metric: str = "multiclass",
        tolerance: Optional[float] = None,
    ) -> dict:
        """The per-pipeline quality gate of the precision ladder: serve
        ``X`` through THIS engine and through a fresh f32 oracle engine
        on the same transformer and ladder, score both with the declared
        ``evaluation/`` metric (against labels ``y`` when given, else
        against the oracle's own predictions), and raise a typed
        ``PrecisionQualityError`` — naming the metric and the measured
        delta — when the drop exceeds the declared tolerance
        (``PRECISION_QUALITY_TOLERANCES[metric]`` unless overridden).
        Returns the quality report on a pass; for an f32 engine the gate
        is the identity check (delta 0) and always passes."""
        X = np.asarray(X)
        if self.precision == "f32":
            out = self(X)
            return check_precision_quality(
                out, out, y=y, metric=metric, tolerance=tolerance,
                precision=self.precision,
            )
        mine = self(X)  # lazily warms this engine off X's signature
        # The throwaway oracle warms ONE rung — the bucket the probe
        # needs (its own top bucket when the probe is oversize, so both
        # engines chunk at the same boundaries) — not the whole ladder:
        # a probe never touches the other rungs, and the cold-bucket
        # path would compile on demand anyway.
        probe_bucket = bucket_for(X.shape[0], self.ladder) or self.max_batch
        oracle = CompiledPipeline(
            self.transformer,
            buckets=[probe_bucket],
            devices=self.devices[:1],
            precision="f32",
            name=f"{self.name}-f32-oracle",
        ).warmup(self.feature_shape, dtype=self._dtype)
        return check_precision_quality(
            oracle(X), mine, y=y, metric=metric, tolerance=tolerance,
            precision=self.precision,
        )

    # -- hot path ----------------------------------------------------------

    def _pick_replica_locked(self) -> _Replica:
        """Least-outstanding replica, ties broken round-robin (caller
        holds the lock)."""
        n = len(self.replicas)
        idx = _least_outstanding(
            n, self._rr, lambda i: self.replicas[i].outstanding
        )
        self._rr = (idx + 1) % n
        return self.replicas[idx]

    def _launch_chunk(
        self, chunk: np.ndarray, pin: Optional[int] = None,
        req_ids: Optional[Sequence[int]] = None,
    ) -> _Launched:
        """Pad one ≤max_batch chunk onto its bucket and launch it on a
        replica (``pin`` overrides the least-outstanding pick). Returns
        without waiting: JAX async dispatch hands back un-materialized
        device arrays. ``req_ids`` rides along for span attribution."""
        m = chunk.shape[0]
        b = bucket_for(m, self.ladder)
        if m != b:
            pad = np.broadcast_to(chunk[-1:], (b - m,) + chunk.shape[1:])
            chunk = np.concatenate([chunk, pad], axis=0)
        with self._lock:
            r = (
                self.replicas[pin] if pin is not None
                else self._pick_replica_locked()
            )
            ex = r.executables.get(b)
            if ex is None:  # cold bucket (warmup skipped): counted miss
                ex = self._compile_bucket_locked(r, b)
            r.outstanding += 1
            r.dispatches += 1
            # Gauge published under the lock: value capture and set stay
            # ordered, so concurrent launch/complete can't publish stale
            # readings out of order and leave the gauge stuck.
            self._out_gauges[r.index].set(r.outstanding)
        self._dispatch_counters.bump(f"d{r.device.id}")
        tr = self._tracer
        t0 = tr.now() if tr is not None else 0
        try:
            out = ex(chunk)
        except BaseException:
            # A failed launch (e.g. transient RESOURCE_EXHAUSTED) has no
            # _Launched record for abandon() to release — undo the slot
            # here or the replica reads busier forever.
            with self._lock:
                r.outstanding -= 1
                self._out_gauges[r.index].set(r.outstanding)
            raise
        serving_counters.record_call(b, m)
        return _Launched(r, out, m, b, t0, req_ids)

    def _release_slot(self, lc: _Launched) -> None:
        """Release one launched chunk's replica slot without touching its
        result (the abandon path — see ``_AsyncResult.abandon``)."""
        with self._lock:
            lc.replica.outstanding -= 1
            self._out_gauges[lc.replica.index].set(lc.replica.outstanding)

    def _complete_chunk(self, lc: _Launched):
        """Materialize one launched chunk: block on the transfer, slice to
        the real rows on host, release the replica slot, and close the
        ``serve.device`` span (launch → materialized) tagged with the
        device that served it."""
        # np.asarray blocks on the transfer, so latency measurements around
        # launch+complete see the true device time; slicing is host-side.
        try:
            out = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[: lc.m], lc.out
            )
        except BaseException:
            self._release_slot(lc)  # a failed chunk must not leak its slot
            raise
        with self._lock:
            lc.replica.outstanding -= 1
            self._out_gauges[lc.replica.index].set(lc.replica.outstanding)
        tr = self._tracer
        if tr is not None:
            attrs = dict(rows=lc.m, bucket=lc.b,
                         device=lc.replica.device.id,
                         replica=lc.replica.index)
            if lc.req_ids is not None:
                # The cross-thread link: which requests' rows this device
                # call carried — the journey reconstruction key.
                attrs["req_ids"] = list(lc.req_ids)
            tr.record("serve.device", "serving", lc.t0, **attrs)
        return out

    def call_async(
        self,
        X,
        replica: Optional[int] = None,
        window: Optional[int] = None,
        req_ids: Optional[Sequence[int]] = None,
    ) -> _AsyncResult:
        """Launch a batch without waiting for the device: returns an
        ``_AsyncResult`` whose ``wait()`` yields the numpy output.

        Chunks beyond the top bucket shard across the replica pool
        (least-outstanding); ``replica=i`` pins every chunk to one
        replica — the micro-batcher's dispatcher uses this so its
        in-flight window is attributable per replica. ``window`` bounds
        how many chunks ride async dispatch at once (default: the
        engine's per-replica in-flight window × the replicas in play).

        ``req_ids`` names the requests riding in this batch (the
        micro-batcher passes its coalesced group's ids so ``serve.device``
        spans link back to each request's journey); a direct engine call
        mints one fresh monotonic id for the whole batch."""
        if self.feature_shape is None:
            # Lazy warmup off the first request's signature: correct, but
            # the first-traffic latency pays the whole ladder. Call
            # warmup() ahead of traffic instead.
            self.warmup(np.asarray(X))
        t0 = time.perf_counter()
        X = np.asarray(X, dtype=self._dtype)
        if X.shape[1:] != self.feature_shape:
            raise ValueError(
                f"request feature shape {X.shape[1:]} != warmed shape "
                f"{self.feature_shape}; re-warm the pipeline for new traffic"
            )
        if X.shape[0] == 0:
            raise ValueError("cannot serve an empty batch")
        if replica is not None and not 0 <= replica < len(self.replicas):
            raise ValueError(
                f"replica {replica} out of range for a "
                f"{len(self.replicas)}-replica pool"
            )
        if window is None:
            window = self.inflight * (
                1 if replica is not None else len(self.replicas)
            )
        if req_ids is None:
            # Direct engine traffic gets an id too: one per batch — the
            # monotonic mint point for CompiledPipeline.__call__.
            req_ids = (next_request_id(),)
        return _AsyncResult(self, X, replica, window, t0, req_ids)

    def __call__(self, X):
        """Serve one batch synchronously: returns numpy, sliced to the
        real row count. The handle's wait() records the always-on
        ``serve.request_latency`` sample (boundaries match an external
        stopwatch around this call, so registry and bench percentiles
        agree)."""
        return self.call_async(X).wait()

    # -- offline data parallelism -----------------------------------------

    def apply_batches(
        self,
        batches,
        prefetch_depth: Optional[int] = None,
        window: Optional[int] = None,
    ):
        """Stream ``(X, labels-or-None)`` pairs (or bare batches) through
        the replica pool with a bounded async window: up to ``window``
        batches (default in-flight × replicas) are in flight at once, so
        out-of-core scoring overlaps N device calls with the PR-1
        prefetcher instead of serializing through one device. Yields
        ``(transformed, labels)`` in source order."""
        from keystone_tpu.loaders.stream import prefetched

        if window is None:
            window = self.inflight * len(self.replicas)
        window = max(1, int(window))
        pending: deque = deque()
        with prefetched(iter(batches), prefetch_depth) as src:
            for item in src:
                if isinstance(item, tuple) and len(item) == 2:
                    X, y = item
                else:
                    X, y = item, None
                pending.append((self.call_async(np.asarray(X)), y))
                if len(pending) >= window:
                    handle, y0 = pending.popleft()
                    yield handle.wait(), y0
            while pending:
                handle, y0 = pending.popleft()
                yield handle.wait(), y0

    def stats(self) -> dict:
        return {
            "name": self.name,
            "ladder": list(self.ladder),
            "precision": self.precision,
            # What the HBM planner chose (per-bucket planned bytes,
            # budget, headroom, trims) — or why it didn't run (pinned /
            # disabled / unpriced). None until warmup prices the plan.
            "plan": dict(self._planned) if self._planned else None,
            "devices": [d.id for d in self.devices],
            "inflight": self.inflight,
            "compile_count": self.compile_count,
            "compiles_by_bucket": dict(sorted(
                self.compiles_by_bucket.items()
            )),
            "warmup_seconds": self.warmup_seconds,
            "donate": self.donate,
            # Dispatch-balance evidence: chunks launched per replica. The
            # registry mirror is serve.dispatch[<name>].
            "replica_dispatches": {
                f"d{r.device.id}": r.dispatches for r in self.replicas
            },
            "replica_outstanding": {
                f"d{r.device.id}": r.outstanding for r in self.replicas
            },
            # Explicitly process-wide (every engine records into the one
            # registry histogram); per-engine latency needs one engine per
            # process or the trace's serve.device spans.
            "process_request_latency": request_latency.snapshot(),
        }


# ---------------------------------------------------------------------------
# PipelineService — request coalescing micro-batcher over the replica pool
# ---------------------------------------------------------------------------


class _Request:
    """One accepted request in the micro-batcher: payload + future +
    deadline, the monotonic request id minted at submit, the caller's
    SLA tier (None for direct service users — tier is what makes a
    request eligible for cross-tenant micro-batching), and the always-on
    flight-recorder journey record that follows it across the
    dispatcher/replica/completion threads."""

    __slots__ = ("x", "datum", "fut", "deadline", "t_sub", "rid", "rec",
                 "tier")

    def __init__(self, x, datum, fut, deadline, t_sub, rid, rec,
                 tier=None):
        self.x = x
        self.datum = datum
        self.fut = fut
        self.deadline = deadline
        self.t_sub = t_sub
        self.rid = rid
        self.rec = rec
        self.tier = tier


def _trace_attrs(rec) -> Dict[str, Any]:
    """The span-attr fragment carrying a request's wire trace id (noted
    on the journey record at submit): spread into every per-request
    tracer span so one trace id stitches daemon journey → service spans
    → offline export. Empty when the caller sent no trace context."""
    meta = rec.meta
    tid = meta.get("trace_id") if meta else None
    return {"trace_id": tid} if tid else {}


class _FlightRec:
    """A flush group launched on a replica, awaiting completion. Carries
    the bucket it padded onto and its launch stamp so the completion
    thread can feed launch→materialized device time to the capacity
    model."""

    __slots__ = ("live", "handle", "t_flush", "rows", "bucket", "t_launch")

    def __init__(self, live, handle, t_flush, rows, bucket=None,
                 t_launch=0):
        self.live = live
        self.handle = handle
        self.t_flush = t_flush
        self.rows = rows
        self.bucket = bucket
        self.t_launch = t_launch


class PipelineService:
    """Coalesces concurrent small requests into bucketed device calls,
    pipelined across the engine's replica pool.

    ``submit(x)`` returns a ``concurrent.futures.Future``. A background
    dispatcher drains the request queue: it takes the oldest request, then
    keeps absorbing queued requests until the flush would exceed
    ``max_rows`` or ``max_delay_ms`` has passed since the flush group
    opened, concatenates them into one batch, and launches it on the
    least-outstanding replica (round-robin on ties) WITHOUT waiting for
    the device — JAX async dispatch returns as soon as the call is
    enqueued, so the dispatcher immediately forms the next group while
    per-replica completion threads materialize results, slice them back
    per-request, and resolve the futures. A bounded in-flight window
    (``inflight`` / ``KEYSTONE_SERVE_INFLIGHT``, default 2 per replica)
    keeps the dispatcher from running unboundedly ahead. With one replica
    and window 1 the service runs the exact pre-pipelining serial flush
    loop (pinned by tests). Under load the delay never waits — the queue
    is non-empty, so flushes are back-to-back full buckets; the delay only
    bounds the latency a lone request pays waiting for company.

    Hardened for sustained overload (utils/reliability.py):

    - **Bounded pending queue.** At ``max_pending`` queued requests,
      ``submit`` fast-fails with ``QueueFullError`` instead of growing
      the queue — under 2× capacity, excess load becomes immediate
      rejections while accepted requests keep a bounded p99, rather than
      every request sliding down an unbounded-latency cliff.
    - **Per-request deadlines.** A request still queued past its deadline
      (per-submit ``deadline_ms``, default ``config.serve_deadline_ms``)
      fails its future with ``DeadlineExceeded`` before wasting a device
      call on an answer nobody is waiting for.
    - **Worker-death detection.** If the dispatcher thread dies (a bug,
      or the harness's ``worker_death`` site), the next ``submit`` fails
      the dead dispatcher's un-launched futures with ``WorkerDiedError``,
      restarts it, and the queue drains normally.
    - **Replica-death re-dispatch.** If a replica dies (the harness's
      ``replica_death`` site), its in-flight groups re-queue at the front
      of the pending queue and re-dispatch to the surviving replicas — no
      future is stranded. If every replica is dead the pool is revived.
    - **A close() that never strands a future.** ``close()`` drains by
      default (``drain=False`` rejects immediately); either way every
      future still unresolved when the workers are gone is failed with
      ``ServiceClosed`` — no caller ever blocks forever on ``result()``.

    Requires a warmed pipeline: warmup belongs before first traffic, not
    under it.
    """

    #: Upper bound on waiting for the worker to drain at close(): the
    #: satellite guarantee is "reject, never hang" — past this, leftover
    #: futures are failed instead of waited for.
    _CLOSE_JOIN_S = 30.0

    def __init__(
        self,
        compiled: CompiledPipeline,
        max_delay_ms: float = 2.0,
        max_rows: Optional[int] = None,
        max_pending: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        inflight: Optional[int] = None,
        name: Optional[str] = None,
        watchdog_ms: Optional[float] = None,
        flight_dir: Optional[str] = None,
        capacity=None,
    ):
        if compiled.feature_shape is None:
            raise RuntimeError(
                "PipelineService requires a warmed CompiledPipeline — call "
                "warmup() with the traffic's feature shape first"
            )
        self.compiled = compiled
        # `is None`, not truthiness: an explicit max_rows=0 must error.
        self.max_rows = int(
            compiled.max_batch if max_rows is None else max_rows
        )
        if self.max_rows < 1:
            raise ValueError(f"max_rows must be >= 1, got {self.max_rows}")
        self.max_delay = max_delay_ms / 1e3
        self.max_pending = int(
            max_pending if max_pending is not None else config.serve_max_pending
        )
        if self.max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {self.max_pending}")
        self.default_deadline_s = (
            deadline_ms if deadline_ms is not None else config.serve_deadline_ms
        ) / 1e3
        self.inflight_limit = int(
            config.serve_inflight if inflight is None else inflight
        )
        if self.inflight_limit < 1:
            raise ValueError(
                f"inflight must be >= 1, got {self.inflight_limit}"
            )
        self.name = name or f"svc{next(_service_seq)}"
        self._plan = active_plan()
        self._tracer = active_tracer()  # resolved once per service
        # The learned capacity model (workflow/capacity.CapacityModel, or
        # None = every capacity consumer disabled, bit-identical to
        # PR-19): prices deadline-aware micro-batching in _loop and is
        # fed per-batch device time from the completion threads. The
        # DAEMON owns fitting it (journeys, arrivals); the service only
        # consults and feeds it.
        self._capacity = capacity
        # Per-SERVICE latency/depth (the process-global registry metrics
        # aggregate every service; two services in one process must not
        # read each other's numbers off their own stats()).
        self._e2e = LatencyHistogram()
        self._depth_max = 0
        # Per-instance registry metrics: namespaced on the service name so
        # two services never get-or-create (and overwrite) the same gauge.
        self._queue_gauge = metrics_registry.gauge(
            f"serve.queue_depth[{self.name}]"
        )
        self._inflight_gauge = metrics_registry.gauge(
            f"serve.inflight[{self.name}]"
        )
        # Outcome-tagged request accounting (ok / expired / rejected /
        # error / closed): overload analyses read rejected+expired from
        # the registry instead of being blind to failed work.
        self._outcomes = metrics_registry.counters(
            f"serve.requests[{self.name}]"
        )
        # The black box: always-on journey ring + error events, dumped on
        # worker/replica death, deadline storms, watchdog stalls, and
        # debug_dump(). context=self.stats runs at dump time from an
        # UNLOCKED point (poll discipline — see utils/flight_recorder.py).
        self._flight = FlightRecorder(
            self.name, directory=flight_dir, context=self.stats
        )
        # Deadline-storm trigger state: perf_counter stamps of the most
        # recent serve_storm_expired expiries; full deque inside one
        # second = storm. Written only via _fail_expired (one root).
        self._storm_n = int(config.serve_storm_expired)
        self._expired_times: deque = deque(maxlen=max(1, self._storm_n))
        # Stall-watchdog state: last time the dispatch side made progress
        # (group popped or completed). Written under self._lock from the
        # dispatcher, completers, and the watchdog itself.
        self._watchdog_s = (
            config.serve_watchdog_ms if watchdog_ms is None else watchdog_ms
        ) / 1e3
        self._last_progress_ns = time.perf_counter_ns()
        self._stalls = 0
        self._wd_stop = threading.Event()
        self._pending: deque = deque()
        self._inflight: list = []  # requests popped but not yet launched
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closed = False
        self.requests = 0
        self.batches_run = 0
        self.rows_served = 0
        self.rejected = 0
        self.expired = 0
        self.worker_restarts = 0
        self.replica_deaths = 0
        self.replica_revivals = 0
        # Replica-pool dispatch state. Engines without a pool (or wrapped
        # engines that hide call_async) serve through the serial path.
        replicas = getattr(compiled, "replicas", None)
        self._n_replicas = len(replicas) if replicas else 1
        self._pipelined = (
            (self._n_replicas > 1 or self.inflight_limit > 1)
            and callable(getattr(compiled, "call_async", None))
        )
        self._rr = 0
        self._outstanding = [0] * self._n_replicas
        self._dead = [False] * self._n_replicas
        # Planned drains (the hot-swap handoff): a retired replica is
        # dead-by-design — its in-flight groups re-queued to survivors
        # via the replica-death machinery — and stays down (revival
        # skips it) until unretire_replicas() or close().
        self._retired = [False] * self._n_replicas
        # One lock, TWO wait-sets: the dispatcher waits on self._cv
        # (pending work / free slots), each replica's completion thread on
        # its own condition — a submit's notify() must never be consumed
        # by a completer while the dispatcher sleeps on (lost wakeup).
        self._ccvs = [
            threading.Condition(self._lock)
            for _ in range(self._n_replicas)
        ]
        self._cqueues: list = [deque() for _ in range(self._n_replicas)]
        self._cq_active: list = [None] * self._n_replicas
        self._completers: list = []
        # Worker first: completion threads poll self._worker liveness for
        # their exit condition, so it must exist before they start.
        self._worker = self._spawn_worker()
        if self._pipelined:
            self._completers = [
                self._spawn_completer(r) for r in range(self._n_replicas)
            ]
        self._watchdog: Optional[threading.Thread] = None
        if self._watchdog_s > 0:
            self._watchdog = self._spawn_watchdog()

    def _spawn_worker(self) -> threading.Thread:
        t = threading.Thread(
            target=self._loop, name="keystone-serve", daemon=True
        )
        t.start()
        return t

    def _spawn_watchdog(self) -> threading.Thread:
        t = threading.Thread(
            target=self._watchdog_loop, name="keystone-serve-watchdog",
            daemon=True,
        )
        t.start()
        return t

    def _spawn_completer(self, r: int) -> threading.Thread:
        t = threading.Thread(
            target=self._complete_loop, args=(r,),
            name=f"keystone-serve-complete-{r}", daemon=True,
        )
        t.start()
        return t

    # -- client side -------------------------------------------------------

    def queue_depth(self) -> int:
        """Pending (queued, un-popped) request count — the occupancy input
        to predicted-deadline admission. Deliberately lock-free: a deque
        ``len`` is atomic under the GIL, and the consumer (the daemon's
        admission path) only needs a load estimate, not a linearizable
        read."""
        return len(self._pending)

    def submit(self, x, deadline_ms: Optional[float] = None,
               trace_id: Optional[str] = None,
               tier: Optional[str] = None) -> Future:
        """Queue one request: a single example (feature-shaped) or a small
        batch (leading row axis). The future resolves to the transformed
        example/batch respectively — or fails with ``QueueFullError``
        (raised here, synchronously), ``DeadlineExceeded``,
        ``WorkerDiedError``, or ``ServiceClosed``; it is never stranded.

        ``deadline_ms`` overrides the service default for this request;
        0/None with a 0 default means no deadline. ``trace_id`` is the
        caller's wire trace context (the daemon threads its journey's id
        through here): noted on this request's journey record and
        stamped onto every tracer span it produces. ``tier`` is the
        caller's SLA tier ("gold" / "best_effort"; the daemon threads the
        admitted tenant's tier): it gates deadline-aware cross-tenant
        micro-batching — untiered requests (direct service users) neither
        anchor nor ride a micro-batch, so the pre-capacity batching
        behavior is preserved bit-identically for them."""
        # lint: ok(KL007) coerces the caller's HOST request payload; no device value is synced
        x = np.asarray(x, dtype=self.compiled.dtype)
        datum = x.shape == self.compiled.feature_shape
        if datum:
            x = x[None, ...]
        if x.shape[1:] != self.compiled.feature_shape:
            raise ValueError(
                f"request shape {x.shape} does not match served feature "
                f"shape {self.compiled.feature_shape}"
            )
        deadline_s = (
            deadline_ms / 1e3 if deadline_ms is not None
            else self.default_deadline_s
        )
        deadline = time.monotonic() + deadline_s if deadline_s > 0 else None
        fut: Future = Future()
        # The request's identity for causal tracing and the flight
        # recorder: minted HERE, before any queueing decision, so even a
        # rejected request has an id in the error-event ring.
        rid = next_request_id()
        # Lifecycle clock: queued → flushed → device → resolved spans and
        # the e2e histogram all measure from this submit timestamp.
        t_sub = time.perf_counter_ns()
        with self._cv:
            if self._closed:
                raise ServiceClosed("PipelineService is closed")
            self._ensure_worker_locked()
            self._revive_dead_locked()
            if len(self._pending) >= self.max_pending:
                # Fast-fail backpressure: reject NOW, at zero device cost,
                # instead of queueing latency the client will time out on.
                self.rejected += 1
                reliability_counters.bump("requests_rejected")
                self._outcomes.bump("rejected")
                self._flight.error(
                    "rejected",
                    f"queue at capacity ({self.max_pending} pending)",
                    rid=rid,
                )
                if self._tracer is not None:
                    extra = {"trace_id": trace_id} if trace_id else {}
                    self._tracer.instant(
                        "serve.rejected", "serving", rows=int(x.shape[0]),
                        req_id=rid, **extra,
                    )
                raise QueueFullError(
                    f"serving queue at capacity ({self.max_pending} "
                    "pending); request rejected fast"
                )
            if not self._pending:
                # Queue transitions empty -> non-empty: re-arm the stall
                # watchdog. Without this, the first request after an idle
                # stretch longer than the watchdog window would read as a
                # "stall" (stale progress stamp + non-empty queue) and
                # dump the black box over a perfectly healthy service.
                self._last_progress_ns = time.perf_counter_ns()
            rec = self._flight.start(rid, int(x.shape[0]))
            if trace_id:
                rec.note(trace_id=trace_id)
            self._pending.append(
                _Request(x, datum, fut, deadline, t_sub, rid, rec, tier)
            )
            self.requests += 1
            depth = len(self._pending)
            self._queue_gauge.set(depth)
            if depth > self._depth_max:
                self._depth_max = depth
            self._cv.notify()
        # Safe (unlocked) point: flush any dump a death/storm detection
        # marked pending while the lock was held.
        self._flight.poll()
        return fut

    def _ensure_worker_locked(self) -> None:
        """Detect a dead dispatcher (caller holds the lock): fail whatever
        it had popped but not launched — those futures can never resolve —
        and restart it so the queued work drains. Groups already launched
        belong to the completion threads and survive the restart."""
        if self._worker.is_alive():
            return
        dead = [rq for rq in self._inflight if not rq.fut.done()]
        for rq in dead:
            if self._resolve(
                rq.fut, exc=WorkerDiedError(
                    "serving worker died while this request was in flight"
                )
            ):
                rq.rec.finish("worker_death")
        if dead:
            reliability_counters.bump(
                "futures_failed_on_worker_death", len(dead)
            )
        self._inflight = []
        self.worker_restarts += 1
        reliability_counters.bump("worker_restarts")
        self._flight.error(
            "worker_death",
            f"dispatcher died; {len(dead)} in-flight future(s) failed",
        )
        self._flight.note_dump("worker_death")
        logger.warning(
            "PipelineService worker died; restarting (restart #%d, %d "
            "in-flight futures failed)", self.worker_restarts, len(dead),
        )
        self._worker = self._spawn_worker()

    # -- worker side -------------------------------------------------------

    @staticmethod
    def _expired(rq: _Request) -> bool:
        return rq.deadline is not None and time.monotonic() > rq.deadline

    def _fail_expired(self, rq: _Request) -> None:
        if not self._resolve(
            rq.fut,
            exc=DeadlineExceeded(
                "request deadline passed before the device ran it"
            ),
        ):
            return  # another path got there first: don't double-count
        rq.rec.finish("expired")
        self.expired += 1
        reliability_counters.bump("deadline_expired")
        self._outcomes.bump("expired")
        # Deadline-storm trigger: a full window of expiries inside one
        # second marks a flight-recorder dump pending (flushed at the
        # next unlocked poll point — this method can run under the lock).
        if self._storm_n > 0:
            now = time.perf_counter()
            self._expired_times.append(now)
            if (
                len(self._expired_times) == self._storm_n
                and now - self._expired_times[0] <= 1.0
            ):
                # Window cleared on trigger: one sustained storm yields
                # one error event per full window, not one per expiry —
                # the last-N error ring must keep the OTHER events that
                # explain the incident, not 256 copies of this one.
                self._expired_times.clear()
                self._flight.error(
                    "deadline_storm",
                    f"{self._storm_n} requests expired within 1s",
                    rid=rq.rid,
                )
                self._flight.note_dump("deadline_storm")
        if self._tracer is not None:
            self._tracer.record(
                "serve.request", "serving", rq.t_sub, outcome="expired",
                rows=int(rq.x.shape[0]), req_id=rq.rid,
                **_trace_attrs(rq.rec),
            )
            # An expiry IS a latency breach: keep its span tree (scan
            # bounded to the request's lifetime — this runs under the
            # dispatch lock during exactly the storms it instruments).
            self._tracer.retain_request(rq.rid, since_ns=rq.t_sub)

    def _filter_expired(self, group) -> list:
        """Deadlines re-checked at flush time: a request can expire while
        the group waits max_delay for company."""
        live = []
        for rq in group:
            if self._expired(rq):
                self._fail_expired(rq)
            else:
                live.append(rq)
        return live

    def _loop(self):
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if not self._pending and self._closed:
                    return
                if self._plan is not None and self._plan.check("worker_death"):
                    # Die like a crashed thread would: queued entries stay
                    # pending (the restarted worker serves them); only a
                    # group already popped would be lost, and the restart
                    # path fails those futures explicitly.
                    raise WorkerDiedError(
                        "injected worker death (KEYSTONE_FAULTS worker_death)"
                    )
                group: list = []
                rows = 0
                flush_at: Optional[float] = None
                while True:
                    if self._pending:
                        rq = self._pending[0]
                        if self._expired(rq):
                            # Expired in queue: fail it before it costs a
                            # device call, keep coalescing.
                            self._pending.popleft()
                            self._fail_expired(rq)
                            continue
                        nxt_rows = rq.x.shape[0]
                        if group and rows + nxt_rows > self.max_rows:
                            break
                        group.append(self._pending.popleft())
                        rows += nxt_rows
                        if flush_at is None:
                            flush_at = time.monotonic() + self.max_delay
                        if rows >= self.max_rows:
                            break
                        continue
                    if not group:
                        break  # everything pending had expired: re-wait
                    remaining = flush_at - time.monotonic()
                    if remaining <= 0 or self._closed:
                        break
                    self._cv.wait(remaining)
                if group and self._capacity is not None:
                    # Deadline-aware cross-tenant micro-batching: fill
                    # this group's padding slack with best-effort work
                    # the FIFO scan above skipped past. No-op without a
                    # capacity model (bit-identical PR-19 batching).
                    rows = self._microbatch_fill_locked(group, rows)
                # Gauge updated even when everything popped had expired
                # (group empty): the queue really did shrink. Either way
                # the dispatcher made progress — re-arm the stall
                # watchdog (we hold the lock).
                self._queue_gauge.set(len(self._pending))
                self._last_progress_ns = time.perf_counter_ns()
                if group:
                    self._inflight = list(group)
                    if not self._pipelined:
                        self._inflight_gauge.set(len(group))
                    now_ns = time.perf_counter_ns()
                    for rq in group:
                        rq.rec.stamp("flushed")
                    if self._tracer is not None:
                        # Queue residency per request: submit →
                        # flush-group pop.
                        for rq in group:
                            self._tracer.record(
                                "serve.queued", "serving", rq.t_sub, now_ns,
                                rows=int(rq.x.shape[0]), req_id=rq.rid,
                                **_trace_attrs(rq.rec),
                            )
            if not group:
                # Everything popped had expired: still a safe unlocked
                # point — an expiry storm detected just above must dump
                # without waiting for the next group or watchdog tick.
                self._flight.poll()
                continue
            if self._pipelined:
                self._dispatch(group)
            else:
                self._flush(group)
                with self._cv:
                    self._inflight = []
                    self._inflight_gauge.set(0)
            # Between groups, lock released: flush any dump marked
            # pending while this iteration held the lock (e.g. a
            # deadline storm detected during coalescing).
            self._flight.poll()

    def _microbatch_fill_locked(self, group: list, rows: int) -> int:
        """Deadline-aware cross-tenant micro-batching (caller holds the
        lock; the flush group is formed). The group's rows pad up to the
        bucket rung anyway — filling those pad rows with REAL best-effort
        work is free device time — so when the group anchors gold-tier
        work and the capacity model is warm, scan the pending queue PAST
        the FIFO head for best-effort requests that (a) fit the padding
        slack and (b) the model predicts still make both their own
        deadline and the gold group's earliest deadline at the rung's p99
        device time. The bucket never changes, so the gold group's device
        call is the same executable on the same shape — gold latency is
        unchanged by construction, and the model check is the
        belt-and-braces contract the bench gates. Every coalesce is
        counted (``capacity.microbatches_formed`` / ``_rows_filled``) and
        journey-attributed (``microbatched`` meta on the rider's record).
        Cold model = counted skip, bit-identical batching. Returns the
        (possibly grown) group row count."""
        model = self._capacity
        if not any(rq.tier == "gold" for rq in group):
            return rows
        b = bucket_for(rows, getattr(self.compiled, "ladder", ()))
        if b is None or b <= rows:
            return rows  # oversize or exact-fit group: no slack to fill
        if not self._pending:
            return rows
        if not model.ready():
            capacity_counters.bump("model_cold_skips")
            return rows
        batch_ms = model.predict_batch_ms(b, q=0.99)
        if batch_ms is None:
            capacity_counters.bump("model_cold_skips")
            return rows
        now = time.monotonic()
        eta = now + batch_ms / 1e3
        gold_deadlines = [
            rq.deadline for rq in group
            if rq.tier == "gold" and rq.deadline is not None
        ]
        if gold_deadlines and eta > min(gold_deadlines):
            return rows  # the anchor itself is at risk: don't add riders
        slack = b - rows
        filled = 0
        kept: deque = deque()
        while self._pending and slack > 0:
            rq = self._pending.popleft()
            n = int(rq.x.shape[0])
            if (
                rq.tier == "best_effort"
                and n <= slack
                and not self._expired(rq)
                and (rq.deadline is None or eta <= rq.deadline)
            ):
                rq.rec.note(microbatched=True, microbatch_bucket=b)
                group.append(rq)
                slack -= n
                filled += n
            else:
                kept.append(rq)
        while kept:  # skipped requests go back, order preserved
            self._pending.appendleft(kept.pop())
        if filled:
            capacity_counters.bump("microbatches_formed")
            capacity_counters.bump("microbatch_rows_filled", filled)
            rows += filled
        return rows

    @staticmethod
    def _resolve(fut: Future, value=None, exc=None) -> bool:
        """Resolve a future, tolerating client-side cancellation and
        already-resolved futures (a close()-swept group whose stuck
        completer later finishes): set_result on those raises
        InvalidStateError, which must not poison the rest of the
        coalesced group. Returns whether THIS call won the resolution —
        outcome counters key off it so one request is never counted
        twice (e.g. both 'closed' and 'ok')."""
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(value)
            return True
        except InvalidStateError:
            return False

    @staticmethod
    def _concat(live):
        if len(live) == 1:
            return live[0].x
        return np.concatenate([rq.x for rq in live], axis=0)

    def _maybe_retain(self, tr, rq: _Request, seconds: float) -> None:
        """Tail sampling: keep the full span tree of a request whose
        end-to-end latency breached the threshold — an explicit
        ``config.trace_tail_ms``, or (at 0 = auto) the running p99 of
        this service's always-on e2e histogram once it has enough
        samples. Negative disables. Only ever called with tracing armed;
        the disabled tracer costs nothing here."""
        thr_ms = config.trace_tail_ms
        if thr_ms < 0:
            return
        if thr_ms == 0:
            if self._e2e.count < TAIL_MIN_COUNT:
                return
            p99 = self._e2e.percentile(99)
            if p99 is None:
                return
            thr_ms = p99 * 1e3
        if seconds * 1e3 >= thr_ms:
            tr.retain_request(rq.rid, since_ns=rq.t_sub)

    def _deliver(self, live, out, tr, t_flush, rows) -> None:
        """Slice one flush's output back per request and resolve the
        futures (the completion path, shared by the serial flush and the
        per-replica completion threads)."""
        off = 0
        retains = []
        for rq in live:
            m = rq.x.shape[0]
            piece = jax.tree_util.tree_map(
                lambda a, o=off, m=m: a[o : o + m], out
            )
            if rq.datum:
                piece = jax.tree_util.tree_map(lambda a: a[0], piece)
            off += m
            # Latency captured BEFORE resolving (set_result runs client
            # done-callbacks inline; their cost must not count as serving
            # latency) but recorded only when this path actually resolved
            # the future — a request another path already failed (close,
            # worker death) must not double-count as 'ok'.
            now_ns = time.perf_counter_ns()
            if not self._resolve(rq.fut, value=piece):
                continue
            rq.rec.finish("ok")
            sec = (now_ns - rq.t_sub) / 1e9
            self._e2e.record(sec)
            e2e_latency.record(sec)
            self._outcomes.bump("ok")
            if tr is not None:
                tr.record(
                    "serve.request", "serving", rq.t_sub, now_ns,
                    outcome="ok", rows=m, req_id=rq.rid,
                    **_trace_attrs(rq.rec),
                )
                retains.append((rq, sec))
        if tr is not None:
            tr.record(
                "serve.flush", "serving", t_flush,
                requests=len(live), rows=rows,
                req_ids=[rq.rid for rq in live],
            )
            # Tail-sample AFTER the group's serve.flush span is in the
            # ring, or retained trees would permanently lack the flushed
            # leg of the journey once the ring churns.
            for rq, sec in retains:
                self._maybe_retain(tr, rq, sec)

    def _fail_group(self, live, e, tr) -> None:
        """Fail every unresolved future in a flush group, keep serving."""
        failed = []
        for rq in live:
            if not rq.fut.done() and self._resolve(rq.fut, exc=e):
                rq.rec.finish(type(e).__name__)
                failed.append(rq.rid)
                self._outcomes.bump("error")
                if tr is not None:
                    tr.record(
                        "serve.request", "serving", rq.t_sub,
                        outcome=type(e).__name__, rows=int(rq.x.shape[0]),
                        req_id=rq.rid, **_trace_attrs(rq.rec),
                    )
                    # Failures keep their span trees like latency
                    # breaches do: the error IS the interesting tail.
                    tr.retain_request(rq.rid, since_ns=rq.t_sub)
        if failed:
            self._flight.error(
                type(e).__name__,
                f"flush group failed ({len(failed)} request(s)): {e}",
                rid=failed[0],
            )

    def _flush(self, group):
        """Serial flush (one replica, window 1): launch AND materialize
        inline — byte-for-byte the pre-pipelining behavior."""
        live = self._filter_expired(group)
        if not live:
            return
        tr = self._tracer
        t_flush = tr.now() if tr is not None else 0
        try:
            X = self._concat(live)
            b = bucket_for(X.shape[0], getattr(self.compiled, "ladder", ()))
            for rq in live:
                rq.rec.dispatched(0, b)
            t_dev = time.perf_counter_ns()
            out = self.compiled(X)
            if self._capacity is not None and b is not None:
                # Launch→materialized device time: the per-bucket price
                # predicted-deadline admission and micro-batching consult.
                self._capacity.observe_batch(
                    b, int(X.shape[0]),
                    (time.perf_counter_ns() - t_dev) / 1e6,
                )
            # Under the lock even though the serial path has no completer
            # threads: these counters are ALSO bumped from _complete_loop
            # on the pipelined path, and the lock discipline (keystone-lint
            # KL001) is per-attribute, not per-configuration. Post-device,
            # so the one acquisition per flush is off the hot path.
            with self._lock:
                self.batches_run += 1
                self.rows_served += X.shape[0]
            self._deliver(live, out, tr, t_flush, int(X.shape[0]))
        # lint: broad-ok any flush failure becomes the group's futures' exception; the worker must keep serving
        except Exception as e:  # fail the whole flush group, keep serving
            self._fail_group(live, e, tr)

    # -- pipelined dispatch ------------------------------------------------

    def _pick_slot_locked(self) -> Optional[int]:
        """A live replica with in-flight room, least-outstanding first and
        round-robin on ties — or None when the window is full everywhere
        (caller holds the lock)."""
        idx = _least_outstanding(
            self._n_replicas,
            self._rr,
            self._outstanding.__getitem__,
            lambda i: (
                not self._dead[i]
                and self._outstanding[i] < self.inflight_limit
            ),
        )
        if idx is not None:
            self._rr = (idx + 1) % self._n_replicas
        return idx

    def _dispatch(self, group):
        """Launch one flush group on a replica without waiting for the
        device; the replica's completion thread resolves the futures."""
        live = self._filter_expired(group)
        tr = self._tracer
        if not live:
            with self._cv:
                self._inflight = []
                self._cv.notify_all()
            return
        with self._cv:
            while True:
                r = self._pick_slot_locked()
                if r is not None:
                    break
                self._revive_if_all_dead_locked()
                r = self._pick_slot_locked()
                if r is not None:
                    break
                # Timed wait: a completion notifies _cv when a slot frees,
                # but the timeout keeps the revive check live regardless.
                self._cv.wait(0.1)
            self._outstanding[r] += 1
            self._inflight_gauge.set(sum(self._outstanding))
        # Everything between the slot claim and the completer hand-off
        # runs under one try: an exception here (concat OOM, launch
        # failure) must release the slot and fail the group, never kill
        # the dispatcher with the slot still counted — leaked slots
        # shrink the window forever and the restart path can't see them.
        handle = None
        t_flush = 0
        rows = 0
        b = None
        t_launch = 0
        try:
            # Deadlines re-checked AFTER the slot wait: under overload
            # the window can hold a group long enough to expire it, and
            # the PR-3 contract is that expired requests fail BEFORE the
            # device call.
            live = self._filter_expired(live)
            if live:
                X = self._concat(live)
                rows = int(X.shape[0])
                t_flush = tr.now() if tr is not None else 0
                # The service's window also bounds the chunk-launch depth
                # of a multi-chunk (oversize) group: one knob, one value.
                # req_ids thread the coalesced requests' identities into
                # the engine so serve.device spans link back to them.
                handle = self.compiled.call_async(
                    X, replica=r, window=self.inflight_limit,
                    req_ids=[rq.rid for rq in live],
                )
                t_launch = time.perf_counter_ns()
                b = bucket_for(rows, getattr(self.compiled, "ladder", ()))
                for rq in live:
                    rq.rec.dispatched(r, b)
        # lint: broad-ok concat/launch failure of any kind fails the group's futures; the dispatcher must survive
        except Exception as e:
            self._fail_group(live, e, tr)
            handle = None
        if handle is None:  # expired-out or failed: slot goes back
            with self._cv:
                self._outstanding[r] = max(0, self._outstanding[r] - 1)
                self._inflight_gauge.set(sum(self._outstanding))
                self._inflight = []
                self._cv.notify_all()
            return
        rec = _FlightRec(live, handle, t_flush, rows, b, t_launch)
        with self._cv:
            if self._dead[r]:
                # The replica died between the slot pick and this enqueue
                # (its completer already drained the queue and exited):
                # abandon the launched work and re-queue the group at the
                # pending front for the survivors — appending to the dead
                # queue would strand every future in it. The kill path
                # already zeroed outstanding[r].
                abandon = getattr(handle, "abandon", None)
                if abandon is not None:
                    abandon()
                for rq in reversed(live):
                    rq.rec.stamp("requeued")
                    self._pending.appendleft(rq)
                reliability_counters.bump("serve_groups_redispatched")
                self._queue_gauge.set(len(self._pending))
                self._inflight = []
            else:
                self._cqueues[r].append(rec)
                self._inflight = []
                self._ccvs[r].notify()

    def _complete_loop(self, r: int):
        """Per-replica completion thread: materialize launched groups in
        order, deliver results, release the in-flight slot. Checks the
        ``replica_death`` fault site per group — a killed replica
        re-queues its in-flight groups for the survivors and exits."""
        while True:
            with self._ccvs[r]:
                while not self._cqueues[r]:
                    if self._dead[r]:
                        return
                    if self._closed and not self._worker.is_alive():
                        return
                    # Timed wait: dispatch notifies this replica's own
                    # condition; the timeout re-checks liveness/closure.
                    self._ccvs[r].wait(0.1)
                if self._dead[r]:
                    return
                if self._plan is not None and self._plan.check(
                    "replica_death"
                ):
                    self._kill_replica_locked(r)
                    rec = None
                else:
                    rec = self._cqueues[r].popleft()
                    self._cq_active[r] = rec
            if rec is None:
                # Killed: the dump marked pending under the lock flushes
                # here, from this dying thread's unlocked tail.
                self._flight.poll()
                return
            tr = self._tracer
            try:
                out = rec.handle.wait()
            except Exception as e:  # lint: broad-ok device failure of any kind becomes the group's futures' exception
                out = None
                self._fail_group(rec.live, e, tr)
            if (
                out is not None
                and self._capacity is not None
                and rec.bucket is not None
            ):
                # Launch→materialized device time for the capacity
                # model's per-bucket price (admission + micro-batching).
                self._capacity.observe_batch(
                    rec.bucket, rec.rows,
                    (time.perf_counter_ns() - rec.t_launch) / 1e6,
                )
            if out is not None:
                try:
                    with self._lock:
                        self.batches_run += 1
                        self.rows_served += rec.rows
                    self._deliver(rec.live, out, tr, rec.t_flush, rec.rows)
                except Exception as e:  # lint: broad-ok never die with futures in hand
                    self._fail_group(rec.live, e, tr)
            with self._cv:
                self._cq_active[r] = None
                # Clamped: a concurrent kill+revive zeroes the count while
                # this group was still in flight.
                self._outstanding[r] = max(0, self._outstanding[r] - 1)
                self._inflight_gauge.set(sum(self._outstanding))
                # A completion is dispatch progress: re-arm the watchdog.
                self._last_progress_ns = time.perf_counter_ns()
                self._cv.notify_all()
            # Group boundary = a safe unlocked point for pending dumps.
            self._flight.poll()

    def _kill_replica_locked(self, r: int, retire: bool = False) -> None:
        """Mark replica r dead and re-queue its in-flight groups at the
        FRONT of the pending queue, order-preserved, so the surviving
        replicas re-dispatch them — zero stranded futures (caller holds
        the lock; the launched device work is abandoned, which is safe:
        the serve chain is pure). ``retire=True`` is the PLANNED variant
        (hot-swap drain): same re-queue machinery, but accounted as a
        retirement — no death counters, no forensic dump over a healthy
        handoff."""
        self._dead[r] = True
        recs = list(self._cqueues[r])
        self._cqueues[r].clear()
        entries = [rq for rec in recs for rq in rec.live]
        for rec in recs:
            # Release the engine-level replica slots of the abandoned
            # launches, or least-outstanding dispatch (direct calls,
            # apply_batches) would see the dead replica as busy forever.
            abandon = getattr(rec.handle, "abandon", None)
            if abandon is not None:
                abandon()
        for rq in reversed(entries):
            # The journey shows the detour: dispatched onto the dead
            # replica, re-queued, then dispatched again on a survivor.
            rq.rec.stamp("requeued")
            self._pending.appendleft(rq)
        self._outstanding[r] = 0
        if retire:
            reliability_counters.bump("serve_replicas_retired")
            logger.info(
                "PipelineService %s: replica %d retired for handoff; %d "
                "in-flight group(s) (%d request(s)) re-dispatched to "
                "survivors", self.name, r, len(recs), len(entries),
            )
        else:
            self.replica_deaths += 1
            reliability_counters.bump("replica_deaths")
            self._flight.error(
                "replica_death",
                f"replica {r} died; {len(entries)} request(s) re-queued",
                rid=entries[0].rid if entries else None,
            )
            self._flight.note_dump("replica_death")
            logger.warning(
                "PipelineService %s: replica %d died; %d in-flight "
                "group(s) (%d request(s)) re-dispatched to survivors",
                self.name, r, len(recs), len(entries),
            )
        if recs:
            reliability_counters.bump(
                "serve_groups_redispatched", len(recs)
            )
        self._queue_gauge.set(len(self._pending))
        self._inflight_gauge.set(sum(self._outstanding))
        self._cv.notify_all()

    def retire_replica(self, r: int) -> bool:
        """Planned drain of one replica — the hot-swap handoff primitive.

        Re-queues the replica's in-flight groups onto the survivors (the
        replica-death machinery, accounted as a retirement) and keeps it
        down until :meth:`unretire_replicas`. Refuses (returns False) on
        the serial path, on an already-retired replica, or when it would
        take down the LAST live replica — the outgoing generation must
        keep answering until its successor takes over."""
        if not self._pipelined:
            return False
        if not 0 <= r < self._n_replicas:
            raise ValueError(
                f"replica {r} out of range for a {self._n_replicas}-replica "
                "service"
            )
        with self._cv:
            if self._closed or self._retired[r]:
                return False
            live = [
                i for i in range(self._n_replicas)
                if not self._dead[i] and not self._retired[i]
            ]
            if live == [r] or not live:
                return False  # never retire the last live replica
            self._retired[r] = True
            if not self._dead[r]:
                self._kill_replica_locked(r, retire=True)
        # Safe unlocked point for any dump marked while the lock was held.
        self._flight.poll()
        return True

    def unretire_replicas(self, indices) -> None:
        """Roll back planned drains (an aborted hot-swap): the named
        replicas become revivable again and are revived immediately."""
        with self._cv:
            for i in indices:
                self._retired[i] = False
            if not self._closed:
                self._revive_dead_locked()

    def _revive_dead_locked(self) -> None:
        """Restart any dead replica (caller holds the lock): executables
        are intact — death is a thread-level condition — so a fresh
        completion thread restores it. Called at the next ``submit`` (the
        same detection point as worker death), so a partially dead pool
        heals instead of serving at reduced capacity forever. Retired
        replicas stay down — their drain was deliberate."""
        for i in range(self._n_replicas):
            if not self._dead[i] or self._retired[i]:
                continue
            self._dead[i] = False
            self._completers[i] = self._spawn_completer(i)
            self.replica_revivals += 1
            reliability_counters.bump("replica_revivals")
            logger.warning(
                "PipelineService %s: replica %d revived", self.name, i,
            )

    def _revive_if_all_dead_locked(self) -> None:
        """The dispatcher's fallback when NO replica is eligible (caller
        holds the lock): with every replica dead and no submit arriving
        to heal the pool, revive it here so already-queued work drains
        (retired replicas stay down; at least one replica is always
        unretired, by retire_replica's last-live guard)."""
        if not self._dead or not all(self._dead):
            return
        self._revive_dead_locked()

    # -- stall watchdog + forensics ----------------------------------------

    def _watchdog_loop(self):
        """Stall watchdog: a non-empty pending queue that has made no
        dispatch progress (no group popped, no completion) for
        ``KEYSTONE_WATCHDOG_MS`` bumps the ``serve.stalls`` counter and
        dumps the flight recorder — turning a silent hang into a counter
        an operator can alert on plus a post-mortem artifact naming
        exactly which requests were stuck where. Each tick is also a
        guaranteed unlocked flush point for dumps other triggers marked
        pending (so a death with no follow-up traffic still dumps)."""
        interval = max(self._watchdog_s / 4.0, 0.05)
        while True:
            if self._wd_stop.wait(interval):
                return
            self._flight.poll()
            with self._lock:
                pending = len(self._pending)
                stalled_s = (
                    time.perf_counter_ns() - self._last_progress_ns
                ) / 1e9
                if not pending or stalled_s < self._watchdog_s:
                    continue
                # Re-arm before dumping: one stall = one dump per
                # watchdog interval, not one per tick.
                self._last_progress_ns = time.perf_counter_ns()
                self._stalls += 1
            stall_counters.bump(self.name)
            reliability_counters.bump("serve_stalls")
            self._flight.error(
                "stall",
                f"{pending} pending request(s), no dispatch progress for "
                f"{stalled_s * 1e3:.0f}ms",
            )
            logger.warning(
                "PipelineService %s: watchdog stall — %d pending, no "
                "dispatch progress for %.0fms; dumping flight recorder",
                self.name, pending, stalled_s * 1e3,
            )
            self._flight.dump("stall")

    def debug_dump(self, path: Optional[str] = None) -> Optional[str]:
        """Dump the flight recorder NOW (no rate limit): every journey
        record still in the ring, the last-N error events, and this
        service's ``stats()`` — the on-demand post-mortem. Returns the
        path written."""
        return self._flight.dump("debug", path=path, force=True)

    # -- lifecycle ---------------------------------------------------------

    def close(self, drain: bool = True, join_s: Optional[float] = None):
        """Stop the service without stranding a single future.

        ``drain=True`` (default) lets the workers serve what is already
        queued and in flight, then joins them; ``drain=False`` rejects
        queued requests immediately with ``ServiceClosed``. In BOTH modes,
        any future still unresolved once the workers are gone — queued
        behind a dead worker, in flight when the join timed out — is
        failed with ``ServiceClosed`` rather than left for a caller to
        block on forever. An EXPLICIT ``join_s`` bounds the TOTAL drain
        wait — one deadline shared across every thread join, not per
        thread, so a wedged 8-replica drain still hands back control in
        ``join_s`` (the hot-swap flip passes ``KEYSTONE_SWAP_DRAIN_MS``
        and that contract is a total bound). The default (``join_s``
        None) keeps the legacy generous per-thread bound: a plain
        ``close(drain=True)`` promises to serve what is queued, and a
        long tail draining in the background must not newly fail as
        ``ServiceClosed`` under a shared cap. Idempotent."""
        per_thread = join_s is None
        join_s = self._CLOSE_JOIN_S if join_s is None else float(join_s)
        rejected: list = []
        with self._cv:
            self._closed = True
            if not drain:
                rejected = list(self._pending)
                self._pending.clear()
            self._cv.notify_all()
            for c in self._ccvs:
                c.notify_all()
        self._wd_stop.set()
        deadline = time.monotonic() + join_s

        def _join(t):
            if per_thread:
                t.join(timeout=join_s)
            else:
                t.join(timeout=max(0.0, deadline - time.monotonic()))

        _join(self._worker)
        for t in self._completers:
            _join(t)
        if self._watchdog is not None:
            _join(self._watchdog)
        with self._cv:
            leftovers = list(self._pending) + list(self._inflight)
            for q in self._cqueues:
                for rec in q:
                    leftovers.extend(rec.live)
                    # Queued (unowned) records release their slots; an
                    # ACTIVE record's handle belongs to its completer —
                    # abandoning it here would race a stuck wait().
                    abandon = getattr(rec.handle, "abandon", None)
                    if abandon is not None:
                        abandon()
                q.clear()
            for i, rec in enumerate(self._cq_active):
                if rec is not None:
                    leftovers.extend(rec.live)
                # In place: a late completer still holds this list.
                self._cq_active[i] = None
            self._pending.clear()
            self._inflight = []
            self._queue_gauge.set(0)
            self._inflight_gauge.set(0)
        failed = 0
        for rq in rejected + leftovers:
            if not rq.fut.done() and self._resolve(
                rq.fut,
                exc=ServiceClosed(
                    "PipelineService closed before this request ran"
                ),
            ):
                rq.rec.finish("closed")
                self._outcomes.bump("closed")
                failed += 1
        if failed:
            reliability_counters.bump("futures_failed_on_close", failed)

    def __enter__(self) -> "PipelineService":
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def stats(self) -> dict:
        """The service health surface: request accounting, end-to-end
        latency percentiles (registry-backed, always on), queue/in-flight
        state, replica-pool dispatch balance, and the engine's compile
        evidence — one dict an operator or bench can poll instead of
        assembling it from private counters."""
        with self._lock:
            pending = len(self._pending)
            inflight = (
                sum(self._outstanding) if self._pipelined
                else len(self._inflight)
            )
            alive = self._worker.is_alive()
            outstanding = list(self._outstanding)
            dead = list(self._dead)
            retired = list(self._retired)
        return {
            "name": self.name,
            "requests": self.requests,
            "batches_run": self.batches_run,
            "rows_served": self.rows_served,
            "rejected": self.rejected,
            "expired": self.expired,
            "worker_restarts": self.worker_restarts,
            "stalls": self._stalls,
            "watchdog_ms": self._watchdog_s * 1e3,
            "flight": self._flight.stats(),
            "coalesce_ratio": (
                self.requests / self.batches_run if self.batches_run else None
            ),
            "pending": pending,
            "inflight": inflight,
            "inflight_limit": self.inflight_limit,
            "pipelined": self._pipelined,
            "worker_alive": alive,
            "closed": self._closed,
            "replicas": {
                "count": self._n_replicas,
                "outstanding": outstanding,
                "dead": dead,
                "retired": retired,
                "deaths": self.replica_deaths,
                "revivals": self.replica_revivals,
            },
            "outcomes": self._outcomes.snapshot(),
            # Per-service, not the process-global registry aggregates.
            "latency": self._e2e.snapshot(),
            "queue_depth": {"value": pending, "max": self._depth_max},
            "compiled": self.compiled.stats(),
        }
