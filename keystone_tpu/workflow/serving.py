"""Shape-stable serving: bucketed batch apply with AOT warmup.

Fitted pipelines are *applied* far more often than they are fit, and the
north-star workload is request traffic whose batch sizes vary per call. A
bare ``jax.jit`` recompiles the whole fused chain for every distinct row
count, so a mixed-size trace compiles forever and never reaches steady
state. The standard TPU answer is statically bounded shapes: round every
batch up a small bucket ladder, pad with rows that cannot affect the real
outputs, run ONE ahead-of-time compiled executable per bucket, and slice
the result (arXiv:1810.09868 AOT compilation; arXiv:2206.14148 bounded
shapes).

Three layers, outermost first:

- ``PipelineService`` — a micro-batcher: concurrent ``submit()`` calls
  coalesce into one bucketed device call (the serving analog of the
  reference's per-partition map — amortize dispatch across requests).
- ``CompiledPipeline`` — the per-process serving engine: bucket ladder,
  mask-safe padding, AOT warmup of every bucket before first traffic,
  donated input buffers on the hot call, host-in/host-out so the steady
  state issues NO jax operations beyond the pre-compiled executable
  (zero steady-state recompiles, measured by tools/bench_serve.py).
- ``bucketed_call`` — the in-graph wiring: ``Transformer.batch_call``
  routes through it when ``config.serve_buckets`` is non-empty (env
  ``KEYSTONE_SERVE_BUCKETS``), so executor-driven applies and
  ``Pipeline.apply_batches`` loops see a bounded shape set too.

Padding is only sound for transformers whose output row i depends on
input row i alone AND whose output row count equals the input row count —
the ``Transformer.row_independent`` flag. Ops that couple rows (batch
statistics at apply time) or fan rows out (``Windower``,
``CenterCornerPatcher``) set it False and the bucketed path refuses them
with ``RowDependenceError`` instead of silently corrupting outputs.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from keystone_tpu.config import config, pow2_ladder
from keystone_tpu.utils.metrics import (
    LatencyHistogram,
    active_tracer,
    metrics_registry,
    reliability_counters,
    serving_counters,
)
from keystone_tpu.utils.reliability import (
    DeadlineExceeded,
    QueueFullError,
    ServiceClosed,
    WorkerDiedError,
    active_plan,
)

logger = logging.getLogger("keystone_tpu")

# Registry-backed serving health metrics (utils/metrics.MetricsRegistry):
# per-device-call and end-to-end submit latency histograms plus
# queue-depth / in-flight gauges. Always on — one clock read and a locked
# bucket increment per REQUEST (not per row), noise against a device call
# — so `MetricsRegistry.snapshot()` reports serving p50/p95/p99 without
# anyone having had to pre-arm tracing before the incident.
request_latency = metrics_registry.histogram("serve.request_latency")
e2e_latency = metrics_registry.histogram("serve.e2e_latency")
queue_depth_gauge = metrics_registry.gauge("serve.queue_depth")
inflight_gauge = metrics_registry.gauge("serve.inflight")


class RowDependenceError(TypeError):
    """Raised when bucketed (padded) apply is requested for a transformer
    whose batch output depends on other rows — padding would change the
    real outputs, so it is refused rather than risked."""


# ---------------------------------------------------------------------------
# Ladder helpers
# ---------------------------------------------------------------------------


def resolve_ladder(
    buckets: Optional[Sequence[int]] = None, max_batch: Optional[int] = None
) -> Tuple[int, ...]:
    """The bucket ladder to serve with: explicit ``buckets`` >
    ``config.serve_buckets`` > pow-2 up to ``max_batch`` /
    ``config.serve_max_batch``. Always sorted, deduplicated, positive."""
    if buckets is None and config.serve_buckets:
        buckets = config.serve_buckets
    if buckets is None:
        # `is None`, not truthiness: an explicit max_batch=0 must hit
        # pow2_ladder's ValueError, not silently become the config default.
        ladder = pow2_ladder(
            config.serve_max_batch if max_batch is None else max_batch
        )
    else:
        ladder = tuple(sorted({int(b) for b in buckets}))
        if max_batch is not None:
            ladder = tuple(b for b in ladder if b <= max_batch)
            if not ladder or ladder[-1] < max_batch:
                ladder = ladder + (int(max_batch),)
    if not ladder or ladder[0] <= 0:
        raise ValueError(f"bucket ladder must be positive ints, got {ladder}")
    return ladder


def bucket_for(n: int, ladder: Sequence[int]) -> Optional[int]:
    """Smallest bucket >= n, or None when n exceeds the ladder (the caller
    chunks)."""
    for b in ladder:
        if n <= b:
            return b
    return None


def _jit_cache_size(jit_fn) -> int:
    """Compiled-entry count of a jitted callable, for compile observability
    on the batch_call path (0 where the runtime doesn't expose it)."""
    try:
        return jit_fn._cache_size()
    except Exception:
        return 0


def _stages(transformer) -> list:
    from keystone_tpu.workflow.pipeline import FusedTransformer

    if isinstance(transformer, FusedTransformer):
        return list(transformer.stages)
    return [transformer]


def _row_coupled_stages(transformer) -> list:
    """Names of stages whose output rows depend on other rows — the ONE
    definition of pad-unsafety both the explicit engine and the implicit
    batch_call knob consult."""
    return [
        type(s).__name__
        for s in _stages(transformer)
        if not getattr(s, "row_independent", True)
    ]


def check_row_independent(transformer) -> None:
    """Raise RowDependenceError naming every offending stage."""
    bad = _row_coupled_stages(transformer)
    if bad:
        raise RowDependenceError(
            f"cannot pad batches through {', '.join(bad)}: the stage's "
            "batch output depends on other rows (row_independent=False), "
            "so bucketed serving would change real outputs. Serve it "
            "per-shape (unset KEYSTONE_SERVE_BUCKETS / serve_buckets) or "
            "keep the row-coupled stage off the bucketed path."
        )


# ---------------------------------------------------------------------------
# In-graph bucketing (Transformer.batch_call wiring)
# ---------------------------------------------------------------------------


# Row-coupled transformer classes we have already warned about falling back
# to per-shape jit under the global bucketing knob (warn once per class, not
# once per batch).
_fallback_warned: set = set()


def bucketed_call(transformer, X):
    """Bucket-pad-run-slice on device, through the transformer's own
    per-shape jit cache — which now only ever sees ladder shapes, so the
    compile set is bounded by the ladder instead of the request mix.

    Used by ``Transformer.batch_call`` when ``config.serve_buckets`` is
    set. Stays device-in/device-out (this runs mid-graph, feeding further
    device ops); the tiny pad/slice ops compile once per (bucket, n) pair
    and then also reach steady state.

    Row-coupled transformers (``row_independent=False``) cannot be padded;
    here — the IMPLICIT, process-wide knob — they fall back to today's
    per-shape jit with a one-time warning, so flipping
    KEYSTONE_SERVE_BUCKETS never crashes a working pipeline (e.g. the
    ImageNet TTA view expansion mid-graph). The EXPLICIT serving engine
    (``CompiledPipeline``), where the user asked for bucketed execution by
    name, refuses them with ``RowDependenceError`` instead.
    """
    import logging

    import jax.numpy as jnp

    bad = _row_coupled_stages(transformer)
    if bad:
        key = tuple(bad)
        if key not in _fallback_warned:
            _fallback_warned.add(key)
            logging.getLogger("keystone_tpu").warning(
                "serve_buckets: %s is not row-independent; padding refused, "
                "falling back to per-shape jit (this path can recompile per "
                "batch size)",
                ", ".join(bad),
            )
        return transformer._jitted()(X)
    ladder = resolve_ladder()
    # Normalize to a jax array up front: a numpy batch and an equal-shape
    # device array key DIFFERENT jit-cache entries, which would double the
    # compile set per bucket.
    X = jnp.asarray(X)
    n = int(X.shape[0])
    if n == 0:
        return transformer._jitted()(X)
    jit_fn = transformer._jitted()
    max_b = ladder[-1]
    outs = []
    for start in range(0, n, max_b):
        chunk = X[start : min(start + max_b, n)]
        m = int(chunk.shape[0])
        b = bucket_for(m, ladder)
        if m != b:
            pad = jnp.broadcast_to(chunk[-1:], (b - m,) + chunk.shape[1:])
            chunk = jnp.concatenate([chunk, pad], axis=0)
        cache_before = _jit_cache_size(jit_fn)
        out = jit_fn(chunk)
        if _jit_cache_size(jit_fn) > cache_before:
            serving_counters.record_compile(b)  # cold ladder bucket
        serving_counters.record_call(b, m)
        if m != b:
            out = jax.tree_util.tree_map(lambda a: a[:m], out)
        outs.append(out)
    if len(outs) == 1:
        return outs[0]
    return jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=0), *outs
    )


# ---------------------------------------------------------------------------
# CompiledPipeline — AOT-warmed bucketed serving engine
# ---------------------------------------------------------------------------


def _serving_transformer(target):
    """Lower a Pipeline / Transformer to the single jittable transformer the
    serving engine compiles (fitting estimators and fusing the chain)."""
    from keystone_tpu.workflow.executor import PipelineEnv
    from keystone_tpu.workflow.pipeline import Pipeline, Transformer

    if isinstance(target, Pipeline):
        fitted = target.fit()
        return PipelineEnv.get().executor.serving_chain(
            fitted.graph, fitted.source, fitted.sink
        )
    if isinstance(target, Transformer):
        if not target.jittable:
            raise TypeError(
                f"{type(target).__name__} is not jittable; the AOT serving "
                "path compiles the whole chain as one XLA program"
            )
        return target
    raise TypeError(f"cannot serve a {type(target).__name__}")


class CompiledPipeline:
    """A fitted pipeline compiled for shape-stable serving.

    - Rounds incoming batches up the bucket ladder, pads with mask-safe
      rows (the last real row, replicated — numerically inert for
      row-independent chains and immune to 0-row pathologies like
      divide-by-norm), runs the bucket's pre-compiled executable, slices.
    - ``warmup()`` AOT-compiles the WHOLE ladder via
      ``jit(...).lower(spec).compile()`` before first traffic.
    - Donates the padded input buffer on the hot call (we own it — it was
      built by padding — so donation is always safe; auto-disabled on CPU
      where XLA ignores it).
    - Host-in/host-out: padding is numpy, results come back as numpy. The
      steady state therefore issues zero jax tracing/compile work — only
      pre-compiled executable calls. Oversize batches chunk through the
      top bucket.
    """

    def __init__(
        self,
        target,
        buckets: Optional[Sequence[int]] = None,
        max_batch: Optional[int] = None,
        donate: Optional[bool] = None,
    ):
        self.transformer = _serving_transformer(target)
        check_row_independent(self.transformer)
        self.ladder = resolve_ladder(buckets, max_batch)
        self.max_batch = self.ladder[-1]
        if donate is None:
            donate = jax.default_backend() != "cpu"
        self.donate = bool(donate)
        self._jit = jax.jit(
            self.transformer.apply_batch,
            donate_argnums=(0,) if self.donate else (),
        )
        self._executables: dict = {}
        self.feature_shape: Optional[Tuple[int, ...]] = None
        self._dtype = None
        self.compile_count = 0
        # Per-ENGINE bucket attribution (serving_counters keeps the
        # process-wide view): two engines in one process must not read
        # each other's compiles off their own stats().
        self.compiles_by_bucket: dict = {}
        self.warmup_seconds: Optional[float] = None
        self._lock = threading.Lock()
        # Resolved ONCE per engine (the active_plan discipline): tracing
        # disabled = a None check on the hot call, nothing more.
        self._tracer = active_tracer()

    @property
    def dtype(self):
        return self._dtype

    # -- warmup ------------------------------------------------------------

    def warmup(
        self, example: Union[Tuple[int, ...], Any], dtype=None
    ) -> "CompiledPipeline":
        """AOT-compile every bucket before first traffic.

        ``example`` is either the per-row feature shape (a tuple of ints)
        or a sample batch (leading axis = rows) whose ``shape[1:]``/dtype
        are taken. Idempotent per (shape, dtype): re-warming compiles only
        missing buckets.
        """
        if isinstance(example, tuple) and all(
            isinstance(d, int) for d in example
        ):
            feature_shape = example
            dt = np.dtype(dtype or config.default_dtype)
        else:
            arr = np.asarray(example)
            if arr.ndim < 1:
                raise ValueError(
                    "warmup example must be a feature-shape tuple or a "
                    "sample batch with a leading row axis"
                )
            feature_shape = arr.shape[1:]
            dt = np.dtype(dtype) if dtype is not None else arr.dtype
        # A float64 host batch must not lower an f64 executable under
        # x64-disabled jax; serve at the dtype jax would compute in.
        dt = np.dtype(jax.dtypes.canonicalize_dtype(dt))
        with self._lock:
            if (
                self.feature_shape is not None
                and (self.feature_shape, self._dtype) != (feature_shape, dt)
            ):
                # New traffic signature: previous executables can't serve it.
                self._executables.clear()
            self.feature_shape, self._dtype = feature_shape, dt
            t0 = time.perf_counter()
            for b in self.ladder:
                if b not in self._executables:
                    self._compile_bucket(b)
            self.warmup_seconds = time.perf_counter() - t0
        return self

    def _compile_bucket(self, b: int):
        """Lower + compile one bucket's executable (caller holds the lock or
        is single-threaded setup code)."""
        spec = jax.ShapeDtypeStruct(
            (b,) + self.feature_shape, self._dtype
        )
        self._executables[b] = self._jit.lower(spec).compile()
        self.compile_count += 1
        self.compiles_by_bucket[b] = self.compiles_by_bucket.get(b, 0) + 1
        serving_counters.record_compile(b)
        return self._executables[b]

    # -- hot path ----------------------------------------------------------

    def __call__(self, X):
        """Serve one batch: returns numpy, sliced to the real row count."""
        if self.feature_shape is None:
            # Lazy warmup off the first request's signature: correct, but
            # the first-traffic latency pays the whole ladder. Call
            # warmup() ahead of traffic instead.
            self.warmup(np.asarray(X))
        t0 = time.perf_counter()
        X = np.asarray(X, dtype=self._dtype)
        if X.shape[1:] != self.feature_shape:
            raise ValueError(
                f"request feature shape {X.shape[1:]} != warmed shape "
                f"{self.feature_shape}; re-warm the pipeline for new traffic"
            )
        n = X.shape[0]
        if n == 0:
            raise ValueError("cannot serve an empty batch")
        outs = []
        for start in range(0, n, self.max_batch):
            chunk = X[start : min(start + self.max_batch, n)]
            outs.append(self._serve_chunk(chunk))
        if len(outs) == 1:
            out = outs[0]
        else:
            out = jax.tree_util.tree_map(
                lambda *xs: np.concatenate(xs, axis=0), *outs
            )
        # Boundaries match what an external caller times around this call,
        # so the registry's percentiles agree with bench_serve's.
        request_latency.record(time.perf_counter() - t0)
        return out

    def _serve_chunk(self, chunk: np.ndarray):
        m = chunk.shape[0]
        b = bucket_for(m, self.ladder)
        if m != b:
            pad = np.broadcast_to(chunk[-1:], (b - m,) + chunk.shape[1:])
            chunk = np.concatenate([chunk, pad], axis=0)
        ex = self._executables.get(b)
        if ex is None:
            with self._lock:
                ex = self._executables.get(b)
                if ex is None:  # cold bucket (warmup skipped): counted miss
                    ex = self._compile_bucket(b)
        tr = self._tracer
        t0 = tr.now() if tr is not None else 0
        out = ex(chunk)
        serving_counters.record_call(b, m)
        # np.asarray blocks on the transfer, so latency measurements around
        # this call see the true device time; slicing happens on host.
        out = jax.tree_util.tree_map(lambda a: np.asarray(a)[:m], out)
        if tr is not None:
            tr.record("serve.device", "serving", t0, rows=m, bucket=b)
        return out

    def stats(self) -> dict:
        return {
            "ladder": list(self.ladder),
            "compile_count": self.compile_count,
            "compiles_by_bucket": dict(sorted(
                self.compiles_by_bucket.items()
            )),
            "warmup_seconds": self.warmup_seconds,
            "donate": self.donate,
            # Explicitly process-wide (every engine records into the one
            # registry histogram); per-engine latency needs one engine per
            # process or the trace's serve.device spans.
            "process_request_latency": request_latency.snapshot(),
        }


# ---------------------------------------------------------------------------
# PipelineService — request coalescing micro-batcher
# ---------------------------------------------------------------------------


class PipelineService:
    """Coalesces concurrent small requests into one bucketed device call.

    ``submit(x)`` returns a ``concurrent.futures.Future``. A background
    worker drains the request queue: it takes the oldest request, then
    keeps absorbing queued requests until the flush would exceed
    ``max_rows`` or ``max_delay_ms`` has passed since the flush group
    opened, concatenates them into one batch, runs the warmed
    ``CompiledPipeline`` once, and splits the result back per-request.
    Under load the delay never waits — the queue is non-empty, so flushes
    are back-to-back full buckets; the delay only bounds the latency a
    lone request pays waiting for company.

    Hardened for sustained overload (utils/reliability.py):

    - **Bounded pending queue.** At ``max_pending`` queued requests,
      ``submit`` fast-fails with ``QueueFullError`` instead of growing
      the queue — under 2× capacity, excess load becomes immediate
      rejections while accepted requests keep a bounded p99, rather than
      every request sliding down an unbounded-latency cliff.
    - **Per-request deadlines.** A request still queued past its deadline
      (per-submit ``deadline_ms``, default ``config.serve_deadline_ms``)
      fails its future with ``DeadlineExceeded`` before wasting a device
      call on an answer nobody is waiting for.
    - **Worker-death detection.** If the worker thread dies (a bug, or
      the harness's ``worker_death`` site), the next ``submit`` fails the
      dead worker's in-flight futures with ``WorkerDiedError``, restarts
      the worker, and the queue drains normally.
    - **A close() that never strands a future.** ``close()`` drains by
      default (``drain=False`` rejects immediately); either way every
      future still unresolved when the worker is gone is failed with
      ``ServiceClosed`` — no caller ever blocks forever on ``result()``.

    Requires a warmed pipeline: warmup belongs before first traffic, not
    under it.
    """

    #: Upper bound on waiting for the worker to drain at close(): the
    #: satellite guarantee is "reject, never hang" — past this, leftover
    #: futures are failed instead of waited for.
    _CLOSE_JOIN_S = 30.0

    def __init__(
        self,
        compiled: CompiledPipeline,
        max_delay_ms: float = 2.0,
        max_rows: Optional[int] = None,
        max_pending: Optional[int] = None,
        deadline_ms: Optional[float] = None,
    ):
        if compiled.feature_shape is None:
            raise RuntimeError(
                "PipelineService requires a warmed CompiledPipeline — call "
                "warmup() with the traffic's feature shape first"
            )
        self.compiled = compiled
        # `is None`, not truthiness: an explicit max_rows=0 must error.
        self.max_rows = int(
            compiled.max_batch if max_rows is None else max_rows
        )
        if self.max_rows < 1:
            raise ValueError(f"max_rows must be >= 1, got {self.max_rows}")
        self.max_delay = max_delay_ms / 1e3
        self.max_pending = int(
            max_pending if max_pending is not None else config.serve_max_pending
        )
        if self.max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {self.max_pending}")
        self.default_deadline_s = (
            deadline_ms if deadline_ms is not None else config.serve_deadline_ms
        ) / 1e3
        self._plan = active_plan()
        self._tracer = active_tracer()  # resolved once per service
        # Per-SERVICE latency/depth (the process-global registry metrics
        # aggregate every service; two services in one process must not
        # read each other's numbers off their own stats()).
        self._e2e = LatencyHistogram()
        self._depth_max = 0
        self._pending: deque = deque()
        self._inflight: list = []  # futures of the group being flushed
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closed = False
        self.requests = 0
        self.batches_run = 0
        self.rows_served = 0
        self.rejected = 0
        self.expired = 0
        self.worker_restarts = 0
        self._worker = self._spawn_worker()

    def _spawn_worker(self) -> threading.Thread:
        t = threading.Thread(
            target=self._loop, name="keystone-serve", daemon=True
        )
        t.start()
        return t

    # -- client side -------------------------------------------------------

    def submit(self, x, deadline_ms: Optional[float] = None) -> Future:
        """Queue one request: a single example (feature-shaped) or a small
        batch (leading row axis). The future resolves to the transformed
        example/batch respectively — or fails with ``QueueFullError``
        (raised here, synchronously), ``DeadlineExceeded``,
        ``WorkerDiedError``, or ``ServiceClosed``; it is never stranded.

        ``deadline_ms`` overrides the service default for this request;
        0/None with a 0 default means no deadline."""
        x = np.asarray(x, dtype=self.compiled.dtype)
        datum = x.shape == self.compiled.feature_shape
        if datum:
            x = x[None, ...]
        if x.shape[1:] != self.compiled.feature_shape:
            raise ValueError(
                f"request shape {x.shape} does not match served feature "
                f"shape {self.compiled.feature_shape}"
            )
        deadline_s = (
            deadline_ms / 1e3 if deadline_ms is not None
            else self.default_deadline_s
        )
        deadline = time.monotonic() + deadline_s if deadline_s > 0 else None
        fut: Future = Future()
        # Lifecycle clock: queued → flushed → device → resolved spans and
        # the e2e histogram all measure from this submit timestamp.
        t_sub = time.perf_counter_ns()
        with self._cv:
            if self._closed:
                raise ServiceClosed("PipelineService is closed")
            self._ensure_worker_locked()
            if len(self._pending) >= self.max_pending:
                # Fast-fail backpressure: reject NOW, at zero device cost,
                # instead of queueing latency the client will time out on.
                self.rejected += 1
                reliability_counters.bump("requests_rejected")
                if self._tracer is not None:
                    self._tracer.instant(
                        "serve.rejected", "serving", rows=int(x.shape[0])
                    )
                raise QueueFullError(
                    f"serving queue at capacity ({self.max_pending} "
                    "pending); request rejected fast"
                )
            self._pending.append((x, datum, fut, deadline, t_sub))
            self.requests += 1
            depth = len(self._pending)
            queue_depth_gauge.set(depth)
            if depth > self._depth_max:
                self._depth_max = depth
            self._cv.notify()
        return fut

    def _ensure_worker_locked(self) -> None:
        """Detect a dead worker (caller holds the lock): fail whatever it
        had in flight — those futures can never resolve — and restart it
        so the queued work drains."""
        if self._worker.is_alive():
            return
        dead = [f for f in self._inflight if not f.done()]
        for f in dead:
            self._resolve(
                f, exc=WorkerDiedError(
                    "serving worker died while this request was in flight"
                )
            )
        if dead:
            reliability_counters.bump(
                "futures_failed_on_worker_death", len(dead)
            )
        self._inflight = []
        self.worker_restarts += 1
        reliability_counters.bump("worker_restarts")
        logger.warning(
            "PipelineService worker died; restarting (restart #%d, %d "
            "in-flight futures failed)", self.worker_restarts, len(dead),
        )
        self._worker = self._spawn_worker()

    # -- worker side -------------------------------------------------------

    @staticmethod
    def _expired(entry) -> bool:
        deadline = entry[3]
        return deadline is not None and time.monotonic() > deadline

    def _fail_expired(self, entry) -> None:
        self.expired += 1
        reliability_counters.bump("deadline_expired")
        if self._tracer is not None:
            self._tracer.record(
                "serve.request", "serving", entry[4], outcome="expired",
                rows=int(entry[0].shape[0]),
            )
        self._resolve(
            entry[2],
            exc=DeadlineExceeded(
                "request deadline passed before the device ran it"
            ),
        )

    def _loop(self):
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if not self._pending and self._closed:
                    return
                if self._plan is not None and self._plan.check("worker_death"):
                    # Die like a crashed thread would: queued entries stay
                    # pending (the restarted worker serves them); only a
                    # group already popped would be lost, and the restart
                    # path fails those futures explicitly.
                    raise WorkerDiedError(
                        "injected worker death (KEYSTONE_FAULTS worker_death)"
                    )
                group: list = []
                rows = 0
                flush_at: Optional[float] = None
                while True:
                    if self._pending:
                        entry = self._pending[0]
                        if self._expired(entry):
                            # Expired in queue: fail it before it costs a
                            # device call, keep coalescing.
                            self._pending.popleft()
                            self._fail_expired(entry)
                            continue
                        nxt_rows = entry[0].shape[0]
                        if group and rows + nxt_rows > self.max_rows:
                            break
                        group.append(self._pending.popleft())
                        rows += nxt_rows
                        if flush_at is None:
                            flush_at = time.monotonic() + self.max_delay
                        if rows >= self.max_rows:
                            break
                        continue
                    if not group:
                        break  # everything pending had expired: re-wait
                    remaining = flush_at - time.monotonic()
                    if remaining <= 0 or self._closed:
                        break
                    self._cv.wait(remaining)
                # Gauge updated even when everything popped had expired
                # (group empty): the queue really did shrink.
                queue_depth_gauge.set(len(self._pending))
                if not group:
                    continue
                self._inflight = [e[2] for e in group]
                inflight_gauge.set(len(group))
                if self._tracer is not None:
                    # Queue residency per request: submit → flush-group pop.
                    now = self._tracer.now()
                    for e in group:
                        self._tracer.record(
                            "serve.queued", "serving", e[4], now,
                            rows=int(e[0].shape[0]),
                        )
            self._flush(group)
            with self._cv:
                self._inflight = []
                inflight_gauge.set(0)

    @staticmethod
    def _resolve(fut: Future, value=None, exc=None) -> None:
        """Resolve a future, tolerating client-side cancellation: a future
        the client cancelled mid-flight must not poison the rest of its
        coalesced group (set_result on it raises InvalidStateError)."""
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(value)
        except InvalidStateError:
            pass

    def _flush(self, group):
        # Deadlines re-checked at flush time: a request can expire while
        # the group waits max_delay for company.
        live = []
        for entry in group:
            if self._expired(entry):
                self._fail_expired(entry)
            else:
                live.append(entry)
        if not live:
            return
        tr = self._tracer
        t_flush = tr.now() if tr is not None else 0
        try:
            if len(live) == 1:
                X = live[0][0]
            else:
                X = np.concatenate([g[0] for g in live], axis=0)
            out = self.compiled(X)
            self.batches_run += 1
            self.rows_served += X.shape[0]
            off = 0
            for x, datum, fut, _deadline, t_sub in live:
                m = x.shape[0]
                piece = jax.tree_util.tree_map(
                    lambda a, o=off, m=m: a[o : o + m], out
                )
                if datum:
                    piece = jax.tree_util.tree_map(lambda a: a[0], piece)
                off += m
                # Latency stamped BEFORE resolving: set_result runs client
                # done-callbacks inline, and their cost must not count as
                # serving latency (for this request or the rest of the
                # group).
                now_ns = time.perf_counter_ns()
                self._e2e.record((now_ns - t_sub) / 1e9)
                e2e_latency.record((now_ns - t_sub) / 1e9)
                if tr is not None:
                    tr.record(
                        "serve.request", "serving", t_sub, now_ns,
                        outcome="ok", rows=m,
                    )
                self._resolve(fut, value=piece)
            if tr is not None:
                tr.record(
                    "serve.flush", "serving", t_flush,
                    requests=len(live), rows=int(X.shape[0]),
                )
        except Exception as e:  # fail the whole flush group, keep serving
            for _x, _d, fut, _deadline, t_sub in live:
                if not fut.done():
                    self._resolve(fut, exc=e)
                    if tr is not None:
                        tr.record(
                            "serve.request", "serving", t_sub,
                            outcome=type(e).__name__,
                        )

    # -- lifecycle ---------------------------------------------------------

    def close(self, drain: bool = True):
        """Stop the service without stranding a single future.

        ``drain=True`` (default) lets the worker serve what is already
        queued, then joins it; ``drain=False`` rejects queued requests
        immediately with ``ServiceClosed``. In BOTH modes, any future
        still unresolved once the worker is gone — queued behind a dead
        worker, in flight when the join timed out — is failed with
        ``ServiceClosed`` rather than left for a caller to block on
        forever. Idempotent."""
        rejected: list = []
        with self._cv:
            self._closed = True
            if not drain:
                rejected = [e[2] for e in self._pending]
                self._pending.clear()
            self._cv.notify_all()
        self._worker.join(timeout=self._CLOSE_JOIN_S)
        with self._cv:
            leftovers = [e[2] for e in self._pending] + list(self._inflight)
            self._pending.clear()
            self._inflight = []
            queue_depth_gauge.set(0)
            inflight_gauge.set(0)
        failed = 0
        for fut in rejected + leftovers:
            if not fut.done():
                self._resolve(
                    fut,
                    exc=ServiceClosed(
                        "PipelineService closed before this request ran"
                    ),
                )
                failed += 1
        if failed:
            reliability_counters.bump("futures_failed_on_close", failed)

    def __enter__(self) -> "PipelineService":
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def stats(self) -> dict:
        """The service health surface: request accounting, end-to-end
        latency percentiles (registry-backed, always on), queue/in-flight
        state, and the engine's compile evidence — one dict an operator or
        bench can poll instead of assembling it from private counters."""
        with self._lock:
            pending = len(self._pending)
            inflight = len(self._inflight)
            alive = self._worker.is_alive()
        return {
            "requests": self.requests,
            "batches_run": self.batches_run,
            "rows_served": self.rows_served,
            "rejected": self.rejected,
            "expired": self.expired,
            "worker_restarts": self.worker_restarts,
            "coalesce_ratio": (
                self.requests / self.batches_run if self.batches_run else None
            ),
            "pending": pending,
            "inflight": inflight,
            "worker_alive": alive,
            "closed": self._closed,
            # Per-service, not the process-global registry aggregates.
            "latency": self._e2e.snapshot(),
            "queue_depth": {"value": pending, "max": self._depth_max},
            "compiled": self.compiled.stats(),
        }
