"""Networked serving daemon: admission control, SLA tiers, hot-swap.

``PipelineService`` coalesces and serves — but only in-process. The
north-star workload ("millions of users", ROADMAP item 3) needs a wire,
per-tenant protection, and the ability to replace the model under load
without dropping a request. This module is that layer, extending the
stdlib ``tools/metrics_server.py`` server pattern into a data-plane
ingress over the existing replica-pool service:

- **Two ingresses, one core.** An HTTP/JSON ingress
  (``POST /predict``; stdlib ``ThreadingHTTPServer``) for
  compatibility, and a length-prefixed socket ingress (4-byte
  big-endian frame length + JSON payload, persistent connections) for
  cheap high-rate clients. Both feed ``serve_request`` — the shared
  admit→submit→await core — so semantics can never drift between wires.

- **Admission control** (fast-fail philosophy of arXiv:2206.14148 —
  refuse work you cannot finish instead of degrading everyone): tenants
  are named API keys (``KEYSTONE_TENANTS`` /
  :func:`parse_tenants`) carrying a token-bucket QPS quota and an SLA
  tier. An over-quota tenant gets HTTP 429 BEFORE any device work
  (``QuotaExceeded``, a ``QueueFullError``); a global pending budget
  (``KEYSTONE_SERVE_PENDING_BUDGET``) caps admitted-but-unanswered
  requests across all tenants, with **best-effort refused at
  ``BE_BUDGET_FRAC`` of the budget** so gold always has reserved
  headroom — the queue-priority half of the SLA. Tiers also select the
  per-request deadline (``KEYSTONE_SERVE_GOLD_DEADLINE_MS`` /
  ``KEYSTONE_SERVE_BE_DEADLINE_MS``); a breached deadline surfaces as
  HTTP 504 (``DeadlineExceeded``), a full service queue as 429, a
  closed/mid-flip service as 503.

- **Learned capacity loop** (workflow/capacity.py, the serving analog
  of the learned TPU cost model in arXiv:2008.01040): when
  ``KEYSTONE_CAPACITY_MODEL`` resolves on, a per-(tier, bucket)
  latency/occupancy model fitted from this daemon's own journey records
  adds a fourth admission leg — refuse a request whose PREDICTED
  completion (queue depth x modeled batch latency) already breaches its
  deadline (counted 429, ``predicted_infeasible``) — drives a
  traffic-aware autoscale loop (``_replan_loop``: replica resize +
  mix-driven ladder re-price through the PR-13 planner, no-flap
  guarded), and prices the service's deadline-aware cross-tenant
  micro-batching. Cold model (fewer than
  ``KEYSTONE_CAPACITY_MIN_SAMPLES`` journeys) = every consumer no-ops,
  bit-identical to model-off.

- **Fit→serve handoff + zero-downtime hot-swap.** The daemon serves one
  :class:`~keystone_tpu.workflow.serialization.ModelArtifact` at a time,
  tagged with an atomic generation counter. ``request_swap(path)`` (or
  ``POST /swap``) loads + verifies the new artifact, AOT-warms the
  successor engine's bucket ladder **replica-by-replica** — after each
  new replica warms, the outgoing generation's matching replica is
  drained via the PR-5 replica-death re-queue machinery
  (``PipelineService.retire_replica``: its in-flight groups re-dispatch
  to the surviving old replicas), so the old generation keeps answering
  on the devices not yet handed over — then flips the generation
  atomically and drains the old service (``close(drain=True)``). Zero
  dropped requests: a request caught on the closing generation is
  transparently re-submitted to its successor (the serve chain is
  pure). Every response carries the generation that served it. A
  mid-swap failure (the ``swap_abort`` fault site, a bad artifact, a
  warmup error) rolls back — retired replicas revive, the old
  generation keeps serving — and force-dumps the flight recorder naming
  the generation and every in-flight request id.

- **A/B serving: two generations from one replica pool.**
  ``ab_swap(path, tenants=[...])`` stands up a CANDIDATE generation next
  to the live one — same device pool, its own AOT-warmed engine/service
  — and routes only the named tenants' traffic to it (the per-tenant
  routing the admission table already provides). Every other tenant
  keeps the live generation; responses carry the generation that served
  them, so an experiment is attributable per response. ``promote_ab()``
  makes the candidate live for everyone (the old generation drains with
  the zero-dropped-requests guarantee of a normal swap);
  ``abort_ab()`` drains the candidate and routes everyone back. A full
  ``request_swap`` is refused while an A/B is active — resolve the
  experiment first.

- **Failure semantics on the wire.** Journeys now carry the network
  leg: every data-plane request gets an always-on flight-recorder
  record with ``accepted → parsed → admitted → submitted → resolved``
  stamps plus tenant/tier/generation/status metadata (the HTTP path
  pre-admits on the header key before reading the body, so its order is
  ``accepted → admitted → parsed → …``; the framed socket — and a
  body-carried key — parses first). The ``conn_drop``
  fault site (and any real broken pipe at response-write time) marks
  the journey outcome ``conn_drop`` — the future itself resolved;
  nothing is stranded. Accepted connections carry read timeouts
  (``CONN_TIMEOUT_S``) so a stalled client cannot pin a handler thread
  forever.
"""

from __future__ import annotations

import hmac
import json
import logging
import os
import queue
import socket
import struct
import threading
import time
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from keystone_tpu.config import (
    config,
    resolved_capacity_model,
    resolved_telemetry_dir,
)
from keystone_tpu.utils.flight_recorder import (
    FlightRecord,
    FlightRecorder,
    derive_health,
    next_request_id,
)
from keystone_tpu.utils.metrics import (
    active_tracer,
    capacity_counters,
    metrics_registry,
)
from keystone_tpu.utils.telemetry import (
    TRACE_ID_RE,
    SloAccounting,
    accept_trace_id,
    active_telemetry,
)
from keystone_tpu.utils.reliability import (
    AuthError,
    DeadlineExceeded,
    QueueFullError,
    QuotaExceeded,
    ServiceClosed,
    WorkerDiedError,
    active_plan,
)
from keystone_tpu.workflow.serialization import (
    ModelArtifact,
    load_artifact,
)
from keystone_tpu.workflow.capacity import CapacityModel, load_capacity_model
from keystone_tpu.workflow.serving import (
    CompiledPipeline,
    PipelineService,
    bucket_for,
    resolve_serve_devices,
)

logger = logging.getLogger("keystone_tpu")

#: Fraction of the global pending budget best-effort tenants may fill;
#: the remainder is gold's reserved headroom.
BE_BUDGET_FRAC = 0.8

#: Read/write timeout on accepted data-plane connections (and the HTTP
#: handler's request-read timeout): a stalled client must not pin a
#: handler thread forever.
CONN_TIMEOUT_S = 30.0

#: Largest accepted request body / socket frame.
MAX_FRAME_BYTES = 64 << 20

#: Bound on waiting for one submitted future when no deadline applies.
RESULT_TIMEOUT_S = 60.0

#: How many generations a request will chase across a concurrent swap
#: before giving up with 503 (2 swaps back-to-back + margin).
SUBMIT_ATTEMPTS = 4

VALID_TIERS = ("gold", "best_effort")

#: Observed-mix total-variation shift that triggers an autoscale
#: re-plan (workflow/capacity.py consumers; tuned against the
#: bench_capacity shifting-mix flood).
REPLAN_MIX_SHIFT = 0.25

#: HTTP status → journey/counter outcome for data-plane responses.
STATUS_OUTCOMES = {
    200: "ok",
    400: "bad_request",
    403: "auth",
    429: "rejected",
    503: "closed",
    504: "expired",
    500: "error",
}


class Tenant:
    """One admission-control principal: API key, token-bucket QPS quota,
    and SLA tier."""

    __slots__ = ("name", "key", "qps", "burst", "tier")

    def __init__(self, name: str, key: Optional[str], qps: float = 0.0,
                 tier: str = "best_effort", burst: Optional[float] = None):
        if tier not in VALID_TIERS:
            raise ValueError(
                f"tenant {name!r}: tier must be one of {VALID_TIERS}, "
                f"got {tier!r}"
            )
        self.name = name
        self.key = key
        self.qps = float(qps)
        self.tier = tier
        # Default burst: one second of rate (classic token bucket), at
        # least 1 so a tiny-qps tenant can ever send.
        self.burst = float(burst) if burst is not None else max(1.0, self.qps)

    def as_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "qps": self.qps, "burst": self.burst,
                "tier": self.tier}


def parse_tenants(spec: str) -> Dict[str, Tenant]:
    """Parse the ``KEYSTONE_TENANTS`` table: comma-separated
    ``name:api_key:qps[:tier[:burst]]`` entries, keyed by API key.
    Empty/blank = open mode (no keys; anonymous best-effort). Bad
    entries fail loudly naming the token — a silently dropped tenant is
    an auth hole."""
    tenants: Dict[str, Tenant] = {}
    for token in (spec or "").split(","):
        token = token.strip()
        if not token:
            continue
        parts = [p.strip() for p in token.split(":")]
        if len(parts) < 2 or not parts[0] or not parts[1]:
            raise ValueError(
                f"KEYSTONE_TENANTS entry {token!r}: expected "
                "'name:api_key:qps[:tier[:burst]]'"
            )
        name, key = parts[0], parts[1]
        try:
            qps = float(parts[2]) if len(parts) > 2 and parts[2] else 0.0
            burst = (
                float(parts[4]) if len(parts) > 4 and parts[4] else None
            )
        except ValueError:
            raise ValueError(
                f"KEYSTONE_TENANTS entry {token!r}: qps/burst must be "
                "numbers"
            ) from None
        tier = parts[3] if len(parts) > 3 and parts[3] else "best_effort"
        if key in tenants:
            raise ValueError(
                f"KEYSTONE_TENANTS: duplicate api key for tenant {name!r}"
            )
        tenants[key] = Tenant(name, key, qps=qps, tier=tier, burst=burst)
    return tenants


class TokenBucket:
    """Thread-safe token bucket: ``rate`` tokens/s up to ``burst``.
    ``rate <= 0`` = unlimited."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._t_last = time.perf_counter()

    def try_acquire(self) -> bool:
        if self.rate <= 0:
            return True
        with self._lock:
            now = time.perf_counter()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t_last) * self.rate
            )
            self._t_last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


class AdmissionController:
    """Per-tenant quota + global pending-budget gate, evaluated BEFORE a
    request costs any queueing or device work.

    Order matters: auth first (403), then the tenant's token bucket
    (429 ``QuotaExceeded`` — an over-quota tenant is rejected by ITS
    quota even when the daemon is idle), then the global budget (429
    ``QueueFullError`` — best-effort refused at ``be_frac`` of the
    budget so gold keeps reserved headroom)."""

    def __init__(self, tenants: Dict[str, Tenant], pending_budget: int,
                 be_frac: float = BE_BUDGET_FRAC):
        self.tenants = dict(tenants)
        self.open_mode = not self.tenants
        self.pending_budget = int(pending_budget)
        if self.pending_budget < 1:
            raise ValueError(
                f"pending budget must be >= 1, got {self.pending_budget}"
            )
        self.be_frac = float(be_frac)
        # Per-tier pending limits, hoisted OUT of the admit hot path:
        # both are pure functions of construction-time knobs, and
        # admit() runs once per request on every ingress thread.
        self._tier_limits = {
            "gold": self.pending_budget,
            "best_effort": max(1, int(self.pending_budget * self.be_frac)),
        }
        self._anonymous = Tenant("anonymous", None, qps=0.0,
                                 tier="best_effort")
        self._buckets = {
            key: TokenBucket(t.qps, t.burst)
            for key, t in self.tenants.items()
        }
        self._lock = threading.Lock()
        self._inflight = 0
        self.admitted = 0
        self.rejected_auth = 0
        self.rejected_quota = 0
        self.rejected_budget = 0

    def admit(self, key: Optional[str]) -> Tenant:
        if self.open_mode:
            tenant = self._anonymous
        else:
            tenant = self.tenants.get(key) if key else None
            if tenant is None:
                with self._lock:
                    self.rejected_auth += 1
                raise AuthError(
                    "unknown or missing API key (daemon tenants are "
                    "configured; see KEYSTONE_TENANTS)"
                )
            if not self._buckets[tenant.key].try_acquire():
                with self._lock:
                    self.rejected_quota += 1
                raise QuotaExceeded(
                    f"tenant {tenant.name!r}: QPS quota "
                    f"({tenant.qps:g}/s, burst {tenant.burst:g}) exhausted; "
                    "request rejected fast"
                )
        limit = self._tier_limits.get(tenant.tier, self.pending_budget)
        with self._lock:
            if self._inflight >= limit:
                self.rejected_budget += 1
                raise QueueFullError(
                    f"admission budget full ({self._inflight} in flight, "
                    f"{tenant.tier} limit {limit} of "
                    f"{self.pending_budget}); request rejected fast"
                )
            self._inflight += 1
            self.admitted += 1
        return tenant

    def release(self) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "open_mode": self.open_mode,
                "tenants": [t.as_dict() for t in self.tenants.values()],
                "pending_budget": self.pending_budget,
                "be_frac": self.be_frac,
                # Both tier limits, explicit: operators should not have
                # to re-derive the best-effort share from be_frac.
                "tier_budgets": dict(self._tier_limits),
                "inflight": self._inflight,
                "admitted": self.admitted,
                "rejected_auth": self.rejected_auth,
                "rejected_quota": self.rejected_quota,
                "rejected_budget": self.rejected_budget,
            }


class Generation:
    """One serving generation: the artifact identity plus the live
    engine/service pair answering under that identity."""

    __slots__ = ("number", "fingerprint", "engine", "service",
                 "artifact_header")

    def __init__(self, number: int, fingerprint: str,
                 engine: CompiledPipeline, service: PipelineService,
                 artifact_header: Dict[str, Any]):
        self.number = number
        self.fingerprint = fingerprint
        self.engine = engine
        self.service = service
        self.artifact_header = artifact_header


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes, or None on a clean/raggedy disconnect.
    Chunks accumulate in a list (one join at the end): ``buf += chunk``
    would memcpy the whole accumulated buffer per ~64KB recv — quadratic
    cost an adversary could lever with frames near MAX_FRAME_BYTES."""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        try:
            chunk = conn.recv(n - got)
        except (ConnectionError, socket.timeout, OSError):
            return None
        if not chunk:
            return None
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def trace_of(rec: FlightRecord) -> Optional[str]:
    """The journey's wire-propagated trace id (``open_record`` notes one
    on every record, so this is only None for records opened outside the
    daemon's ingress paths)."""
    meta = rec.meta
    return meta.get("trace_id") if meta else None


class _SloGauges:
    """Registry adapter putting per-TIER SLO hit-rate / error-budget
    burn on ``/metrics`` (``keystone_daemon_slo_<tier>{key=...}``
    gauges). Tenant names stay OFF the open scrape surface by design —
    per-tenant detail lives on ``/stats``, where anonymous callers get
    it redacted. Points at the newest same-named daemon's accounting
    (the shared-histogram convention when tests reuse a name)."""

    def __init__(self) -> None:
        self.source: Optional["SloAccounting"] = None

    def snapshot(self) -> Dict[str, Any]:
        src = self.source
        return src.tier_rates() if src is not None else {}

    def reset(self) -> None:
        pass  # a view: the accounting's rolling window forgets on its own


class _IngressHandler(BaseHTTPRequestHandler):
    """HTTP/JSON ingress routes. Data plane: ``POST /predict``.
    Control plane: ``POST /swap``, ``GET /healthz|/metrics|/stats``
    (control responses are exempt from the ``conn_drop`` site — it
    models client data traffic, and a dropped swap ack must not make a
    retried swap run twice)."""

    #: Connection-level read timeout (satellite: a stalled client must
    #: not pin a handler thread).
    timeout = CONN_TIMEOUT_S

    @property
    def owner(self) -> "ServingDaemon":
        return self.server.owner  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # quiet: journeys are the log
        pass

    def _write_json(self, status: int, doc: Dict[str, Any]) -> bool:
        body = json.dumps(doc).encode()
        try:
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if "generation" in doc:
                self.send_header("X-Generation", str(doc["generation"]))
            if doc.get("trace_id"):
                # Every response — 2xx and rejections alike — echoes the
                # request's trace id so a client can stitch its retries
                # to the daemon-side journey.
                self.send_header("X-Trace-Id", str(doc["trace_id"]))
            self.end_headers()
            self.wfile.write(body)
            return True
        except (ConnectionError, TimeoutError, OSError):
            # The client went away mid-write: a real conn_drop.
            self.close_connection = True
            return False

    def _read_body(self, deadline: Optional[float] = None) -> Optional[bytes]:
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            return None
        if length <= 0 or length > MAX_FRAME_BYTES:
            return None
        return self._read_deadlined(length, deadline)

    def _read_deadlined(self, length: int,
                        deadline: Optional[float] = None) -> Optional[bytes]:
        """Read exactly ``length`` body bytes under ONE total deadline.

        The per-recv socket timeout alone cannot bound this: the HTTP
        path pre-admits on the header key BEFORE the body arrives, so a
        client trickling one byte per 29s would hold its admission slot
        (a global-budget unit) indefinitely while every individual recv
        still beats ``CONN_TIMEOUT_S`` — pinned slots would starve all
        tenants, gold included. ``read1`` (at most one underlying recv
        per call, buffered data first) lets the deadline be re-checked
        between recvs, bounding the slot hold to ~CONN_TIMEOUT_S total.
        ``_predict`` passes ONE deadline shared by its body read AND the
        post-rejection drain — two fresh deadlines would double the
        window a trickler can hold its slot.
        """
        if deadline is None:
            deadline = time.monotonic() + CONN_TIMEOUT_S
        read1 = getattr(self.rfile, "read1", self.rfile.read)
        chunks: List[bytes] = []
        remaining = length
        try:
            while remaining > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self.connection.settimeout(min(left, CONN_TIMEOUT_S))
                chunk = read1(min(65536, remaining))
                if not chunk:
                    return None
                chunks.append(chunk)
                remaining -= len(chunk)
        except (ConnectionError, TimeoutError, OSError):
            return None
        finally:
            try:
                self.connection.settimeout(CONN_TIMEOUT_S)
            except OSError:
                pass
        return b"".join(chunks)

    def _drain_body(self, cap: int = 4 << 20,
                    deadline: Optional[float] = None) -> None:
        """Read (and discard) up to ``cap`` bytes of an unread request
        body before responding to an early rejection: closing a socket
        with unread received data makes Linux RST the connection, which
        can destroy the in-flight 429/400 before the client reads it —
        and a retrying client would then re-send the whole body (the Go
        net/http drain idiom). The cap covers realistic prediction
        payloads; it stays bounded (rather than draining the full 64MB
        frame limit) and rides ``_read_deadlined``'s total deadline, so
        a slow sender can pin a rejected handler for at most
        ~``CONN_TIMEOUT_S`` — not ``cap`` bytes' worth of trickle."""
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            return
        if length > 0:
            self._read_deadlined(min(length, cap), deadline)

    def _control_denied(self) -> Optional[str]:
        """None when this caller may use the control plane (POST /swap,
        full /stats); else the refusal message. A data-plane tenant key
        is NOT control-plane credit — swapping the model is operator
        privilege, so it takes the dedicated ``KEYSTONE_SWAP_TOKEN``
        (constant-time compare). With tenants configured but no token
        set, the control plane is locked rather than open: admission
        control would otherwise guard /predict while any anonymous peer
        could replace the model behind it. Open dev mode (no tenants,
        no token) stays open."""
        owner = self.owner
        token = owner.swap_token
        if token:
            supplied = self.headers.get("X-Swap-Token") or ""
            if hmac.compare_digest(supplied.encode(), token.encode()):
                return None
            return "bad or missing X-Swap-Token"
        if owner.admission_open:
            return None
        return ("control plane locked: tenants are configured but "
                "KEYSTONE_SWAP_TOKEN is not set")

    # -- data plane --------------------------------------------------------

    def _predict(self) -> None:
        owner = self.owner
        # Wire-propagated trace context: honour a well-formed client
        # X-Trace-Id, mint one otherwise (malformed ids never propagate
        # verbatim into journeys or response headers).
        rec = owner.open_record(trace_hdr=self.headers.get("X-Trace-Id"))
        # Pre-admission on the HEADER key (and in open mode) BEFORE the
        # body is read: a rejected multi-MB request must not cost the
        # daemon its socket read + JSON parse — that read would be an
        # amplification lever during exactly the overload admission
        # exists for. A body-carried key still works; it just pays the
        # read first.
        tenant = None
        # ONE deadline for everything this request reads off the wire
        # (body, or the post-rejection drain): an admitted slot is held
        # for at most ~CONN_TIMEOUT_S of client trickling, total.
        body_deadline = time.monotonic() + CONN_TIMEOUT_S
        key_hdr = self.headers.get("X-API-Key")
        if key_hdr is not None or owner.admission_open:
            tenant, rejection = owner.admit_request(rec, key_hdr)
            if rejection is not None:
                status, doc, outcome = rejection
                # unread body would RST the response
                self._drain_body(deadline=body_deadline)
                wrote = self._write_json(status, doc)
                owner.finish_request(
                    rec, outcome if wrote else "conn_drop", None, status
                )
                return
        body = self._read_body(deadline=body_deadline)
        payload: Optional[dict] = None
        if body is not None:
            try:
                parsed = json.loads(body)
                if isinstance(parsed, dict):
                    payload = parsed
            except ValueError:
                payload = None
        if payload is None or "x" not in payload:
            doc = {"error": "bad_request",
                   "message": "expected a JSON object body with an 'x' "
                              "array", "request_id": rec.rid,
                   "trace_id": trace_of(rec)}
            if body is None:
                # over-bound/unread body: same RST risk
                self._drain_body(deadline=body_deadline)
            wrote = self._write_json(400, doc)
            # tenant rides along: a pre-admitted slot must release.
            owner.finish_request(
                rec, "bad_request" if wrote else "conn_drop", tenant, 400
            )
            return
        rec.stamp("parsed")
        key = key_hdr or payload.get("key")
        deadline_ms = payload.get("deadline_ms")
        hdr_deadline = self.headers.get("X-Deadline-Ms")
        if hdr_deadline is not None:
            try:
                deadline_ms = float(hdr_deadline)
            except ValueError:
                # Same contract as a garbage body deadline: an explicit
                # but unreadable override is a 400, not a silent
                # fallback to the tier default.
                doc = {"error": "bad_request",
                       "message": f"X-Deadline-Ms must be a number, got "
                                  f"{hdr_deadline!r}",
                       "request_id": rec.rid,
                       "trace_id": trace_of(rec)}
                wrote = self._write_json(400, doc)
                owner.finish_request(
                    rec, "bad_request" if wrote else "conn_drop", tenant, 400
                )
                return
        status, doc, tenant, outcome = owner.serve_request(
            rec, key, payload["x"], deadline_ms, tenant=tenant
        )
        if owner.maybe_drop_connection():
            # Injected client-side drop: the serve completed (the future
            # resolved — nothing stranded); only the answer is lost.
            self.close_connection = True
            owner.finish_request(rec, "conn_drop", tenant, status)
            return
        wrote = self._write_json(status, doc)
        owner.finish_request(
            rec, outcome if wrote else "conn_drop", tenant, status
        )

    # -- control plane -----------------------------------------------------

    def _swap(self) -> None:
        owner = self.owner
        denied = self._control_denied()
        if denied is not None:
            self._drain_body()
            self._write_json(403, {"error": "forbidden", "message": denied})
            return
        body = self._read_body()
        try:
            payload = json.loads(body) if body else {}
        except ValueError:
            payload = None
        if not isinstance(payload, dict) or not payload.get("artifact"):
            self._write_json(400, {
                "error": "bad_request",
                "message": "expected {'artifact': <path>}",
            })
            return
        try:
            generation = owner.request_swap(
                payload["artifact"],
                expect_fingerprint=payload.get("expect_fingerprint"),
            )
        except FutureTimeout:
            self._write_json(504, {
                "error": "swap_timeout",
                "message": "swap still running past KEYSTONE_SWAP_TIMEOUT_MS",
            })
            return
        except Exception as e:  # lint: broad-ok any swap failure becomes the control response; the ingress must survive
            self._write_json(409, {
                "error": type(e).__name__,
                "message": str(e)[:500],
            })
            return
        self._write_json(200, {"generation": generation})

    def do_POST(self):  # noqa: N802 (http.server API)
        path = self.path.split("?")[0]
        if path == "/predict":
            self._predict()
        elif path == "/swap":
            self._swap()
        else:
            self._write_json(404, {"error": "not_found"})

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?")[0]
        owner = self.owner
        if path == "/healthz":
            healthy, doc = derive_health(owner.health_stats())
            self._write_json(200 if healthy else 503, doc)
        elif path == "/metrics":
            body = metrics_registry.prometheus().encode()
            try:
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            except (ConnectionError, TimeoutError, OSError):
                self.close_connection = True
        elif path == "/stats":
            # Anonymous callers get operational stats with the tenant
            # table reduced to a count — names/quotas/tiers are control
            # plane (healthz/metrics stay fully open for LBs/scrapers).
            self._write_json(
                200, owner.stats(redact_tenants=self._control_denied()
                                 is not None)
            )
        else:
            self._write_json(404, {"error": "not_found"})


class ServingDaemon:
    """The networked serving frontend over a hot-swappable generation of
    ``CompiledPipeline`` + ``PipelineService`` (module docstring has the
    architecture). Construct from a saved artifact path (the fit→serve
    handoff) or directly from a fitted pipeline/transformer (tests,
    demos)."""

    #: Per-thread bound on waiting for the ingress/swap threads at
    #: close() (class attr so tests can shrink it to exercise the
    #: close-outlives-a-long-swap path without the full wait).
    CLOSE_JOIN_S = 10.0

    def __init__(
        self,
        artifact: Optional[Any] = None,
        *,
        pipeline: Any = None,
        host: Optional[str] = None,
        http_port: Optional[int] = None,
        socket_port: Optional[int] = None,
        enable_socket: bool = True,
        tenants: Optional[Dict[str, Tenant]] = None,
        pending_budget: Optional[int] = None,
        buckets=None,
        max_batch: Optional[int] = None,
        devices=None,
        inflight: Optional[int] = None,
        max_delay_ms: float = 2.0,
        max_rows: Optional[int] = None,
        max_pending: Optional[int] = None,
        feature_shape: Optional[Tuple[int, ...]] = None,
        dtype=None,
        gold_deadline_ms: Optional[float] = None,
        be_deadline_ms: Optional[float] = None,
        name: Optional[str] = None,
        flight_dir: Optional[str] = None,
        swap_hook: Optional[Callable[["ServingDaemon"], None]] = None,
        swap_token: Optional[str] = None,
        result_timeout_s: float = RESULT_TIMEOUT_S,
    ):
        if (artifact is None) == (pipeline is None):
            raise ValueError(
                "construct with exactly one of artifact= (a saved "
                "ModelArtifact path or object) or pipeline="
            )
        self.name = name or "daemon"
        self.host = host if host is not None else config.serve_host
        self._swap_hook = swap_hook
        self.swap_token = (
            config.swap_token if swap_token is None else str(swap_token)
        )
        self._result_timeout_s = float(result_timeout_s)
        # Resolved ONCE per daemon (the active_plan discipline).
        self._plan = active_plan()
        # Engine/service construction knobs, reused for every successor
        # generation so a swap never silently changes serving shape.
        self._buckets = buckets
        self._max_batch = max_batch
        self._devices = resolve_serve_devices(devices)
        self._inflight_opt = inflight
        self._max_delay_ms = float(max_delay_ms)
        self._max_rows = max_rows
        self._max_pending = max_pending
        self._flight_dir = flight_dir
        tier_deadlines = {
            "gold": (
                config.serve_gold_deadline_ms
                if gold_deadline_ms is None else float(gold_deadline_ms)
            ),
            "best_effort": (
                config.serve_be_deadline_ms
                if be_deadline_ms is None else float(be_deadline_ms)
            ),
        }
        self._tier_deadline_ms = tier_deadlines
        self._admission = AdmissionController(
            parse_tenants(config.tenants) if tenants is None else tenants,
            config.serve_pending_budget
            if pending_budget is None else pending_budget,
        )
        self._outcomes = metrics_registry.counters(
            f"daemon.requests[{self.name}]"
        )
        self._inflight_gauge = metrics_registry.gauge(
            f"daemon.inflight[{self.name}]"
        )
        self._tier_hist = {
            tier: metrics_registry.histogram(
                f"daemon.e2e[{self.name}:{tier}]"
            )
            for tier in VALID_TIERS
        }
        # The daemon's OWN black box: network-leg journeys (accepted →
        # parsed → admitted → submitted → resolved) with tenant / tier /
        # generation / status metadata; dump context = self.stats (runs
        # from unlocked poll points only).
        self._flight = FlightRecorder(
            f"daemon-{self.name}", directory=flight_dir, context=self.stats
        )
        # Per-tenant/tier SLO accounting, exported per-TIER on /metrics
        # via the shared adapter (tenant names never reach the open
        # scrape surface) and in full on /stats. The durable telemetry
        # export resolves to None unless KEYSTONE_TELEMETRY_DIR is set —
        # default off, and journeys ride its bounded queue so admission
        # never blocks on disk.
        self._slo = SloAccounting()
        self._telemetry = active_telemetry()
        metrics_registry.part(
            f"daemon.slo[{self.name}]", _SloGauges
        ).source = self._slo
        # Learned capacity model (workflow/capacity.py), resolved ONCE
        # per daemon: None = disabled (KEYSTONE_CAPACITY_MODEL resolution
        # order lives in config.resolved_capacity_model), and every
        # consumer — predicted admission, the re-plan loop, the
        # service's micro-batcher — no-ops on None. Warm-started from
        # the telemetry segments when they exist, so a restarted daemon
        # predicts from its predecessor's observations.
        self._capacity: Optional[CapacityModel] = (
            load_capacity_model(resolved_telemetry_dir(), self.name)
            if resolved_capacity_model() else None
        )
        # Autoscale re-plan state (the traffic-aware consumer): the mix
        # snapshot the last re-plan acted on, the no-flap stamp, and the
        # last decision for /stats.
        self._replan_stop = threading.Event()
        self._replan_thread: Optional[threading.Thread] = None
        self._capacity_last_mix: Dict[int, float] = {}
        self._last_replan_t = 0.0
        self._last_replan: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()
        self._active: set = set()
        self._draining = False
        self._closed = False
        self.swaps = 0
        self.swap_failures = 0
        # A/B experiment state: a candidate Generation serving only the
        # named tenants (None = no experiment active).
        self._ab_gen: Optional[Generation] = None
        self._ab_tenants: frozenset = frozenset()
        # Highest generation number that ever SERVED traffic (live or as
        # an A/B candidate): numbers are never reused once responses
        # were tagged with them — an aborted candidate's number stays
        # burned so per-response attribution stays unambiguous. (A swap
        # that failed BEFORE install served nothing; its number may
        # recycle.)
        self._gen_hwm = 0
        # Generation 0: load/verify the artifact (or wrap the given
        # pipeline), AOT-warm the whole ladder, stand up the service.
        if artifact is not None and not isinstance(artifact, ModelArtifact):
            artifact = load_artifact(str(artifact))
        if artifact is not None:
            target = artifact.pipeline
            fingerprint = artifact.fingerprint
            header = artifact.header()
            serve_hints = artifact.serve
        else:
            target = pipeline
            fingerprint = "unversioned"
            header = {"schema_version": None, "fingerprint": fingerprint}
            serve_hints = {}
        if feature_shape is None and serve_hints.get("feature_shape"):
            feature_shape = tuple(serve_hints["feature_shape"])
        if feature_shape is None:
            raise ValueError(
                "feature_shape is required (pass it, or save the artifact "
                "with serve hints: save_artifact(..., feature_shape=...))"
            )
        self._feature_shape = tuple(int(d) for d in feature_shape)
        self._dtype = dtype if dtype is not None else serve_hints.get("dtype")
        engine = self._build_engine(target, 0)
        engine.warmup(self._feature_shape, dtype=self._dtype)
        service = self._build_service(engine, 0)
        self._gen = Generation(0, fingerprint, engine, service, header)
        # Ingress last: no traffic before the ladder is warm.
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self.http_port: Optional[int] = None
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self.socket_port: Optional[int] = None
        self._swap_q: "queue.Queue" = queue.Queue()
        self._swap_thread = threading.Thread(
            target=self._swap_loop, name=f"keystone-daemon-swap-{self.name}",
            daemon=True,
        )
        self._swap_thread.start()
        if self._capacity is not None:
            self._replan_thread = threading.Thread(
                target=self._replan_loop,
                name=f"keystone-daemon-replan-{self.name}", daemon=True,
            )
            self._replan_thread.start()
        try:
            self._start_http(
                config.serve_port if http_port is None else int(http_port)
            )
            if enable_socket:
                self._start_socket(
                    config.serve_socket_port if socket_port is None
                    else int(socket_port)
                )
        except BaseException:
            # An ingress bind failure (occupied port) must not leak the
            # already-running generation service, swap worker, or a
            # half-bound HTTP server — a retrying operator process would
            # otherwise accumulate thread pools and keep the HTTP port
            # wedged forever.
            self.close()
            raise

    # -- construction helpers ----------------------------------------------

    def _build_engine(self, target, number: int) -> CompiledPipeline:
        return CompiledPipeline(
            target,
            buckets=self._buckets,
            max_batch=self._max_batch,
            devices=self._devices,
            inflight=self._inflight_opt,
            name=f"{self.name}-g{number}",
        )

    def _build_service(self, engine: CompiledPipeline,
                       number: int) -> PipelineService:
        return PipelineService(
            engine,
            max_delay_ms=self._max_delay_ms,
            max_rows=self._max_rows,
            max_pending=self._max_pending,
            deadline_ms=0.0,  # deadlines come per-request from the tiers
            inflight=self._inflight_opt,
            name=f"{self.name}-g{number}",
            flight_dir=self._flight_dir,
            capacity=self._capacity,
        )

    def _start_http(self, port: int) -> None:
        self._httpd = ThreadingHTTPServer((self.host, port),
                                          _IngressHandler)
        self._httpd.owner = self  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self.http_port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"keystone-daemon-http-{self.name}", daemon=True,
        )
        self._http_thread.start()

    def _start_socket(self, port: int) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, port))
        self._sock.listen(128)
        # Timed accept: a blocked accept() is NOT interrupted by another
        # thread closing the socket on Linux — the accept loop must poll
        # the closed flag or close() would hang on the join.
        self._sock.settimeout(0.5)
        self.socket_port = self._sock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"keystone-daemon-accept-{self.name}", daemon=True,
        )
        self._accept_thread.start()

    # -- socket ingress (thread targets registered in keystone-lint) -------

    def _accept_loop(self) -> None:
        """Socket-ingress accept thread: one handler thread per
        connection (persistent framed connections, so the per-conn spawn
        amortizes over many requests)."""
        sock = self._sock
        while True:
            try:
                conn, _addr = sock.accept()
            except socket.timeout:
                with self._lock:
                    if self._closed:
                        return
                continue
            except OSError:
                return  # listening socket closed: daemon shutdown
            with self._lock:
                closed = self._closed
            if closed:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            conn.settimeout(CONN_TIMEOUT_S)
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name=f"keystone-daemon-conn-{self.name}", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        """One framed connection: 4-byte big-endian length + JSON
        ``{"x": ..., "key": ..., "deadline_ms": ...}`` per request,
        response framed the same with a ``status`` field. Loops until
        the client closes (or a frame violates the protocol)."""
        try:
            while True:
                header = _recv_exact(conn, 4)
                if header is None:
                    return
                # Journey opens at the frame header — even a
                # bounds-violating or truncated frame leaves a record
                # (the open_record contract), mirroring the HTTP path.
                (length,) = struct.unpack(">I", header)
                rec = self.open_record()
                if length == 0 or length > MAX_FRAME_BYTES:
                    sent = self._send_frame(conn, {
                        "status": 400, "error": "bad_request",
                        "message": f"frame length {length} out of bounds",
                        "request_id": rec.rid, "trace_id": trace_of(rec),
                    })
                    self.finish_request(
                        rec, "bad_request" if sent else "conn_drop",
                        None, 400,
                    )
                    return
                data = _recv_exact(conn, length)
                if data is None:
                    # Client vanished mid-frame: the journey records the
                    # drop instead of silently evaporating.
                    self.finish_request(rec, "conn_drop", None, None)
                    return
                try:
                    payload = json.loads(data)
                    if not isinstance(payload, dict) or "x" not in payload:
                        raise ValueError("expected an object with 'x'")
                except ValueError as e:
                    sent = self._send_frame(conn, {
                        "status": 400, "error": "bad_request",
                        "message": str(e)[:200], "request_id": rec.rid,
                        "trace_id": trace_of(rec),
                    })
                    self.finish_request(
                        rec, "bad_request" if sent else "conn_drop",
                        None, 400,
                    )
                    continue
                rec.stamp("parsed")
                # The framed wire carries its trace id IN the payload
                # (no headers to ride): a well-formed client id replaces
                # the placeholder minted at the frame header; garbage
                # keeps the minted one — same contract as HTTP.
                raw_tid = payload.get("trace_id")
                if isinstance(raw_tid, str) and TRACE_ID_RE.match(raw_tid):
                    rec.note(trace_id=raw_tid)
                status, doc, tenant, outcome = self.serve_request(
                    rec, payload.get("key"), payload["x"],
                    payload.get("deadline_ms"),
                )
                if self.maybe_drop_connection():
                    self.finish_request(rec, "conn_drop", tenant, status)
                    return
                sent = self._send_frame(conn, {"status": status, **doc})
                self.finish_request(
                    rec, outcome if sent else "conn_drop", tenant, status
                )
                if not sent:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _send_frame(conn: socket.socket, doc: Dict[str, Any]) -> bool:
        frame = json.dumps(doc).encode()
        try:
            conn.sendall(struct.pack(">I", len(frame)) + frame)
            return True
        except (ConnectionError, socket.timeout, OSError):
            return False

    # -- the shared data-plane core -----------------------------------------

    def open_record(self, trace_hdr: Optional[str] = None) -> FlightRecord:
        """Open one network-leg journey at connection-accept time, before
        parsing — even an unparseable request leaves a record. A
        well-formed caller-supplied trace id is adopted; anything else
        (including nothing) gets a freshly minted one, so EVERY journey
        — conn_drops included — carries a trace id from its first
        stamp."""
        rec = self._flight.start(
            next_request_id(), 0, first_phase="accepted"
        )
        rec.note(trace_id=accept_trace_id(trace_hdr))
        with self._lock:
            self._active.add(rec.rid)
            self._inflight_gauge.set(len(self._active))
        return rec

    def maybe_drop_connection(self) -> bool:
        """The ``conn_drop`` fault site: True = pretend the client went
        away before the response write (data plane only)."""
        plan = self._plan
        return plan is not None and plan.check("conn_drop")

    def admit_request(
        self, rec: FlightRecord, key: Optional[str],
        deadline_ms: Optional[float] = None,
    ) -> Tuple[Optional[Tenant], Optional[Tuple[int, Dict[str, Any], str]]]:
        """Admission for one journey: ``(tenant, None)`` on success —
        journey stamped ``admitted``, slot taken — or
        ``(None, (status, doc, outcome))`` on rejection. Side-effect-ful
        (quota token + budget slot), so call exactly once per request.

        Admission chain order: auth (403) → tenant quota (429) → pending
        budget (429) → predicted deadline (429 ``predicted_infeasible``
        — only with a warm capacity model; the refused slot is released
        before returning, so a refusal costs no budget). ``deadline_ms``
        is the caller's explicit deadline when its transport already
        parsed one (the framed socket); the HTTP pre-admission path
        passes None and the tier default applies."""
        rid = rec.rid

        def rej(status: int, kind: str, message: str,
                outcome: Optional[str] = None):
            return None, (status, {
                "error": kind, "message": str(message)[:500],
                "request_id": rid, "trace_id": trace_of(rec),
            }, outcome or STATUS_OUTCOMES.get(status, "error"))

        try:
            tenant = self._admission.admit(key)
        except AuthError as e:
            return rej(403, "auth", str(e))
        except QuotaExceeded as e:
            return rej(429, "quota", str(e))
        except QueueFullError as e:
            return rej(429, "budget", str(e))
        rec.note(tenant=tenant.name, tier=tenant.tier)
        model = self._capacity
        if model is not None:
            # Offered-rate EWMA per tenant: fed at admission so the
            # autoscaler sees load the moment it arrives, not a full
            # journey later.
            model.observe_arrival(tenant.name)
            rejection = self._predict_admission(rec, tenant, deadline_ms,
                                                model, rej)
            if rejection is not None:
                return rejection
        rec.stamp("admitted")
        return tenant, None

    def _predict_admission(self, rec: FlightRecord, tenant: Tenant,
                           deadline_ms: Optional[float],
                           model: CapacityModel, rej):
        """The predicted-deadline admission leg: refuse (a counted 429,
        ``predicted_infeasible``, never silent) when the model predicts
        this request's completion past its deadline — BEFORE any device
        work. Cold model = no-op (counted); a refusal releases the
        admission slot admit() just took and is recorded for the model's
        strict-accuracy guard."""
        if not model.ready():
            capacity_counters.bump("model_cold_skips")
            return None
        if deadline_ms is None:
            eff_deadline = float(self._tier_deadline_ms[tenant.tier])
        else:
            try:
                eff_deadline = float(deadline_ms)
            except (TypeError, ValueError):
                return None  # garbage deadline: the 400 path owns it
        if eff_deadline <= 0:
            return None  # no deadline, nothing to breach
        _closed, g = self._route(tenant)
        svc = g.service
        depth = svc.queue_depth()
        pred = model.predict_completion_ms(
            tenant.tier, max(1, rec.rows), depth, svc.max_rows,
            bucket=bucket_for(max(1, rec.rows), g.engine.ladder),
        )
        if pred is None:
            return None
        rec.note(predicted_ms=round(pred["predicted_ms"], 3))
        if pred["predicted_ms"] <= eff_deadline:
            return None
        capacity_counters.bump("predicted_refusals")
        model.note_refusal(
            tenant.tier, max(1, rec.rows), depth, svc.max_rows,
            eff_deadline, pred["predicted_ms"], trace_id=trace_of(rec),
            bucket=pred["bucket"],
        )
        # The slot admit() took goes straight back: this request is
        # refused with None tenant, so finish_request will NOT release.
        self._admission.release()
        return rej(
            429, "predicted_infeasible",
            f"predicted completion {pred['predicted_ms']:.0f}ms breaches "
            f"the {eff_deadline:.0f}ms deadline before any device work "
            f"({pred['batches_ahead']} batch(es) ahead at "
            f"{pred['batch_ms']:.1f}ms modeled bucket-{pred['bucket']} "
            "latency); request refused fast",
            outcome="predicted_infeasible",
        )

    def serve_request(
        self, rec: FlightRecord, key: Optional[str], x_payload: Any,
        deadline_ms: Optional[float] = None,
        tenant: Optional[Tenant] = None,
    ) -> Tuple[int, Dict[str, Any], Optional[Tenant], str]:
        """Admit → submit → await, transport-agnostic. Returns
        ``(status, response doc, admitted tenant or None, outcome)``;
        the caller writes the response, applies the conn_drop site, and
        closes the journey via :meth:`finish_request`. A caller that
        already holds an admitted ``tenant`` (the HTTP pre-admission
        path) passes it in; admission then does NOT run again."""
        # Admission FIRST — before the (possibly multi-MB) payload is
        # even converted to an array: a rejected request must cost the
        # daemon as close to nothing as the transport allows. The HTTP
        # ingress pre-admits on the header key before even READING the
        # body and passes the tenant in; the framed-socket ingress must
        # read its frame regardless (to stay in sync) and admits here.
        if tenant is None:
            tenant, rejection = self.admit_request(rec, key, deadline_ms)
            if rejection is not None:
                status, doc, outcome = rejection
                return status, doc, None, outcome
        rid = rec.rid

        def terr(status: int, kind: str, message: str):
            # Post-admission failure: tenant rides along so
            # finish_request releases the admission slot.
            return status, {
                "error": kind, "message": message[:500], "request_id": rid,
                "tenant": tenant.name, "tier": tenant.tier,
                "trace_id": trace_of(rec),
            }, tenant, STATUS_OUTCOMES.get(status, "error")

        # Everything after admission runs inside ONE boundary: any
        # exception — enumerated or not (MemoryError on a huge payload,
        # a bug) — must return through terr so finish_request releases
        # the admitted slot. An escape here is a permanent slot leak.
        try:
            return self._serve_admitted(rec, tenant, x_payload,
                                        deadline_ms, terr)
        except Exception as e:  # lint: broad-ok any post-admission failure becomes this request's 500; the slot must release via terr
            return terr(500, "error", f"{type(e).__name__}: {e}")

    def _serve_admitted(self, rec: FlightRecord, tenant: Tenant,
                        x_payload: Any, deadline_ms: Optional[float],
                        terr) -> Tuple[int, Dict[str, Any],
                                       Optional[Tenant], str]:
        """The post-admission half of serve_request (caller owns the
        slot-releasing exception boundary)."""
        rid = rec.rid
        if deadline_ms is None:
            deadline_ms = float(self._tier_deadline_ms[tenant.tier])
        else:
            # Validated on the slot-releasing path: a garbage deadline
            # is a 400, never an exception that leaks the admitted slot.
            try:
                deadline_ms = float(deadline_ms)
            except (TypeError, ValueError):
                return terr(400, "bad_request",
                            f"deadline_ms must be a number, got "
                            f"{deadline_ms!r}")
        _closed, g = self._route(tenant)
        try:
            x = np.asarray(x_payload, dtype=g.engine.dtype)
        except (TypeError, ValueError) as e:
            return terr(400, "bad_request", f"unparseable payload: {e}")
        rows = int(x.shape[0]) if x.ndim > len(self._feature_shape) else 1
        rec.rows = rows

        # ONE absolute deadline across generation-chase replays: a
        # straggler replayed onto the swap successor keeps its REMAINING
        # budget, not a fresh window — the client's SLA does not reset
        # because we swapped, and a breached deadline must surface as
        # 504, never as a late 200 stacked SUBMIT_ATTEMPTS windows deep.
        deadline_abs = (
            time.monotonic() + deadline_ms / 1e3 if deadline_ms > 0
            else None
        )
        last_exc: Optional[BaseException] = None
        for _attempt in range(SUBMIT_ATTEMPTS):
            remaining_ms = 0.0
            if deadline_abs is not None:
                remaining_ms = (deadline_abs - time.monotonic()) * 1e3
                if remaining_ms <= 0:
                    return terr(504, "expired",
                                f"deadline {deadline_ms:.0f}ms passed "
                                "while landing on a live generation")
            # Per-attempt re-read, one lock hit (closed + routing in the
            # same acquisition): an A/B tenant chases its candidate
            # generation (which falls back to the live one the moment
            # the experiment promotes/aborts); everyone else chases the
            # live generation across swaps, exactly as before.
            closed, g = self._route(tenant)
            if closed:
                return terr(503, "closed", "daemon is closed")
            try:
                # The trace id crosses the daemon/service boundary here:
                # the service notes it on its own journey and stamps it
                # onto every tracer span for this request.
                fut = g.service.submit(x, deadline_ms=remaining_ms,
                                       trace_id=trace_of(rec),
                                       tier=tenant.tier)
            except QueueFullError as e:
                return terr(429, "queue_full", str(e))
            except DeadlineExceeded as e:
                return terr(504, "expired", str(e))
            except ValueError as e:
                return terr(400, "bad_request", str(e))
            except ServiceClosed as e:
                # Generation flip race: the service closed between the
                # self._gen read and the submit. Chase the successor.
                last_exc = e
                continue
            rec.stamp("submitted")
            timeout_s = (
                max(remaining_ms / 1e3 * 4, 1.0) if remaining_ms > 0
                else self._result_timeout_s
            )
            try:
                y = fut.result(timeout=timeout_s)
            except DeadlineExceeded as e:
                return terr(504, "expired", str(e))
            except (ServiceClosed, WorkerDiedError) as e:
                # Drained-out straggler of a closing generation (or a
                # restarted worker): the serve chain is pure, so replay
                # on the current generation — zero dropped requests
                # across a swap.
                last_exc = e
                continue
            except FutureTimeout:
                return terr(504, "timeout",
                            f"no result within {timeout_s:.1f}s")
            except Exception as e:  # lint: broad-ok device/serve failure of any kind becomes this request's 500; the ingress must survive
                return terr(500, "error", f"{type(e).__name__}: {e}")
            rec.note(generation=g.number)
            doc = {
                "y": np.asarray(y).tolist(),
                "generation": g.number,
                "request_id": rid,
                "tenant": tenant.name,
                "tier": tenant.tier,
                "trace_id": trace_of(rec),
            }
            return 200, doc, tenant, "ok"
        return terr(
            503, "closed",
            f"request could not land on a live generation after "
            f"{SUBMIT_ATTEMPTS} attempts: {last_exc}",
        )

    def finish_request(self, rec: FlightRecord, outcome: str,
                       tenant: Optional[Tenant], status: Optional[int] = None
                       ) -> None:
        """Close one journey exactly once per request: outcome + status
        onto the record, outcome counter, SLO accounting, the durable
        telemetry journey (bounded queue — drops counted, NEVER blocks),
        tier latency (ok only), admission slot release, and the unlocked
        flight-recorder poll."""
        if status is not None:
            rec.note(status=status)
        rec.finish(outcome)
        self._outcomes.bump(outcome)
        # SLO accounting needs a status to classify; a status-less
        # conn_drop (client vanished mid-frame, nothing served) has no
        # verdict to record. Client-caused statuses are excluded inside
        # observe().
        if status is not None:
            self._slo.observe(
                tenant.name if tenant is not None else "anonymous",
                tenant.tier if tenant is not None else "best_effort",
                int(status),
            )
        tel = self._telemetry
        if tel is not None:
            tel.journey(f"daemon-{self.name}", rec)
        if tenant is not None:
            if self._capacity is not None:
                self._observe_capacity(rec, tenant, outcome)
            self._admission.release()
            if outcome == "ok":
                t0 = rec.phases[0][1]
                self._tier_hist[tenant.tier].record(
                    max((time.perf_counter_ns() - t0) / 1e9, 1e-9)
                )
        with self._lock:
            self._active.discard(rec.rid)
            self._inflight_gauge.set(len(self._active))
        self._flight.poll()

    def _observe_capacity(self, rec: FlightRecord, tenant: Tenant,
                          outcome: str) -> None:
        """Feed one finished journey into the capacity model: the
        submitted→resolved leg (queue wait + device time as the tier
        experienced it), the bucket its rows pad to on the live ladder,
        and — when predicted-deadline admission priced it — the
        prediction, for the /stats predicted-vs-observed surface."""
        model = self._capacity
        if model is None:
            return
        t_sub = t_res = None
        for phase, t_ns in rec.phases:
            if phase == "submitted" and t_sub is None:
                t_sub = t_ns
            elif phase == "resolved":
                t_res = t_ns
        service_ms = (
            (t_res - t_sub) / 1e6
            if t_sub is not None and t_res is not None else None
        )
        meta = rec.meta or {}
        model.observe_journey(
            tier=tenant.tier,
            tenant=tenant.name,
            rows=max(1, rec.rows),
            bucket=rec.bucket if rec.bucket else bucket_for(
                max(1, rec.rows), self._gen.engine.ladder
            ),
            service_ms=service_ms,
            outcome=outcome,
            predicted_ms=meta.get("predicted_ms"),
        )

    # -- traffic-aware autoscaling (capacity re-plan loop) -------------------

    def _replan_loop(self) -> None:
        """The autoscale worker: wake every ``KEYSTONE_CAPACITY_REPLAN_S``
        seconds, compare the observed bucket mix with the mix the last
        re-plan acted on, and re-size the replica pool / re-price the
        ladder when the shift crosses ``REPLAN_MIX_SHIFT``. Never dies:
        a re-plan failure is logged and the next tick retries."""
        period = max(0.1, float(config.capacity_replan_s))
        while not self._replan_stop.wait(period):
            try:
                self._maybe_replan()
            except Exception:  # lint: broad-ok a re-plan failure must not kill the loop; the daemon keeps serving on the old plan
                logger.exception(
                    "daemon %s: capacity re-plan failed; serving "
                    "continues on the previous plan", self.name,
                )

    def _maybe_replan(self) -> None:
        """One autoscale evaluation (called from the re-plan loop and,
        in tests, directly): cold model and too-small mix shifts no-op;
        a triggered re-plan inside the no-flap window is refused and
        counted; an executed re-plan resizes replicas toward the
        offered-load estimate, re-prices the ladder from the observed
        mix through the PR-13 planner, and decision-logs itself."""
        from keystone_tpu.workflow.rules import record_decision

        model = self._capacity
        if model is None:
            return
        if not model.ready():
            capacity_counters.bump("model_cold_skips")
            return
        mix = model.traffic_mix()
        if not mix:
            return
        if not self._capacity_last_mix:
            # First warm tick: baseline the mix, nothing to compare yet.
            self._capacity_last_mix = mix
            return
        shift = CapacityModel.mix_shift(mix, self._capacity_last_mix)
        if shift < REPLAN_MIX_SHIFT:
            return
        now = time.monotonic()
        window = 2.0 * max(0.1, float(config.capacity_replan_s))
        if now - self._last_replan_t < window:
            # No-flap guard: two consecutive re-plans within the window
            # refuse — counted and decision-logged, never silent.
            capacity_counters.bump("replans_suppressed")
            record_decision(
                rule="CapacityReplan", node=self.name,
                action="suppress",
                provenance="capacity",
                reason=(
                    f"mix shifted {shift:.2f} but the last re-plan ran "
                    f"{now - self._last_replan_t:.1f}s ago (no-flap "
                    f"window {window:.1f}s)"
                ),
            )
            return
        g = self._gen
        svc, engine = g.service, g.engine
        # Replica sizing: offered req/s against the modeled throughput
        # of one replica at the modal rung, 20% headroom, clamped to
        # the device pool the engine was built over.
        rate = model.arrival_rate()
        modal = max(mix, key=mix.get)
        batch_ms = model.predict_batch_ms(modal, q=0.5)
        pool = len(engine.replicas)
        svc_stats = svc.stats()["replicas"]
        live = sum(
            1 for i in range(svc_stats["count"])
            if not svc_stats["retired"][i]
        )
        desired = live
        if batch_ms and batch_ms > 0 and rate > 0:
            per_replica_rps = max(1, modal) / (batch_ms / 1e3)
            desired = max(1, min(pool, int(
                1 + (1.2 * rate) // max(per_replica_rps, 1e-9)
            )))
        resized = 0
        if desired > live:
            grow = [
                i for i in range(svc_stats["count"])
                if svc_stats["retired"][i]
            ][: desired - live]
            if grow:
                svc.unretire_replicas(grow)
                resized = len(grow)
        elif desired < live:
            for i in range(svc_stats["count"] - 1, -1, -1):
                if live - resized <= desired:
                    break
                if not svc_stats["retired"][i] and svc.retire_replica(i):
                    resized += 1
        if resized:
            capacity_counters.bump("replicas_resized")
        # Ladder re-price from the MIX (not just the shape): keep rungs
        # the traffic actually arrives at (>= 2% of the mix), always
        # keep the top candidate rung (oversize coverage), and let the
        # engine push the survivors back through the HBM planner.
        base = [int(b) for b in engine.base_ladder]
        wanted = sorted({
            b for b in base
            if mix.get(b, 0.0) >= 0.02 or b == base[-1]
        })
        repriced = engine.reprice_ladder(wanted)
        action = (
            f"replicas={live}->{live + (resized if desired > live else -resized)};"
            f"ladder={','.join(str(b) for b in engine.ladder)}"
        )
        reason = (
            f"observed mix shifted {shift:.2f} (TV distance) past "
            f"{REPLAN_MIX_SHIFT}; modal bucket {modal}, offered "
            f"{rate:.1f} req/s"
        )
        capacity_counters.bump("replans")
        record_decision(
            rule="CapacityReplan", node=self.name, action=action,
            provenance="capacity", reason=reason,
            cost={
                "mix_shift": round(shift, 4),
                "modal_bucket": int(modal),
                "offered_rps": round(rate, 3),
                "replicas_resized": resized,
                "ladder_repriced": bool(repriced),
            },
        )
        self._capacity_last_mix = mix
        self._last_replan_t = now
        self._last_replan = {
            "action": action,
            "reason": reason,
            "mix_shift": round(shift, 4),
            "t_monotonic": now,
        }
        # Persistence cadence: each executed re-plan checkpoints the
        # model through the telemetry segments (bounded queue, never
        # blocks), so a crash between re-plans loses little learning.
        model.save(self._telemetry)
        logger.info(
            "daemon %s: capacity re-plan — %s (%s)",
            self.name, action, reason,
        )

    # -- hot swap ------------------------------------------------------------

    def request_swap(self, artifact_path: str, wait: bool = True,
                     timeout_s: Optional[float] = None,
                     expect_fingerprint: Optional[str] = None,
                     trace_id: Optional[str] = None):
        """Queue a hot swap to the artifact at ``artifact_path``.
        ``wait=True`` (default) blocks for the result — the new
        generation number — re-raising the swap's failure;
        ``wait=False`` returns the Future. Swaps serialize on the swap
        worker thread: one at a time, in request order. ``trace_id``
        correlates this swap with whatever initiated it (the online
        trainer mints one per refresh) in spans and telemetry."""
        fut: Future = Future()
        with self._lock:
            # Check AND enqueue under the one lock close() takes: a put
            # landing after close() drained the queue would leave this
            # future unresolved forever (put never blocks — unbounded
            # queue — so holding the lock here is safe).
            if self._closed:
                raise ServiceClosed("daemon is closed")
            self._swap_q.put(
                (str(artifact_path), expect_fingerprint, trace_id, fut)
            )
        if not wait:
            return fut
        if timeout_s is None:
            timeout_s = config.swap_timeout_ms / 1e3
        return fut.result(timeout=timeout_s)

    def _swap_loop(self) -> None:
        """Swap worker thread: serializes hot swaps; a failed swap
        becomes the requester's exception, never this thread's death."""
        while True:
            item = self._swap_q.get()
            if item is None:
                return
            path, expect_fp, trace_id, fut = item
            try:
                fut.set_result(self._do_swap(path, expect_fp, trace_id))
            except BaseException as e:  # lint: broad-ok any swap failure becomes the requester's exception; the swap worker must survive
                fut.set_exception(e)

    def _do_swap(self, path: str,
                 expect_fingerprint: Optional[str] = None,
                 trace_id: Optional[str] = None) -> int:
        t0 = time.perf_counter_ns()
        with self._lock:
            # Captured UNDER the lock: promote_ab() flips self._gen
            # outside the serialized swap worker, so an unlocked read
            # here could drain/rollback a generation that is no longer
            # the live one.
            old = self._gen
            if self._closed:
                raise ServiceClosed("daemon closed; swap abandoned")
            if self._ab_gen is not None:
                # A full swap would strand the experiment's candidate
                # (and its tenants' routing) mid-flight: resolve the A/B
                # first — promote it or abort it, explicitly.
                raise RuntimeError(
                    "an A/B experiment is active; promote_ab() or "
                    "abort_ab() before a full swap"
                )
            self._draining = True
            # Past any number that ever served (an aborted A/B burned
            # its number): attribution stays unambiguous.
            number = max(old.number, self._gen_hwm) + 1
        retired: List[int] = []
        try:
            art = load_artifact(path, expect_fingerprint=expect_fingerprint)
            engine = self._build_engine(art.pipeline, number)
            for i in range(len(engine.replicas)):
                if self._plan is not None:
                    self._plan.maybe_raise("swap_abort")
                # Warm the successor's replica i, then drain the
                # outgoing generation's replica i (re-queue machinery;
                # refused for the last live replica — the old
                # generation answers until the flip).
                engine.warmup(self._feature_shape, dtype=self._dtype,
                              replica=i)
                if old.service.retire_replica(i):
                    retired.append(i)
            if self._swap_hook is not None:
                self._swap_hook(self)
            service = self._build_service(engine, number)
            new = Generation(number, art.fingerprint, engine, service,
                             art.header())
            with self._lock:
                closed = self._closed
                if not closed:
                    self._gen = new
                    self._gen_hwm = max(self._gen_hwm, number)
                    self._draining = False
                    self.swaps += 1
            if closed:
                # close() raced this swap: never flip onto a closed
                # daemon — the successor's threads would live forever
                # behind a service nothing will ever close.
                service.close(drain=False)
                raise ServiceClosed("daemon closed mid-swap; rolled back")
            # The drain primitive: the old generation serves everything
            # already queued/in flight, then dies. Stragglers it fails
            # (drain bound exceeded) replay onto the new generation in
            # serve_request's ServiceClosed retry.
            old.service.close(drain=True,
                              join_s=config.swap_drain_ms / 1e3)
            # Swap observability: one span (trace-correlated when the
            # refresh that triggered it minted a trace id) plus a
            # durable record, so the offline timeline shows WHEN the
            # model changed between the request journeys around it.
            tracer = active_tracer()
            if tracer is not None:
                tracer.record(
                    "daemon.swap", "serving", t0,
                    trace_id=trace_id, from_generation=old.number,
                    generation=number,
                )
            tel = self._telemetry
            if tel is not None:
                tel.emit({
                    "kind": "swap",
                    "service": f"daemon-{self.name}",
                    "pid": tel.pid,
                    "trace_id": trace_id,
                    "from_generation": old.number,
                    "generation": number,
                    "artifact": os.path.basename(path),
                    "fingerprint": art.fingerprint,
                    "start_ns": t0,
                    "end_ns": time.perf_counter_ns(),
                })
            logger.info(
                "daemon %s: hot-swapped generation %d -> %d "
                "(artifact %s, %d replica(s) handed over incrementally)",
                self.name, old.number, number, art.fingerprint[:12],
                len(retired),
            )
            return number
        except BaseException as e:
            with self._lock:
                self._draining = False
                self.swap_failures += 1
                inflight_ids = sorted(self._active)
            # Rollback, not outage: retired replicas revive, the old
            # generation keeps serving, and the black box records who
            # was in flight when the swap died.
            old.service.unretire_replicas(retired)
            self._flight.error(
                "swap_abort",
                f"swap to {os.path.basename(path)} failed; generation "
                f"{old.number} keeps serving; in-flight request ids "
                f"{inflight_ids}: {type(e).__name__}: {e}",
            )
            self._flight.dump("swap_abort", force=True)
            logger.warning(
                "daemon %s: swap to %s FAILED (%s); rolled back to "
                "generation %d (%d in-flight request(s) unaffected)",
                self.name, path, type(e).__name__, old.number,
                len(inflight_ids),
            )
            raise

    # -- A/B serving ---------------------------------------------------------

    def _route(self, tenant: Optional[Tenant]) -> Tuple[bool, Generation]:
        """(closed, generation answering this tenant) under ONE lock
        acquisition — THE tenant→generation routing rule (A/B candidate
        for enrolled tenants while an experiment is active, the live
        generation otherwise), shared by the dtype read and every submit
        attempt so the two can never diverge."""
        with self._lock:
            closed = self._closed
            ab = self._ab_gen
            if (
                ab is not None and tenant is not None
                and tenant.name in self._ab_tenants
            ):
                return closed, ab
            return closed, self._gen

    def ab_swap(self, artifact_path: str, tenants,
                expect_fingerprint: Optional[str] = None) -> int:
        """Serve a CANDIDATE artifact to only the named tenants — two
        generations answering from one replica pool. The candidate's
        ladder AOT-warms fully before any routed traffic; nothing about
        the live generation changes. Returns the candidate generation
        number. Resolve with :meth:`promote_ab` / :meth:`abort_ab`."""
        # Accept tenant NAMES or Tenant objects — str(Tenant) is an
        # object repr that would match nobody, silently serving the
        # candidate zero traffic.
        names = frozenset(
            t.name if isinstance(t, Tenant) else str(t) for t in tenants
        )
        if not names:
            raise ValueError("ab_swap needs at least one tenant name")
        # Validate against the admission table: a typo'd name would pass
        # every guard yet enroll nobody — an experiment silently serving
        # the candidate zero traffic while stats() claims it is active.
        known = (
            {self._admission._anonymous.name} if self._admission.open_mode
            else {t.name for t in self._admission.tenants.values()}
        )
        unknown = names - known
        if unknown:
            raise ValueError(
                f"ab_swap tenant(s) {sorted(unknown)} not in the "
                f"admission table (known: {sorted(known)})"
            )
        with self._lock:
            if self._closed:
                raise ServiceClosed("daemon is closed")
            if self._draining:
                raise RuntimeError("a full swap is in progress")
            if self._ab_gen is not None:
                raise RuntimeError(
                    "an A/B experiment is already active; promote_ab() "
                    "or abort_ab() first"
                )
        art = load_artifact(str(artifact_path),
                            expect_fingerprint=expect_fingerprint)
        with self._lock:
            number = max(self._gen.number, self._gen_hwm) + 1
        engine = self._build_engine(art.pipeline, number)
        engine.warmup(self._feature_shape, dtype=self._dtype)
        service = self._build_service(engine, number)
        cand = Generation(number, art.fingerprint, engine, service,
                          art.header())
        with self._lock:
            closed = self._closed
            # Re-checked in the COMMIT section: a full swap that ran
            # during the slow load/warmup above advanced the live
            # generation (or is mid-drain) — installing the candidate
            # now would reuse a number and bypass the
            # refused-mid-experiment invariant from the other side.
            raced = (
                closed or self._ab_gen is not None or self._draining
                or max(self._gen.number, self._gen_hwm) + 1 != number
            )
            if not raced:
                self._ab_gen = cand
                self._ab_tenants = names
                # The candidate starts serving NOW: its number is burned.
                self._gen_hwm = max(self._gen_hwm, number)
        if raced:
            service.close(drain=False)
            if closed:
                raise ServiceClosed("daemon closed during ab_swap")
            raise RuntimeError(
                "a concurrent swap or A/B landed during ab_swap; the "
                "candidate was discarded — retry against the new live "
                "generation"
            )
        logger.info(
            "daemon %s: A/B candidate generation %d (artifact %s) serving "
            "tenant(s) %s; generation %d stays live for the rest",
            self.name, number, art.fingerprint[:12], sorted(names),
            self._gen.number,
        )
        return number

    def promote_ab(self) -> int:
        """Make the A/B candidate the live generation for EVERY tenant.
        The outgoing generation drains with the normal swap guarantee
        (stragglers replay on the successor; zero dropped requests)."""
        with self._lock:
            cand = self._ab_gen
            if cand is None:
                raise RuntimeError("no A/B experiment is active")
            self._ab_gen = None
            self._ab_tenants = frozenset()
            old = self._gen
            self._gen = cand
            self.swaps += 1
        old.service.close(drain=True, join_s=config.swap_drain_ms / 1e3)
        logger.info(
            "daemon %s: A/B candidate promoted — generation %d -> %d",
            self.name, old.number, cand.number,
        )
        return cand.number

    def abort_ab(self) -> None:
        """End the experiment: drain the candidate (its in-flight
        requests replay on the live generation) and route every tenant
        back to the live generation."""
        with self._lock:
            cand = self._ab_gen
            self._ab_gen = None
            self._ab_tenants = frozenset()
        if cand is None:
            return
        cand.service.close(drain=True, join_s=config.swap_drain_ms / 1e3)
        logger.info(
            "daemon %s: A/B candidate generation %d aborted; generation "
            "%d serves everyone", self.name, cand.number, self._gen.number,
        )

    # -- surfaces ------------------------------------------------------------

    @property
    def admission_open(self) -> bool:
        """True in open mode (no tenants configured): every request is
        the anonymous best-effort tenant, so the HTTP ingress can
        pre-admit before reading the body even without a header key."""
        return self._admission.open_mode

    @property
    def generation(self) -> int:
        return self._gen.number

    @property
    def artifact_fingerprint(self) -> str:
        return self._gen.fingerprint

    def health_stats(self) -> Dict[str, Any]:
        """The /healthz source (also pluggable into
        ``tools/metrics_server.py`` as ``health_source``): the live
        generation's service stats plus the daemon's generation /
        artifact / draining identity."""
        g = self._gen
        with self._lock:
            draining = self._draining
            closed = self._closed
        s = g.service.stats()
        s["generation"] = g.number
        s["artifact_fingerprint"] = g.fingerprint
        s["draining"] = draining
        if closed:
            s["closed"] = True
        return s

    def stats(self, redact_tenants: bool = False) -> Dict[str, Any]:
        g = self._gen
        with self._lock:
            active = len(self._active)
            draining = self._draining
            closed = self._closed
            swaps = self.swaps
            swap_failures = self.swap_failures
            ab_gen = self._ab_gen
            ab_tenants = sorted(self._ab_tenants)
        admission = self._admission.stats()
        if redact_tenants:
            admission["tenants"] = len(admission["tenants"])
        ab = None
        if ab_gen is not None:
            ab = {
                "generation": ab_gen.number,
                "artifact_fingerprint": ab_gen.fingerprint,
                # Tenant names are admission metadata: redacted to a
                # count for anonymous /stats callers like the table.
                "tenants": len(ab_tenants) if redact_tenants else ab_tenants,
            }
        engine_stats = g.engine.stats()
        return {
            "name": self.name,
            "generation": g.number,
            "artifact_fingerprint": g.fingerprint,
            "artifact": dict(g.artifact_header),
            "draining": draining,
            "closed": closed,
            "swaps": swaps,
            "swap_failures": swap_failures,
            "ab": ab,
            "active_requests": active,
            "http_port": self.http_port,
            "socket_port": self.socket_port,
            "feature_shape": list(self._feature_shape),
            # What the memory planner chose for the live generation's
            # engine — resolved ladder, serving precision, per-bucket
            # planned bytes, HBM budget/headroom, trims — so an operator
            # can see the plan on the wire without digging into the
            # nested service stats.
            "serve_plan": {
                k: engine_stats[k] for k in ("ladder", "precision", "plan")
            },
            "tier_deadline_ms": dict(self._tier_deadline_ms),
            "admission": admission,
            "outcomes": self._outcomes.snapshot(),
            # Per-tier e2e latency percentiles (the /metrics histograms,
            # surfaced next to the SLO block they explain).
            "latency": {
                tier: hist.snapshot()
                for tier, hist in self._tier_hist.items()
            },
            # Per-tenant/tier deadline-hit rate + error-budget burn over
            # the rolling window; anonymous callers get tenant names
            # collapsed (same redaction contract as the admission table).
            "slo": self._slo.snapshot(redact_tenants=redact_tenants),
            # The learned capacity model: freshness, per-bucket
            # predicted-vs-observed p99, guard accounting, and the last
            # autoscale decision. Tenant arrival rates follow the SLO
            # redaction contract for anonymous callers.
            "capacity": (
                dict(
                    self._capacity.stats(redact_tenants=redact_tenants),
                    enabled=True,
                    last_replan=self._last_replan,
                )
                if self._capacity is not None else {"enabled": False}
            ),
            "telemetry": (
                self._telemetry.stats()
                if self._telemetry is not None else None
            ),
            "flight": self._flight.stats(),
            "service": g.service.stats(),
        }

    def debug_dump(self, path: Optional[str] = None) -> Optional[str]:
        """Dump the daemon's network-leg black box NOW (no rate limit)."""
        return self._flight.dump("debug", path=path, force=True)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Stop ingress, the swap worker, and the live generation's
        service (drained — no future stranded). Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._replan_stop.set()
        if self._replan_thread is not None:
            self._replan_thread.join(timeout=self.CLOSE_JOIN_S)
        self._swap_q.put(None)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._http_thread is not None:
            self._http_thread.join(timeout=self.CLOSE_JOIN_S)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=self.CLOSE_JOIN_S)
        self._swap_thread.join(timeout=self.CLOSE_JOIN_S)
        # A swap enqueued between the closed check and our sentinel
        # landed BEHIND the sentinel and will never run: fail its
        # future instead of leaving the requester blocked.
        while True:
            try:
                item = self._swap_q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            fut = item[-1]
            try:
                fut.set_exception(
                    ServiceClosed("daemon closed; swap abandoned")
                )
            except InvalidStateError:
                pass  # a racing _swap_loop already resolved it
        # If a long in-progress swap outlived the join above, the drain
        # loop just consumed ITS shutdown sentinel: re-seed it so the
        # swap worker's next get() exits instead of parking forever on
        # an empty queue (a stale sentinel in an already-exited worker's
        # queue is harmless).
        self._swap_q.put(None)
        with self._lock:
            ab = self._ab_gen
            self._ab_gen = None
            self._ab_tenants = frozenset()
        if ab is not None:
            ab.service.close(drain=True)
        self._gen.service.close(drain=True)
        # Durable telemetry epilogue: the span trees for traced requests
        # are exported ONCE here (per-request journey records already
        # streamed live), then the queue is drained so the offline view
        # reconstructs the full timeline from KEYSTONE_TELEMETRY_DIR
        # alone after this process exits. The process-wide log itself
        # stays open — other components (another daemon, the trainer)
        # may still be writing.
        tel = self._telemetry
        if tel is not None:
            # Final capacity snapshot BEFORE the drain: the successor
            # daemon restores the fitted model from this record instead
            # of relearning from zero.
            if self._capacity is not None:
                self._capacity.save(tel)
            tracer = active_tracer()
            if tracer is not None:
                tel.spans(tracer)
            tel.drain(timeout=self.CLOSE_JOIN_S)

    def __enter__(self) -> "ServingDaemon":
        return self

    def __exit__(self, *exc):
        self.close()
        return False
