"""Online learning: incremental fit on live streams + continuous refresh.

KeystoneML's normal-equation solvers carry their sufficient statistics as
running sums — the ``gram``/``atb`` accumulators of
``linalg/normal_equations.py``, exactly the state the streaming-solve
checkpoints already snapshot. This module is the subsystem that keeps a
model *current* from those sums: fold new labeled batches into retained
accumulators (the streamed map-reduce shape DrJAX formalizes,
arXiv:2403.07128, with the psum'd per-chunk gram of arXiv:2112.09017),
re-solve cheaply through the existing Cholesky path, and push refreshed
weights through the serving daemon's hot-swap with zero dropped requests.

Three layers:

- :class:`OnlineState` — the retained sufficient statistic
  (gram / AᵀB / column sums / effective row count) plus the identity that
  guards it (feature width, label tail, dtypes, mesh manifest). The fold
  is **grouping-invariant by construction**: rows buffer host-side and
  accumulate in fixed ``chunk_rows`` pieces at absolute stream phase, so
  ``partial_fit`` over K batches is bit-identical to one ``partial_fit``
  over their concatenation — no matter how the stream was batched, and
  no matter whether batches arrived sharded or on one device (every
  chunk re-shards through ``RowMatrix``, the placement-invariance rule
  of the data-parallel fit).
- estimator ``partial_fit`` / ``solve_online`` (``LinearMapEstimator``,
  ``BlockLeastSquaresEstimator``, ``LeastSquaresEstimator``) — thin
  wrappers over :func:`partial_fit_step` + :meth:`OnlineState.solve`.
- :class:`OnlineTrainer` — the refresh loop: folds submitted batches,
  and on a cadence (``KEYSTONE_ONLINE_REFRESH_MS``) re-solves,
  serializes a versioned ``ModelArtifact``, and hot-swaps it into a
  live ``ServingDaemon`` via ``request_swap``. A failed refresh (the
  ``refresh_abort``/``swap_abort`` fault sites, a bad artifact) is
  counted and the old generation keeps serving; with a
  ``checkpoint_dir`` the accumulator state snapshots after every fold
  and a killed trainer resumes **bit-identically** (the
  ``_stream_fingerprint`` contract: state folded under one mesh width
  migrates onto another via ``utils.mesh.reshard_state`` — elastic
  mesh, default on, counted — or refuses typed, never a wrong answer).

Forgetting modes (exclusive):

- **time-decay** (``decay=γ``, ``KEYSTONE_ONLINE_DECAY``): each
  ``partial_fit`` call first scales every retained sum by γ, so a batch
  folded a calls ago carries weight γ^a — the exponentially-weighted
  ridge problem (oracle-pinned in tests/test_online.py).
- **sliding window** (``window=k``, ``KEYSTONE_ONLINE_WINDOW``): each
  ``partial_fit`` call is one window unit kept in a per-window
  accumulator ring; when the ring exceeds k the oldest unit's sums are
  subtracted from the running totals (subtract-on-evict, counted as
  ``windows_evicted``). Note K-vs-concat bit-identity intentionally
  does not apply here: the window unit IS the call.

Observability: every fold / re-solve / refresh / eviction lands in the
``online`` registry family (:class:`~keystone_tpu.utils.metrics.OnlineCounters`),
riding ``/metrics`` like every other counter set.

Typed refusal: :class:`OnlineStateError` when a fold's feature width,
label tail, dtype identity, or mesh manifest mismatches the retained
state — folding apples into orange accumulators is never a warning.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, List, Optional, Tuple

import numpy as np

from keystone_tpu.utils.mesh import register_reshard_adapter
from keystone_tpu.utils.telemetry import active_telemetry, mint_trace_id

logger = logging.getLogger("keystone_tpu")

_STATE_KEY = "online_state"

#: Canonical fold granularity (rows) — part of the state's identity:
#: two states with different chunking produce different (both valid)
#: accumulation groupings, so the chunk size rides the fingerprint.
DEFAULT_CHUNK_ROWS = 512


class OnlineStateError(ValueError):
    """A fold (or solve) cannot proceed: the batch's feature width,
    label tail, dtype identity, or mesh manifest does not match the
    retained accumulators, decay/window were combined, or the state is
    empty. Typed so callers can distinguish 'wrong data for this state'
    from a numerical failure."""


def supports_partial_fit(est: Any) -> bool:
    """True when ``est`` implements the online contract
    (``partial_fit`` + ``solve_online``, both callable). Estimators that
    inherit the methods but cannot honor them (class-weighted problems
    whose weights need the full label set) null them out."""
    return callable(getattr(est, "partial_fit", None)) and callable(
        getattr(est, "solve_online", None)
    )


def _online_counters():
    from keystone_tpu.utils.metrics import online_counters

    return online_counters


class OnlineState:
    """Retained normal-equation sufficient statistics for one problem.

    Accumulators are host ``float64`` (exact round-trip through
    checkpoints; per-chunk device contributions are f32 — adding them in
    f64 in a fixed order is what makes the fold deterministic). The
    device work per chunk is the placement-invariant ``RowMatrix``
    program set: fused gram+AᵀB plus the psum'd column sums the
    intercept means ride — sharded and single-device folds are
    bit-identical because both re-shard onto the same mesh.

    Thread-safety: instances are NOT internally locked; the
    ``OnlineTrainer`` (the one concurrent consumer) serializes access
    under its own lock.
    """

    def __init__(self, d: int, b_tail: Tuple[int, ...],
                 chunk_rows: Optional[int] = None,
                 window: Optional[int] = None):
        from keystone_tpu.config import config
        from keystone_tpu.utils.mesh import num_data_shards

        if window is not None and int(window) <= 0:
            raise OnlineStateError("window must be a positive batch count")
        self.d = int(d)
        self.b_tail = tuple(int(t) for t in b_tail)
        if len(self.b_tail) > 1:
            # The intercept's rank-one centering (np.outer) supports
            # scalar and vector label tails — refuse what solve() could
            # not honor rather than crashing there later.
            raise OnlineStateError(
                f"online fits take scalar or vector labels per row, got "
                f"label tail {self.b_tail}"
            )
        self.chunk_rows = int(chunk_rows or DEFAULT_CHUNK_ROWS)
        self.window = None if window is None else int(window)
        # Mesh manifest + dtype identity, captured at creation: a fold or
        # resume under a different mesh/dtype regime is refused, never
        # silently blended (the _stream_fingerprint rule).
        self.device_count = int(num_data_shards())
        self.data_axis = str(config.data_axis)
        self.default_dtype = str(config.default_dtype)
        self.accum_dtype = str(config.accum_dtype)
        k_shape = self.b_tail or ()
        self.gram = np.zeros((self.d, self.d), dtype=np.float64)
        self.atb = np.zeros((self.d,) + k_shape, dtype=np.float64)
        self.x_sum = np.zeros((self.d,), dtype=np.float64)
        self.y_sum = np.zeros(k_shape, dtype=np.float64)
        #: Effective row count of the FOLDED chunks (a float: decay
        #: turns it into Σ weights). Rows still buffered pending a full
        #: chunk are not in here — ``total_rows`` counts both.
        self.rows = 0.0
        self.folds = 0
        self.decays = 0
        # Pending rows not yet a full chunk (host copies, < chunk_rows).
        self._pend_x: List[np.ndarray] = []
        self._pend_y: List[np.ndarray] = []
        self._pend_rows = 0
        # Sliding-window ring: one (gram, atb, x_sum, y_sum, rows) tuple
        # per partial_fit call, newest last.
        self._ring: List[Tuple] = []

    # -- construction ------------------------------------------------------

    @classmethod
    def for_batch(cls, X, Y, chunk_rows: Optional[int] = None,
                  window: Optional[int] = None) -> "OnlineState":
        """A fresh state shaped for (X, Y)'s problem."""
        X = np.asarray(X)
        Y = np.asarray(Y)
        if X.ndim != 2:
            raise OnlineStateError(
                f"online fits take 2-D feature batches, got shape {X.shape}"
            )
        return cls(X.shape[1], tuple(Y.shape[1:]), chunk_rows=chunk_rows,
                   window=window)

    @property
    def total_rows(self) -> float:
        """Effective rows including the pending (not-yet-chunked) buffer
        — the emptiness test every solve/refresh guard uses."""
        return self.rows + float(self._pend_rows)

    # -- identity ----------------------------------------------------------

    def fingerprint(self) -> dict:
        """The state's problem + mesh identity (the checkpoint binding,
        shaped like ``_stream_fingerprint`` so the one mesh-manifest
        refusal rule covers it)."""
        return {
            "d": self.d,
            "b_tail": tuple(self.b_tail),
            "chunk_rows": self.chunk_rows,
            "window": self.window,
            "default_dtype": self.default_dtype,
            "accum_dtype": self.accum_dtype,
            "device_count": self.device_count,
            "data_axis": self.data_axis,
        }

    def _check_fold(self, X: np.ndarray, Y: np.ndarray) -> None:
        from keystone_tpu.config import config
        from keystone_tpu.utils.mesh import num_data_shards

        if X.ndim != 2 or X.shape[1] != self.d:
            raise OnlineStateError(
                f"fold of feature width {X.shape[1:]} into retained "
                f"width-{self.d} accumulators refused"
            )
        if tuple(Y.shape[1:]) != self.b_tail:
            raise OnlineStateError(
                f"fold of label tail {tuple(Y.shape[1:])} into retained "
                f"{self.b_tail} accumulators refused"
            )
        if X.shape[0] != Y.shape[0]:
            raise OnlineStateError(
                f"feature/label row mismatch: {X.shape[0]} vs {Y.shape[0]}"
            )
        mesh_now = (int(num_data_shards()), str(config.data_axis))
        if mesh_now != (self.device_count, self.data_axis):
            raise OnlineStateError(
                f"fold under mesh {mesh_now} into accumulators folded "
                f"under ({self.device_count}, {self.data_axis!r}) refused "
                "— migrate the state onto the current mesh via "
                "utils.mesh.reshard_state (snapshot/from_snapshot does "
                "this automatically with elastic mesh on), or fold on "
                "the recording mesh width; the retained work is "
                "recoverable"
            )
        dtypes_now = (str(config.default_dtype), str(config.accum_dtype))
        if dtypes_now != (self.default_dtype, self.accum_dtype):
            raise OnlineStateError(
                f"fold under dtypes {dtypes_now} into accumulators folded "
                f"under ({self.default_dtype}, {self.accum_dtype}) refused"
            )

    # -- folding -----------------------------------------------------------

    def _chunk_stats(self, Xc: np.ndarray, Yc: np.ndarray) -> Tuple:
        """One canonical chunk's device-computed contribution, pulled to
        host f64. The RowMatrix programs re-shard onto the default mesh
        (per-shard gemm + psum), so the bits do not depend on where the
        caller's batch lived."""
        from keystone_tpu.linalg.row_matrix import RowMatrix

        A = RowMatrix.from_array(Xc)
        B = RowMatrix.from_array(Yc)
        g, ab = A.gram_and_atb(B)
        xs = A.col_sums()
        ys = B.col_sums()
        return (
            np.asarray(g, dtype=np.float64),
            np.asarray(ab, dtype=np.float64),
            np.asarray(xs, dtype=np.float64),
            np.asarray(ys, dtype=np.float64),
            float(Xc.shape[0]),
        )

    def _add(self, stats: Tuple) -> None:
        g, ab, xs, ys, n = stats
        self.gram += g
        self.atb += ab
        self.x_sum += xs
        self.y_sum += ys
        self.rows += n

    def _sub(self, stats: Tuple) -> None:
        g, ab, xs, ys, n = stats
        self.gram -= g
        self.atb -= ab
        self.x_sum -= xs
        self.y_sum -= ys
        self.rows -= n

    def fold(self, X, Y) -> "OnlineState":
        """Fold one labeled batch into the retained accumulators.

        Infinite-horizon mode buffers rows and accumulates full
        ``chunk_rows`` pieces at absolute stream phase — the mechanism
        behind the K-batches-vs-concatenation bit-identity contract.
        Window mode folds the call as one self-contained window unit
        (phase resets per call) and evicts the oldest unit past the
        window length.
        """
        X = np.asarray(X)
        Y = np.asarray(Y)
        self._check_fold(X, Y)
        if X.shape[0] == 0:
            raise OnlineStateError("empty batch fold refused")
        if self.window is not None:
            stats = self._call_stats(X, Y)
            self._ring.append(stats)
            self._add(stats)
            while len(self._ring) > self.window:
                self._sub(self._ring.pop(0))
                _online_counters().bump("windows_evicted")
        else:
            # Defensive copies: a sub-chunk batch stays BUFFERED past
            # this call, and np.asarray of a host array is a view — a
            # caller reusing one preallocated batch buffer would
            # otherwise silently corrupt the pending rows before they
            # fold (and break the grouping-invariance contract).
            self._pend_x.append(np.array(X, copy=True))
            self._pend_y.append(np.array(Y, copy=True))
            self._pend_rows += int(X.shape[0])
            self._drain_pending()
        self.folds += 1
        _online_counters().bump("batches_folded")
        return self

    def _call_stats(self, X: np.ndarray, Y: np.ndarray) -> Tuple:
        """One call's total contribution via the same canonical chunk
        decomposition, phase 0 (window units are self-contained)."""
        total = None
        for s in range(0, X.shape[0], self.chunk_rows):
            stats = self._chunk_stats(X[s:s + self.chunk_rows],
                                      Y[s:s + self.chunk_rows])
            if total is None:
                total = list(stats)
            else:
                total = [a + b for a, b in zip(total, stats)]
        return tuple(total)

    def _drain_pending(self) -> None:
        while self._pend_rows >= self.chunk_rows:
            Xc, Yc = self._take_pending(self.chunk_rows)
            self._add(self._chunk_stats(Xc, Yc))

    def _take_pending(self, n: int) -> Tuple[np.ndarray, np.ndarray]:
        """Pop exactly n rows off the pending buffer (n <= pending)."""
        xs, ys, got = [], [], 0
        while got < n:
            X0, Y0 = self._pend_x[0], self._pend_y[0]
            take = min(n - got, X0.shape[0])
            xs.append(X0[:take])
            ys.append(Y0[:take])
            if take == X0.shape[0]:
                self._pend_x.pop(0)
                self._pend_y.pop(0)
            else:
                self._pend_x[0] = X0[take:]
                self._pend_y[0] = Y0[take:]
            got += take
        self._pend_rows -= n
        return (
            xs[0] if len(xs) == 1 else np.concatenate(xs, axis=0),
            ys[0] if len(ys) == 1 else np.concatenate(ys, axis=0),
        )

    def flush(self) -> None:
        """Fold any pending partial chunk now (a short chunk). Resets
        the absolute phase — only decay (which rescales history anyway)
        and checkpoint-independent callers should force this."""
        if self._pend_rows > 0:
            Xc, Yc = self._take_pending(self._pend_rows)
            self._add(self._chunk_stats(Xc, Yc))

    # -- forgetting --------------------------------------------------------

    def decay(self, gamma: float) -> "OnlineState":
        """Scale every retained sum by γ ∈ (0, 1]: data folded a calls
        ago ends up weighted γ^a — the exponentially-weighted ridge
        problem. Pending rows flush first (they belong to the
        pre-decay regime). Exclusive with the window ring."""
        gamma = float(gamma)
        if not 0.0 < gamma <= 1.0:
            raise OnlineStateError(f"decay must be in (0, 1], got {gamma}")
        if self.window is not None:
            raise OnlineStateError(
                "decay and window are exclusive forgetting modes"
            )
        if gamma == 1.0:
            return self
        self.flush()
        self.gram *= gamma
        self.atb *= gamma
        self.x_sum *= gamma
        self.y_sum *= gamma
        self.rows *= gamma
        self.decays += 1
        return self

    # -- solving -----------------------------------------------------------

    def _totals_with_pending(self) -> Tuple:
        """Current totals INCLUDING pending rows, computed on copies so
        the live buffer keeps its phase for future folds."""
        if self._pend_rows == 0:
            return (self.gram, self.atb, self.x_sum, self.y_sum, self.rows)
        xs = (self._pend_x[0] if len(self._pend_x) == 1
              else np.concatenate(self._pend_x, axis=0))
        ys = (self._pend_y[0] if len(self._pend_y) == 1
              else np.concatenate(self._pend_y, axis=0))
        tail = self._chunk_stats(xs, ys)
        return (
            self.gram + tail[0], self.atb + tail[1],
            self.x_sum + tail[2], self.y_sum + tail[3],
            self.rows + tail[4],
        )

    def solve(self, lam: float = 0.0, refine_steps: int = 1,
              fit_intercept: bool = True):
        """Re-solve the retained problem via the existing Cholesky path
        (``linalg.normal_equations._chol_solve``). Returns ``(W, b)``
        (``b`` None without an intercept). Centering is applied as the
        exact f64 rank-one correction of the uncentered sums — the
        weighted-mean form, so decay/window states solve their weighted
        problem with the matching intercept."""
        import jax.numpy as jnp

        from keystone_tpu.linalg.normal_equations import _chol_solve

        gram, atb, x_sum, y_sum, n = self._totals_with_pending()
        if n <= 0:
            raise OnlineStateError("solve on an empty online state refused")
        _online_counters().bump("resolves")
        if fit_intercept:
            x_mean = x_sum / n
            y_mean = y_sum / n
            gram_c = gram - np.outer(x_sum, x_sum) / n
            if atb.ndim == 1:
                atb_c = atb - x_sum * (float(y_sum) / n)
            else:
                atb_c = atb - np.outer(x_sum, y_sum) / n
        else:
            gram_c, atb_c = gram, atb
        cdtype = jnp.dtype(self.accum_dtype)
        W = _chol_solve(
            jnp.asarray(gram_c, dtype=cdtype),
            jnp.asarray(atb_c, dtype=cdtype),
            jnp.asarray(lam, dtype=cdtype),
            int(refine_steps),
        )
        if not fit_intercept:
            return W, None
        b = (jnp.asarray(y_mean, dtype=W.dtype)
             - jnp.asarray(x_mean, dtype=W.dtype) @ W)
        return W, b

    # -- checkpointing -----------------------------------------------------

    def snapshot(self) -> dict:
        """Exact-resume snapshot: fingerprint + f64 accumulators + the
        pending row bytes + the window ring. NumPy round-trips bit-exact,
        which is what makes resumed folds bit-identical."""
        return {
            "fingerprint": self.fingerprint(),
            "gram": np.array(self.gram),
            "atb": np.array(self.atb),
            "x_sum": np.array(self.x_sum),
            "y_sum": np.array(self.y_sum),
            "rows": float(self.rows),
            "folds": int(self.folds),
            "decays": int(self.decays),
            "pend_x": [np.array(x) for x in self._pend_x],
            "pend_y": [np.array(y) for y in self._pend_y],
            "ring": [tuple(np.array(a) if isinstance(a, np.ndarray) else a
                           for a in entry) for entry in self._ring],
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "OnlineState":
        """Rebuild a state from :meth:`snapshot`. A snapshot of the same
        problem recorded under a DIFFERENT mesh width migrates onto the
        current mesh (``utils.mesh.reshard_state`` — the accumulators are
        placement-free f64 sums, so the migrated state folds and solves
        bit-identically; elastic mesh, default on, counted) or, with
        ``KEYSTONE_ELASTIC_MESH=0``, refuses with the typed
        ``MeshMismatchError`` (the one rule every checkpointing solver
        shares) — never a wrong-answer resume."""
        from keystone_tpu.utils.mesh import (
            mesh_resume_decision,
            reshard_state,
        )

        fp = dict(snap["fingerprint"])
        state = cls(
            fp["d"], tuple(fp["b_tail"]), chunk_rows=fp["chunk_rows"],
            window=fp.get("window"),
        )
        expected = state.fingerprint()
        decision, fp = mesh_resume_decision(fp, expected, "online state")
        if decision == "fresh":
            raise OnlineStateError(
                f"online-state snapshot holds a different problem "
                f"({fp} != {expected}); delete it to start fresh"
            )
        if decision == "migrate":
            snap = reshard_state(
                dict(snap, fingerprint=fp), family="online_state"
            )
        state.gram = np.asarray(snap["gram"], dtype=np.float64)
        state.atb = np.asarray(snap["atb"], dtype=np.float64)
        state.x_sum = np.asarray(snap["x_sum"], dtype=np.float64)
        state.y_sum = np.asarray(snap["y_sum"], dtype=np.float64)
        state.rows = float(snap["rows"])
        state.folds = int(snap["folds"])
        state.decays = int(snap.get("decays", 0))
        state._pend_x = [np.asarray(x) for x in snap.get("pend_x", [])]
        state._pend_y = [np.asarray(y) for y in snap.get("pend_y", [])]
        state._pend_rows = int(sum(x.shape[0] for x in state._pend_x))
        state._ring = [tuple(e) for e in snap.get("ring", [])]
        return state

    def save(self, directory: str) -> None:
        """Persist the snapshot through the atomic DiskCache (a kill
        mid-save leaves the previous complete snapshot)."""
        save_state_snapshot(directory, self.snapshot())

    @classmethod
    def load(cls, directory: str) -> Optional["OnlineState"]:
        """The checkpointed state, or None when none exists. A snapshot
        recorded under a different mesh width migrates or raises the
        typed ``MeshMismatchError`` (see :meth:`from_snapshot`)."""
        from keystone_tpu.workflow.disk_cache import DiskCache

        snap = DiskCache(directory, suffix=".online.pkl").get(_STATE_KEY)
        if snap is None:
            return None
        state = cls.from_snapshot(snap)
        from keystone_tpu.utils.metrics import reliability_counters

        reliability_counters.bump("checkpoints_resumed")
        return state


def _reshard_online_snapshot(snap, layout):
    """Elastic-mesh adapter for :meth:`OnlineState.snapshot` payloads:
    every retained accumulator (gram/AᵀB/col-sums, the pending row bytes,
    the window ring's stats units) is a host-resident f64 sum or raw row
    buffer — placement-free, nothing per-shard to re-fold — so migration
    rewrites the fingerprint's mesh manifest onto ``layout`` and passes
    the bytes through untouched. Torn payloads (accumulator shapes
    contradicting the fingerprint) refuse typed instead."""
    from keystone_tpu.utils.mesh import reshard_refused

    fp = dict(snap.get("fingerprint") or {})
    d = int(fp.get("d", -1))
    gram = snap.get("gram")
    gram = np.asarray(gram) if gram is not None else None
    if gram is None or gram.shape != (d, d):
        raise reshard_refused(
            "online state",
            "snapshot accumulators do not match their fingerprint "
            "(torn or partially written checkpoint)",
        )
    fp["device_count"] = int(layout.num_shards)
    fp["data_axis"] = str(layout.axis)
    return dict(snap, fingerprint=fp)


register_reshard_adapter("online_state", _reshard_online_snapshot)


def save_state_snapshot(directory: str, snap: dict) -> None:
    """Write one already-taken :meth:`OnlineState.snapshot` through the
    atomic DiskCache — THE checkpoint write shared by ``state.save`` and
    the trainer's off-lock writer (one key, one suffix, no drift)."""
    from keystone_tpu.workflow.disk_cache import DiskCache

    DiskCache(directory, suffix=".online.pkl").put(
        _STATE_KEY, snap, overwrite=True
    )
    from keystone_tpu.utils.mesh import write_mesh_manifest

    write_mesh_manifest(directory, snap.get("fingerprint") or {})
    from keystone_tpu.utils.metrics import reliability_counters

    reliability_counters.bump("checkpoints_written")


def partial_fit_step(state: Optional[OnlineState], X, Y,
                     decay: Optional[float] = None,
                     window: Optional[int] = None,
                     chunk_rows: Optional[int] = None) -> OnlineState:
    """THE partial_fit implementation every estimator wrapper delegates
    to: create-or-reuse the state, apply per-call decay, fold. Mutates
    and returns ``state`` (one object across the stream)."""
    if state is None:
        state = OnlineState.for_batch(X, Y, chunk_rows=chunk_rows,
                                      window=window)
    elif window is not None and window != state.window:
        raise OnlineStateError(
            f"window={window} conflicts with the retained state's "
            f"window={state.window}; the mode is fixed at state creation"
        )
    elif chunk_rows is not None and chunk_rows != state.chunk_rows:
        # Same refusal as window: the fold granularity is part of the
        # state's fingerprint identity, never silently dropped.
        raise OnlineStateError(
            f"chunk_rows={chunk_rows} conflicts with the retained "
            f"state's chunk_rows={state.chunk_rows}; the granularity is "
            "fixed at state creation"
        )
    if decay is not None:
        state.decay(decay)
    return state.fold(X, Y)


# ---------------------------------------------------------------------------
# Refit-head discovery (shared by Pipeline.refit_stream, OnlineTrainer,
# and the KG105 lint rule — one definition of "the head")
# ---------------------------------------------------------------------------


def _skip_persist(graph, gid):
    """See through identity cache nodes (the executor convention)."""
    while getattr(graph.operators.get(gid), "persist", False):
        gid = graph.dependencies[gid][0]
    return gid


def _head_estimator_node(graph, sink):
    """THE definition of "the refit head": the sink must be a lazily-fit
    estimator application (DelegatingOperator over an EstimatorOperator
    — the ``featurize.and_then(est, data, labels)`` shape). Returns the
    EstimatorOperator's graph id, or None for any other shape. Shared by
    the KG105 lint rule, the runtime fallback, and the seeding path so
    they can never disagree about what the head is."""
    from keystone_tpu.workflow.operators import (
        DelegatingOperator,
        EstimatorOperator,
    )

    gid = _skip_persist(graph, sink)
    if not isinstance(graph.operators.get(gid), DelegatingOperator):
        return None
    est_dep = _skip_persist(graph, graph.dependencies[gid][0])
    if not isinstance(graph.operators.get(est_dep), EstimatorOperator):
        return None
    return est_dep


def head_fit_values(graph, sink):
    """The (features, labels) values the head estimator is fitted on,
    evaluated through the session-cached executor walk (a pipeline that
    already ``fit()`` in this session pays ~nothing). This is what seeds
    a fresh online state so the FIRST refresh re-solves the whole
    problem, not just the streamed tail."""
    from keystone_tpu.workflow.pipeline import PipelineDataset

    est_gid = _head_estimator_node(graph, sink)
    if est_gid is None:
        raise ValueError("not a refit-able pipeline shape")
    feats_gid, labels_gid = graph.dependencies[est_gid]
    feats = PipelineDataset(graph, feats_gid).get()
    labels = PipelineDataset(graph, labels_gid).get()
    return feats, labels


def refit_head_estimator(graph, sink):
    """The head estimator of a refit-able pipeline (see
    ``_head_estimator_node``), or None when the graph has a different
    shape (the caller decides whether that is an error or a lint
    silence)."""
    est_gid = _head_estimator_node(graph, sink)
    if est_gid is None:
        return None
    return graph.operators[est_gid].estimator


def combine_head(prefix, head_t):
    """Re-attach a (re-solved) head transformer to its frozen featurize
    prefix — THE recombination used by refit_stream ticks, trainer
    refreshes, and resolve(), so the three surfaces can never diverge
    on how a refreshed pipeline is assembled."""
    if prefix is not None:
        return prefix.and_then(head_t)
    return head_t.to_pipeline()


def split_fitted_head(fitted):
    """Split a FITTED pipeline into (frozen featurize prefix or None,
    head transformer node): the sink transformer is the head, everything
    upstream is the frozen prefix. Returns ``(prefix_pipeline_or_None,
    head_transformer)``."""
    from keystone_tpu.workflow.graph import SourceId
    from keystone_tpu.workflow.operators import TransformerOperator
    from keystone_tpu.workflow.pipeline import Pipeline

    graph, source, sink = fitted.graph, fitted.source, fitted.sink
    gid = _skip_persist(graph, sink)
    op = graph.operators.get(gid)
    if not isinstance(op, TransformerOperator):
        raise ValueError(
            f"fitted refit pipeline's head is {op.label() if op else gid!r},"
            " not a transformer; fit the pipeline first"
        )
    head_t = op.transformer
    prefix_sink = graph.dependencies[gid][0]
    if isinstance(prefix_sink, SourceId):
        return None, head_t
    return Pipeline(graph, source, prefix_sink), head_t


# ---------------------------------------------------------------------------
# OnlineTrainer — the continuous serving-refresh loop
# ---------------------------------------------------------------------------


class OnlineTrainer:
    """Keep a model current: fold live batches, re-solve on a cadence,
    publish versioned artifacts, hot-swap a live daemon.

    ``pipeline`` is the unfitted ``featurize.and_then(head_est, X0, y0)``
    shape; construction fits it once (the initial model; featurize
    stages are FROZEN thereafter) and — when the head supports
    ``partial_fit`` — prepares the retained accumulator state.
    ``submit(X, y)`` featurizes through the frozen prefix and folds;
    the ``_refresh_loop`` thread (cadence ``refresh_ms``, env
    ``KEYSTONE_ONLINE_REFRESH_MS``; 0 = manual ``refresh()`` only)
    re-solves, writes ``{artifact_dir}/{name}-gNNNN.kart`` and pushes it
    through ``daemon.request_swap`` — the zero-dropped-requests handoff.

    Failure semantics: a refresh that dies at ANY point (the
    ``refresh_abort`` fault site, a failed swap, a full disk) is counted
    (``refreshes_failed``), logged, and changes nothing — the daemon
    keeps answering on its current generation and the accumulators are
    untouched, so the next cadence tick simply retries. With
    ``checkpoint_dir``, the state snapshots after every fold: a killed
    trainer process resumes bit-identically (mesh-width changes refused,
    typed)."""

    def __init__(self, pipeline, daemon=None, artifact_dir: Optional[str] = None,
                 *, refresh_ms: Optional[float] = None,
                 decay: Optional[float] = None,
                 window: Optional[int] = None,
                 chunk_rows: Optional[int] = None,
                 checkpoint_dir: Optional[str] = None,
                 feature_shape: Optional[Tuple[int, ...]] = None,
                 name: str = "online", start: Optional[bool] = None,
                 seed_state: bool = True, keep_artifacts: int = 8):
        from keystone_tpu.config import config
        from keystone_tpu.utils.reliability import active_plan

        self.name = str(name)
        self._daemon = daemon
        self._artifact_dir = artifact_dir
        self._checkpoint_dir = checkpoint_dir
        self._feature_shape = feature_shape
        self._chunk_rows = chunk_rows
        # Resolved ONCE (the active_plan discipline): refresh cadence,
        # forgetting knobs, fault plan.
        self._refresh_ms = (
            config.online_refresh_ms if refresh_ms is None
            else float(refresh_ms)
        )
        if decay is None:
            decay = (
                config.online_decay if config.online_decay != 1.0 else None
            )
        if window is None:
            window = config.online_window or None
        if decay is not None and window is not None:
            raise OnlineStateError(
                "decay and window are exclusive forgetting modes"
            )
        self._decay = decay
        self._window = window
        self._plan = active_plan()
        head = refit_head_estimator(pipeline.graph, pipeline.sink)
        if head is None:
            raise ValueError(
                "OnlineTrainer needs a pipeline whose sink is a lazily-fit "
                "estimator head (featurize.and_then(est, data, labels))"
            )
        if not supports_partial_fit(head):
            raise OnlineStateError(
                f"{type(head).__name__} does not implement partial_fit; "
                "the refresh loop would silently full-refit every tick "
                "(Pipeline.refit_stream supports that fallback; the "
                "trainer refuses it)"
            )
        self._head = head
        fitted = pipeline.fit()
        self._prefix, self._head_t = split_fitted_head(fitted)
        self._lock = threading.Lock()
        # Serializes whole refreshes end-to-end (snapshot → solve →
        # publish → swap): a manual refresh() racing the cadence tick
        # could otherwise install the OLDER of two re-solves as the
        # newest generation with zero fold debt left to trigger a
        # correcting tick. Ordering: _refresh_lock is taken BEFORE
        # self._lock, never the reverse.
        self._refresh_lock = threading.Lock()
        # Serializes checkpoint WRITES only (they run off the main lock:
        # a multi-MB pickle-to-disk per fold must not stall
        # resolve/refresh/stats and every other producer).
        self._ckpt_lock = threading.Lock()
        self._ckpt_written_folds = 0
        self._keep_artifacts = max(1, int(keep_artifacts))
        self._state: Optional[OnlineState] = None
        if checkpoint_dir is not None:
            self._state = OnlineState.load(checkpoint_dir)
            if self._state is not None:
                # The mismatch originates HERE, so it refuses HERE — a
                # trainer that constructed fine but threw on every
                # submit would keep serving the pre-kill model forever
                # while the cadence loop saw nothing pending.
                if self._state.window != self._window:
                    raise OnlineStateError(
                        f"resumed checkpoint was folded with window="
                        f"{self._state.window}, this trainer is "
                        f"configured window={self._window}; delete the "
                        "checkpoint to change the forgetting mode"
                    )
                if (self._chunk_rows is not None
                        and self._state.chunk_rows != self._chunk_rows):
                    raise OnlineStateError(
                        f"resumed checkpoint was folded at chunk_rows="
                        f"{self._state.chunk_rows}, this trainer asks "
                        f"for {self._chunk_rows}; delete the checkpoint "
                        "to change the fold granularity"
                    )
                if self._state.decays > 0 and self._decay is None:
                    # γ-weighted history continued UNWEIGHTED silently
                    # changes the forgetting semantics mid-stream.
                    # (A different γ is legal — decay is per-call — and
                    # decay starting fresh on an undecayed resume too.)
                    raise OnlineStateError(
                        "resumed checkpoint carries time-decayed history "
                        f"({self._state.decays} decay(s) applied), but "
                        "this trainer is configured without decay; set "
                        "decay= (or delete the checkpoint) to change the "
                        "forgetting mode"
                    )
                self._ckpt_written_folds = self._state.folds
                logger.info(
                    "online trainer %s: resumed accumulator checkpoint "
                    "(%d fold(s), %.0f effective rows)",
                    self.name, self._state.folds, self._state.rows,
                )
        if self._state is None and seed_state:
            # Seed with the INITIAL training problem (featurized values
            # re-read through the session cache the fit just warmed):
            # the first refresh then re-solves initial ∪ streamed, never
            # a near-degenerate model from the first small batch alone.
            # A resumed checkpoint already contains its history and is
            # never double-seeded.
            feats0, labels0 = head_fit_values(pipeline.graph,
                                              pipeline.sink)
            self._state = partial_fit_step(
                None, feats0, labels0, window=self._window,
                chunk_rows=self._chunk_rows,
            )
            if checkpoint_dir is not None:
                self._state.save(checkpoint_dir)
                self._ckpt_written_folds = self._state.folds
        self._folds_since_refresh = 0
        # The artifact sequence continues past whatever this name
        # already published into artifact_dir: a restarted/resumed
        # trainer must never overwrite g0001 UNDER a stale g0008 (an
        # operator rolling back to "newest by number" would deploy the
        # pre-kill model).
        self._seq = self._max_published_seq()
        self._pushed = 0
        self._attempts = 0
        self._fitted = fitted
        self._last_artifact: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start is None:
            start = self._refresh_ms > 0
        if start and self._refresh_ms > 0:
            self._thread = threading.Thread(
                target=self._refresh_loop,
                name=f"keystone-online-refresh-{self.name}", daemon=True,
            )
            self._thread.start()

    def _max_published_seq(self) -> int:
        """Highest gNNNN this trainer name already wrote to
        artifact_dir (0 when none/unset)."""
        if self._artifact_dir is None or not os.path.isdir(
                self._artifact_dir):
            return 0
        import glob

        best = 0
        pattern = os.path.join(self._artifact_dir,
                               f"{self.name}-g[0-9]*.kart")
        for path in glob.glob(pattern):
            stem = os.path.basename(path)[len(self.name) + 2:-len(".kart")]
            try:
                best = max(best, int(stem))
            except ValueError:
                continue  # not ours
        return best

    # -- data path ---------------------------------------------------------

    def _featurize(self, X):
        if self._prefix is None:
            return X
        return self._prefix.apply(X).get()

    def submit(self, X, y) -> None:
        """Featurize one labeled batch through the frozen prefix and
        fold it into the retained state (checkpointed when configured)."""
        feats = self._featurize(X)
        snap = folds = None
        with self._lock:
            self._state = partial_fit_step(
                self._state, feats, y, decay=self._decay,
                window=self._window, chunk_rows=self._chunk_rows,
            )
            self._folds_since_refresh += 1
            if self._checkpoint_dir is not None:
                # Snapshot (host memcpy) under the lock; the disk write
                # runs OUTSIDE it so a multi-MB pickle cannot stall
                # concurrent producers or the cadence refresh.
                snap = self._state.snapshot()
                folds = self._state.folds
        if snap is not None:
            with self._ckpt_lock:
                # Monotonic guard: concurrent submits release the main
                # lock in fold order but could reach the writer out of
                # order — an older snapshot must never overwrite newer.
                if folds > self._ckpt_written_folds:
                    save_state_snapshot(self._checkpoint_dir, snap)
                    self._ckpt_written_folds = folds

    # -- refresh path ------------------------------------------------------

    def refresh(self) -> "Pipeline":
        """Re-solve NOW, publish, and hot-swap (when wired to a daemon).
        Raises on failure — the caller (or the cadence loop, which
        catches and retries next tick) decides; the failure is counted
        either way and serving is unaffected. Whole refreshes serialize
        (a manual call racing the cadence tick publishes in snapshot
        order, never an older re-solve over a newer one)."""
        try:
            with self._refresh_lock:
                return self._refresh_inner()
        except BaseException:
            _online_counters().bump("refreshes_failed")
            raise

    def _refresh_inner(self):
        from keystone_tpu.workflow.serialization import save_artifact

        # One trace id per refresh, minted HERE (no wire to accept one
        # from): it rides the daemon's swap span + telemetry record, so
        # the offline timeline links "model changed" back to the refresh
        # that caused it.
        refresh_trace = mint_trace_id()
        t0 = time.perf_counter_ns()
        if self._plan is not None:
            # The chaos seam: a refresh killed here leaves the daemon
            # serving its current generation and the accumulators (plus
            # their checkpoint) intact for a bit-identical retry.
            self._plan.maybe_raise("refresh_abort")
        with self._lock:
            state = self._snapshot_state_locked()
            # Captured, NOT reset: the fold debt clears only when the
            # publish SUCCEEDS, so a refresh that dies in
            # save_artifact/request_swap leaves the cadence loop armed
            # to retry next tick exactly as documented.
            pending = self._folds_since_refresh
            self._seq += 1
            self._attempts += 1
            seq = self._seq
        # The solve runs OUTSIDE the lock (on the f64 snapshot copy):
        # a large-d Cholesky must not stall concurrent submit() folds
        # for its whole duration.
        fitted = combine_head(self._prefix, self._head.solve_online(state))
        path = None
        if self._artifact_dir is not None:
            path = os.path.join(
                self._artifact_dir, f"{self.name}-g{seq:04d}.kart"
            )
            save_artifact(fitted, path, feature_shape=self._feature_shape)
        if self._daemon is not None:
            if path is None:
                raise ValueError(
                    "hot-swapping into a daemon needs artifact_dir"
                )
            self._daemon.request_swap(path, trace_id=refresh_trace)
        with self._lock:
            self._fitted = fitted
            self._last_artifact = path
            self._pushed += 1
            # Subtract (don't zero): folds submitted DURING the publish
            # keep their tick.
            self._folds_since_refresh = max(
                0, self._folds_since_refresh - pending
            )
        _online_counters().bump("refreshes_pushed")
        tel = active_telemetry()
        if tel is not None:
            tel.emit({
                "kind": "refresh",
                "service": self.name,
                "pid": tel.pid,
                "trace_id": refresh_trace,
                "seq": seq,
                "artifact": path,
                "folds_applied": pending,
                "start_ns": t0,
                "end_ns": time.perf_counter_ns(),
            })
        if path is not None:
            self._prune_artifacts(seq)
        return fitted

    def _prune_artifacts(self, latest_seq: int) -> None:
        """Bounded retention: keep the newest ``keep_artifacts``
        versioned artifacts, delete the rest — a steady 5s cadence must
        not fill the volume (which would fail every future refresh and
        the co-located checkpoints with it). The daemon holds its loaded
        generations in memory, so deleting served files is safe."""
        floor = latest_seq - self._keep_artifacts + 1
        if floor <= 0:
            return
        import glob

        pattern = os.path.join(self._artifact_dir,
                               f"{self.name}-g[0-9]*.kart")
        for old in glob.glob(pattern):
            stem = os.path.basename(old)[len(self.name) + 2:-len(".kart")]
            try:
                seq = int(stem)
            except ValueError:
                continue  # not ours
            if seq < floor:
                try:
                    os.unlink(old)
                except OSError:
                    pass  # retention is best-effort; next refresh retries

    def resolve(self):
        """Re-solve the retained state NOW and return the refreshed
        fitted pipeline WITHOUT publishing — no artifact, no swap. The
        read-your-current-model surface (and the bench's honest
        re-solve timer: exactly the work a refresh adds on top of
        publish/swap)."""
        with self._lock:
            state = self._snapshot_state_locked()
        return combine_head(self._prefix, self._head.solve_online(state))

    def _snapshot_state_locked(self) -> OnlineState:
        """A deep f64 copy of the retained state (caller holds the
        lock), so the Cholesky re-solve can run off-lock without a
        concurrent fold tearing the accumulators mid-read.

        The copy's pending tail is FLUSHED here, still under the lock:
        the tail fold runs the RowMatrix psum collectives, and two
        threads interleaving collective launches on one mesh (a
        concurrent ``submit`` fold vs an off-lock tail fold) deadlock
        the participant rendezvous. After the flush the off-lock solve
        is collective-free (host centering + the jitted Cholesky), which
        is safe next to anything. The LIVE state keeps its pending
        buffer and phase untouched."""
        state = self._state
        if state is None or state.total_rows <= 0:
            raise OnlineStateError("refresh with nothing folded yet refused")
        snap = OnlineState.from_snapshot(state.snapshot())
        snap.flush()
        return snap

    def _maybe_refresh(self) -> None:
        with self._lock:
            pending = self._folds_since_refresh
        if pending <= 0:
            return
        try:
            self.refresh()
        except Exception as e:  # lint: broad-ok a failed cadence refresh is counted + logged; the loop retries next tick and serving keeps the old generation
            logger.warning(
                "online trainer %s: refresh failed (%s: %s); serving "
                "keeps the current generation, retrying next tick",
                self.name, type(e).__name__, e,
            )

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self._refresh_ms / 1e3):
            self._maybe_refresh()

    # -- introspection -----------------------------------------------------

    @property
    def fitted(self):
        """The latest fitted pipeline (initial fit, or the last refresh)."""
        with self._lock:
            return self._fitted

    @property
    def last_artifact(self) -> Optional[str]:
        with self._lock:
            return self._last_artifact

    def stats(self) -> dict:
        with self._lock:
            state = self._state
            return {
                "name": self.name,
                "refresh_ms": self._refresh_ms,
                "decay": self._decay,
                "window": self._window,
                "folds": 0 if state is None else state.folds,
                "effective_rows": (
                    0.0 if state is None else state.total_rows
                ),
                "folds_since_refresh": self._folds_since_refresh,
                # COMPLETED publishes — a dashboard must not read a
                # failing-every-tick trainer as "refreshing" (attempts
                # counts the tries; the gap is the failure signal).
                "refreshes": self._pushed,
                "refresh_attempts": self._attempts,
                "artifact_seq": self._seq,
                "last_artifact": self._last_artifact,
            }

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def __enter__(self) -> "OnlineTrainer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
