"""Immutable dataflow-graph IR for pipelines.

The reference models a pipeline as an immutable DAG of operator nodes with
typed source/sink endpoints (Ref: src/main/scala/workflow/Graph.scala,
workflow/GraphId.scala [unverified]). We keep that shape: ``NodeId`` ->
``Operator`` with dependency edges on ``GraphId`` (node or source).

Unlike the reference (which remaps ids when merging graphs), every id here is
globally unique (a process-wide counter), so merging two graphs is a plain
dict union and structural sharing of common prefixes is free. Composition
operations that would re-wire an existing node instead *instantiate* a fresh
copy of the right-hand subgraph (`instantiate`), preserving immutability.

Cross-graph deduplication (so a re-used prefix is only computed/fitted once)
is done by *structural hashing* rather than id identity — see
``structural_hash`` and the executor's memo tables; this plays the role of the
reference's `workflow/Prefix.scala` prefix hashing [unverified].
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Mapping, Sequence, Tuple, Union

_counter = itertools.count()


@dataclass(frozen=True)
class NodeId:
    id: int

    def __repr__(self):
        return f"n{self.id}"


@dataclass(frozen=True)
class SourceId:
    id: int

    def __repr__(self):
        return f"src{self.id}"


GraphId = Union[NodeId, SourceId]


def fresh_node_id() -> NodeId:
    return NodeId(next(_counter))


def fresh_source_id() -> SourceId:
    return SourceId(next(_counter))


class Graph:
    """Immutable DAG: ``operators[node]`` with ``dependencies[node]`` edges.

    Sources are implicit: any ``SourceId`` appearing in a dependency list is a
    free input of the graph. Pipelines track their own source/sink endpoints.
    """

    __slots__ = ("operators", "dependencies")

    def __init__(
        self,
        operators: Mapping[NodeId, Any] | None = None,
        dependencies: Mapping[NodeId, Tuple[GraphId, ...]] | None = None,
    ):
        self.operators: Dict[NodeId, Any] = dict(operators or {})
        self.dependencies: Dict[NodeId, Tuple[GraphId, ...]] = dict(dependencies or {})

    # -- construction ------------------------------------------------------

    def add(self, op: Any, deps: Sequence[GraphId]) -> Tuple["Graph", NodeId]:
        nid = fresh_node_id()
        ops = dict(self.operators)
        dps = dict(self.dependencies)
        ops[nid] = op
        dps[nid] = tuple(deps)
        return Graph(ops, dps), nid

    def union(self, other: "Graph") -> "Graph":
        """Merge two graphs. Shared node ids must agree (they do by
        construction: ids are globally unique and nodes immutable)."""
        ops = dict(self.operators)
        ops.update(other.operators)
        dps = dict(self.dependencies)
        dps.update(other.dependencies)
        return Graph(ops, dps)

    # -- traversal ---------------------------------------------------------

    def reachable(self, targets: Iterable[GraphId]) -> List[NodeId]:
        """Nodes reachable (upward through dependencies) from targets, in
        topological order (dependencies first)."""
        order: List[NodeId] = []
        seen: Dict[GraphId, bool] = {}
        stack: List[Tuple[GraphId, bool]] = [(t, False) for t in targets]
        while stack:
            gid, processed = stack.pop()
            if processed:
                order.append(gid)  # type: ignore[arg-type]
                continue
            if gid in seen or isinstance(gid, SourceId):
                continue
            seen[gid] = True
            stack.append((gid, True))
            for dep in self.dependencies[gid]:
                if dep not in seen and isinstance(dep, NodeId):
                    stack.append((dep, False))
        return order

    def sources_of(self, targets: Iterable[GraphId]) -> List[SourceId]:
        srcs: List[SourceId] = []
        seen = set()
        for t in targets:
            if isinstance(t, SourceId) and t not in seen:
                seen.add(t)
                srcs.append(t)
        for nid in self.reachable(targets):
            for dep in self.dependencies[nid]:
                if isinstance(dep, SourceId) and dep not in seen:
                    seen.add(dep)
                    srcs.append(dep)
        return srcs

    # -- instantiation (fresh-copy of a subgraph) --------------------------

    def instantiate(
        self,
        targets: Sequence[GraphId],
        replace: Mapping[GraphId, GraphId] | None = None,
    ) -> Tuple["Graph", List[GraphId]]:
        """Copy the subgraph reachable from ``targets`` with fresh node ids,
        rewriting ids per ``replace`` (typically mapping a SourceId to a data
        node or to another graph's sink). Returns (graph-with-copies-merged,
        new targets). Nodes are copied; operators are shared by reference.
        """
        replace = dict(replace or {})
        mapping: Dict[GraphId, GraphId] = dict(replace)
        ops = dict(self.operators)
        dps = dict(self.dependencies)
        for nid in self.reachable(targets):
            new_id = fresh_node_id()
            mapping[nid] = new_id
            ops[new_id] = self.operators[nid]
            dps[new_id] = tuple(mapping.get(d, d) for d in self.dependencies[nid])
        new_targets = [mapping.get(t, t) for t in targets]
        return Graph(ops, dps), new_targets

    def pruned(self, targets: Sequence[GraphId]) -> "Graph":
        """Keep only nodes reachable from targets (drops composition orphans,
        keeping graph size linear in the live pipeline)."""
        keep = self.reachable(targets)
        return Graph(
            {n: self.operators[n] for n in keep},
            {n: self.dependencies[n] for n in keep},
        )

    def replace_node(self, nid: NodeId, op: Any, deps: Sequence[GraphId]) -> "Graph":
        ops = dict(self.operators)
        dps = dict(self.dependencies)
        ops[nid] = op
        dps[nid] = tuple(deps)
        return Graph(ops, dps)

    def consumers(self, targets: Iterable[GraphId]) -> Dict[GraphId, List[NodeId]]:
        """Map each graph id to the list of nodes that depend on it (within
        the subgraph reachable from targets)."""
        out: Dict[GraphId, List[NodeId]] = {}
        for nid in self.reachable(targets):
            for dep in self.dependencies[nid]:
                out.setdefault(dep, []).append(nid)
        return out


def structural_hash(
    graph: Graph,
    target: GraphId,
    source_key: Callable[[SourceId], Any],
    _memo: Dict[GraphId, int] | None = None,
) -> int:
    """Structural (prefix) hash of the computation producing ``target``.

    Two nodes with the same operator signature and structurally identical
    dependency prefixes hash equal, even across graph copies. This is the
    TPU-rebuild analog of the reference's fitted-prefix memoization key
    (Ref: workflow/Prefix.scala [unverified]).
    """
    memo: Dict[GraphId, int] = {} if _memo is None else _memo

    def rec(gid: GraphId) -> int:
        if gid in memo:
            return memo[gid]
        if isinstance(gid, SourceId):
            h = hash(("source", source_key(gid)))
        else:
            op = graph.operators[gid]
            dep_h = tuple(rec(d) for d in graph.dependencies[gid])
            h = op.prefix_hash(dep_h)
        memo[gid] = h
        return h

    return rec(target)


def structural_digest(
    graph: Graph,
    target: GraphId,
    _memo: Dict[GraphId, Any] | None = None,
    source_token: str | None = None,
) -> str | None:
    """Content-stable prefix digest of ``target`` — the cross-process cache
    key. None when any operator in the prefix lacks content identity, or the
    prefix reaches a free source (an unbound input has no content) — unless
    ``source_token`` names the free input, for digesting pipeline TEMPLATES
    (e.g. an unfitted featurizer front) rather than bound executions."""
    memo: Dict[GraphId, Any] = {} if _memo is None else _memo

    def rec(gid: GraphId):
        if gid in memo:
            return memo[gid]
        if isinstance(gid, SourceId):
            if source_token is not None:
                from keystone_tpu.workflow.fingerprint import digest_tree

                d = digest_tree(("source", source_token))
            else:
                d = None
        else:
            op = graph.operators[gid]
            dep_d = tuple(rec(x) for x in graph.dependencies[gid])
            d = op.prefix_digest(dep_d)
        memo[gid] = d
        return d

    return rec(target)
