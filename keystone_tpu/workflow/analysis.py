"""Static pipeline-graph linter (Layer 1 of keystone-lint).

KeystoneML's core move is whole-pipeline optimization over a statically
analyzable operator DAG; this module adds the *checking* half of that
bargain. An abstract-interpretation pass propagates symbolic shape/dtype
specs through the graph (``jax.eval_shape`` on the transformers' batch
functions — no device compute, no data) and a small rule catalog turns
what the pass sees into structured diagnostics, so serveability, shape,
and recompile hazards surface BEFORE a trace ever reaches a device
(arXiv:2206.14148's pre-execution resource checking; arXiv:2008.01040's
pre-execution graph analysis).

Rule catalog (KG = Keystone Graph):

- ``KG001 serve-unjittable`` — a non-jittable (host) transformer on the
  would-be serving chain. ``compiled()`` would refuse it at call time;
  the linter says so up front.
- ``KG002 serve-row-coupled`` — a ``row_independent=False`` stage on the
  chain: bucket padding would change real outputs
  (``RowDependenceError`` at serve time).
- ``KG003 serve-nonlinear`` — a gather join / multi-input node on the
  chain: the bucketed engine compiles ONE linear program per bucket.
- ``KG101 recompile-hazard`` — a shape-polymorphic input feeding jit
  consumers with no bucket ladder configured: every distinct row count
  recompiles the whole fused chain.
- ``KG102 dtype-seam`` — a silent upcast across a node boundary (output
  dtype wider than input), or mixed dtypes meeting at a gather join:
  the upcast doubles bytes/HBM mid-chain without anyone asking for it.
- ``KG103 shard-pad`` — fitting under ``config.shard_data_batches`` with
  a dataset whose batch rows can never divide the active data mesh: every
  fused-chain call over it mask-pads onto the mesh (extra pad rows per
  call) — the old silent single-device cliff, now caught statically (a
  pure shape check, no execution) so the operator can pick a divisible
  batch size instead of paying the padding.
- ``KG104 plan-over-budget`` — a memory plan whose priced HBM exceeds
  the budget, caught at lint time instead of at warmup/trace time: a
  pinned serve bucket ladder (ladder × replicas × storage dtype — the
  AOT-warmed executables all coexist) beyond the ladder budget share,
  or a pinned solve chunk (rows × bytes/row from the propagated spec)
  beyond the chunk budget share. Shape-only pricing off the propagated
  specs — no execution, no compile; the un-pinned defaults stay silent
  because the warmup/plan path auto-sizes those.
- ``KG105 refit-full-head`` — linting with ``refit=True`` (the
  ``Pipeline.refit_stream`` contract) against a pipeline whose head
  estimator does not implement ``partial_fit``: every cadence tick then
  silently costs a FULL head refit over the buffered stream instead of
  a cheap accumulator re-solve.
- ``KG106 undonated-fit-chain`` — with ``config.donate_buffers`` on, an
  estimator's jittable feature chain takes its input from a dataset the
  runtime places directly onto the mesh (the divisible "shard" class):
  the placed array is caller-owned, so the fused lowering runs WITHOUT
  donating its input and the fit holds the batch live twice (input +
  chain output). Host-staged arrivals (streamed batches, the pad class)
  donate their staging copy instead. Shape-only, no execution.
- ``KG107 checkpoint-mesh-drift`` — an estimator configured with a
  ``checkpoint_dir`` whose on-disk mesh manifest was recorded under a
  DIFFERENT mesh width than the active data mesh: the fit will hit the
  elastic migration (counted) — or the typed ``MeshMismatchError`` with
  ``KEYSTONE_ELASTIC_MESH=0`` — at resume time. Flagged up front from
  the directory's JSON sidecar (a static dict read: no unpickling, no
  orbax restore, no execution).
- ``KG108 autoscale-pinned`` — a capacity model is enabled (telemetry
  dir configured / ``KEYSTONE_CAPACITY_MODEL``) while the replica count
  and/or the serve bucket ladder are hand-pinned
  (``KEYSTONE_SERVE_DEVICES`` != 0 / ``KEYSTONE_SERVE_BUCKETS``): the
  capacity re-plan loop refuses to touch pinned resources (pins win, by
  contract), so the pin silently defeats the traffic-aware autoscaling
  the model was enabled for. Same classifier discipline as KG104:
  static config reads only, pinned configurations only — the un-pinned
  defaults are exactly what the re-plan loop is allowed to size.
- ``KG201 dead-node`` — a node in the graph unreachable from the sink
  (composition orphans the pruner should have dropped).
- ``KG202 cache-advice`` — a non-trivial subchain re-used by >= 2
  consumers with no cache node: each consumer recomputes the prefix.
- ``KG203 profile-unused`` — a measured profile for this pipeline exists
  in the profile store, but the auto-cache rule would run model-only
  (``config.auto_cache`` is off, so the measured costs are never used
  for cache placement; the resource planner may still consume them).

Severity model: serveability rules (KG00x) are *errors* when linting
with ``serve=True`` (the pre-``compiled()`` gate) and *warnings*
otherwise; KG101/KG102/KG103/KG104/KG105/KG106/KG107/KG108 are
warnings; KG201/KG202/KG203 are info.

Wire-up: ``Pipeline.lint()`` runs this directly; the opt-in env gate
``KEYSTONE_LINT=warn|error|off`` (default off) runs it before every
``fit()`` / ``compiled()`` via ``enforce_lint`` — ``warn`` logs,
``error`` raises ``LintError`` on error-severity findings. CLI/CI
rendering goes through ``tools/lint_report.py.format_findings`` over
``LintReport.as_dicts()`` — the same table the AST layer prints;
``LintReport.render()`` is only the inline (no-tools-import)
convenience for interactive use.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from keystone_tpu.workflow.graph import Graph, GraphId, NodeId, SourceId
from keystone_tpu.workflow.operators import (
    DatasetOperator,
    DatumOperator,
    DelegatingOperator,
    EstimatorOperator,
    GatherOperator,
    TransformerOperator,
)

logger = logging.getLogger("keystone_tpu")

#: Rule ids -> one-line descriptions (the catalog tools/lint_report.py
#: and the README render; tests assert it stays in sync with the rules).
GRAPH_RULES: Dict[str, str] = {
    "KG001": "non-jittable (host) transformer on the serving chain",
    "KG002": "row-coupled stage on the serving chain (padding unsound)",
    "KG003": "gather/multi-input node on the serving chain (not linear)",
    "KG101": "shape-polymorphic input feeds jit consumers without buckets",
    "KG102": "silent dtype upcast / mixed-dtype seam across nodes",
    "KG103": "dataset batch rows never divide the active data mesh",
    "KG104": "pinned serve ladder / solve chunk priced beyond the HBM budget",
    "KG105": "refit_stream head estimator lacks partial_fit (full refit "
             "per cadence tick)",
    "KG106": "estimator's fit chain lowers without donation (mesh-placed "
             "caller-owned input)",
    "KG107": "checkpoint_dir holds state recorded under a different mesh "
             "width",
    "KG108": "capacity model enabled but replica count / serve ladder "
             "hand-pinned (pin defeats autoscaling)",
    "KG201": "dead node unreachable from the pipeline sink",
    "KG202": "re-used subchain with no cache node",
    "KG203": "stored measured profile exists but auto-cache is model-only",
}


class LintError(ValueError):
    """Raised by the KEYSTONE_LINT=error gate on error-severity findings."""


@dataclass(frozen=True)
class Diagnostic:
    """One structured finding: rule id, severity, where, what, and how to
    fix it — the graph-layer analog of a compiler diagnostic."""

    rule: str
    severity: str  # "error" | "warning" | "info"
    node: str      # "n12:RandomPatcher" or "-" for graph-wide findings
    message: str
    hint: str = ""

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "node": self.node,
            "message": self.message,
            "hint": self.hint,
        }


@dataclass
class LintReport:
    """The diagnostics of one lint pass, with severity accessors.
    ``as_dicts()`` is the interchange shape ``tools/lint_report.py``'s
    shared formatter consumes; ``render()`` is a dependency-free inline
    rendering for interactive use."""

    diagnostics: List[Diagnostic] = field(default_factory=list)

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self) -> int:
        return len(self.diagnostics)

    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warning"]

    def by_rule(self, rule: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.rule == rule]

    def as_dicts(self) -> List[dict]:
        return [d.as_dict() for d in self.diagnostics]

    def render(self) -> str:
        """Human-readable table (one line per finding)."""
        if not self.diagnostics:
            return "pipeline lint: clean"
        lines = []
        for d in sorted(
            self.diagnostics,
            key=lambda d: ({"error": 0, "warning": 1, "info": 2}[d.severity],
                           d.rule),
        ):
            loc = f" @ {d.node}" if d.node != "-" else ""
            hint = f" [{d.hint}]" if d.hint else ""
            lines.append(f"{d.severity:<7} {d.rule}{loc}: {d.message}{hint}")
        return "\n".join(lines)


def _node_label(graph: Graph, nid: NodeId) -> str:
    return f"{nid!r}:{graph.operators[nid].label()}"


# ---------------------------------------------------------------------------
# Abstract shape/dtype propagation
# ---------------------------------------------------------------------------


def _spec_of_value(data: Any):
    """A ShapeDtypeStruct for a concrete batch, or None for host objects
    without array shape/dtype (token lists, strings)."""
    import numpy as np

    import jax

    shape = getattr(data, "shape", None)
    dtype = getattr(data, "dtype", None)
    if shape is None or dtype is None:
        return None
    try:
        return jax.ShapeDtypeStruct(tuple(int(s) for s in shape),
                                    np.dtype(dtype))
    except (TypeError, ValueError):
        return None


def _input_spec(example: Any) -> Tuple[Any, bool]:
    """Resolve the lint input: (spec-or-None, polymorphic_batch).

    ``example`` may be a sample batch (array), a ``jax.ShapeDtypeStruct``,
    a per-row feature-shape tuple (batch dim unknown -> polymorphic, a
    nominal batch stands in for propagation), or None (no input spec —
    dataset-rooted subgraphs still propagate; the batch is treated as
    polymorphic, which is what serving traffic is).
    """
    import numpy as np

    import jax

    if example is None:
        return None, True
    if isinstance(example, jax.ShapeDtypeStruct):
        return example, False
    if isinstance(example, tuple) and all(isinstance(d, int) for d in example):
        from keystone_tpu.config import config

        return (
            jax.ShapeDtypeStruct((8,) + example, np.dtype(config.default_dtype)),
            True,
        )
    spec = _spec_of_value(np.asarray(example))
    return spec, False


def propagate_specs(
    graph: Graph, sink: GraphId, source_spec: Any = None
) -> Dict[GraphId, Any]:
    """Abstract interpretation of the DAG: walk in topological order and
    compute each node's output ``ShapeDtypeStruct`` via ``jax.eval_shape``
    on the transformer's batch function — symbolic execution, no device
    work, no data. Unknown stays unknown (None) and poisons downstream
    specs rather than guessing: estimator fits (the fitted transformer is
    a runtime value), host transformers, and anything eval_shape refuses.
    """
    import jax
    import jax.numpy as jnp

    specs: Dict[GraphId, Any] = {}
    for nid in graph.reachable([sink]):
        op = graph.operators[nid]
        deps = graph.dependencies[nid]
        dep_specs = [
            specs.get(d) if isinstance(d, NodeId) else source_spec
            for d in deps
        ]
        out = None
        if isinstance(op, DatasetOperator):
            out = _spec_of_value(op.data)
        elif isinstance(op, DatumOperator):
            out = None
        elif isinstance(op, TransformerOperator):
            t = op.transformer
            if getattr(t, "jittable", False) and dep_specs and dep_specs[0] is not None:
                try:
                    out = jax.eval_shape(t.apply_batch, dep_specs[0])
                except Exception:  # lint: broad-ok abstract eval is best-effort; unknown, not fatal
                    out = None
        elif isinstance(op, GatherOperator):
            if dep_specs and all(s is not None for s in dep_specs):
                try:
                    out = jax.eval_shape(
                        lambda *xs: jnp.concatenate(
                            [jnp.asarray(x) for x in xs], axis=-1
                        ),
                        *dep_specs,
                    )
                except Exception:  # lint: broad-ok mismatched branches reported by KG102, not a crash
                    out = None
        elif getattr(op, "persist", False):  # identity cache node
            out = dep_specs[0] if dep_specs else None
        # Estimator / Delegating / unknown operators: runtime values.
        specs[nid] = out
    return specs


# ---------------------------------------------------------------------------
# The serve-chain walk (the non-throwing twin of executor.serving_chain)
# ---------------------------------------------------------------------------


def _walk_serve_chain(graph: Graph, source: SourceId, sink: GraphId):
    """Walk sink -> source the way ``GraphExecutor.serving_chain`` would
    after fit: see through cache nodes, follow a DelegatingOperator's
    input edge (its estimator resolves at fit time). Returns
    (chain_nodes sink-first, first_nonlinear_node_or_None)."""
    chain: List[NodeId] = []
    gid: GraphId = sink
    while gid != source:
        if isinstance(gid, SourceId):
            return chain, None  # foreign source; composition artifact
        op = graph.operators[gid]
        deps = graph.dependencies[gid]
        if getattr(op, "persist", False):
            gid = deps[0]
            continue
        if isinstance(op, DelegatingOperator):
            chain.append(gid)
            gid = deps[1]  # [estimator, input]
            continue
        if isinstance(op, GatherOperator) or len(deps) != 1:
            return chain, gid
        chain.append(gid)
        gid = deps[0]
    return chain, None


# ---------------------------------------------------------------------------
# The lint pass
# ---------------------------------------------------------------------------


def lint_graph(
    graph: Graph,
    source: SourceId,
    sink: GraphId,
    example: Any = None,
    serve: bool = False,
    have_ladder: Optional[bool] = None,
    refit: bool = False,
) -> LintReport:
    """Run every graph rule over ``graph`` and return a ``LintReport``.

    ``serve=True`` escalates the serveability rules (KG00x) to errors —
    the pre-``compiled()`` contract. ``example`` feeds the shape/dtype
    propagation (see ``_input_spec``); ``have_ladder`` overrides the
    bucket-ladder detection for KG101 (None = read
    ``config.serve_buckets``); ``refit=True`` additionally checks the
    ``Pipeline.refit_stream`` contract (KG105: head estimator without
    ``partial_fit`` — every cadence tick is a full head refit).
    """
    from keystone_tpu.config import config

    report = LintReport()
    emit = report.diagnostics.append
    serve_sev = "error" if serve else "warning"

    order = graph.reachable([sink])
    live = set(order)

    # -- KG201: dead nodes -------------------------------------------------
    for nid in graph.operators:
        if nid not in live:
            emit(Diagnostic(
                "KG201", "info", _node_label(graph, nid),
                "node is unreachable from the pipeline sink",
                hint="prune with graph.pruned([sink])",
            ))

    # -- serveability: KG001 / KG002 / KG003 -------------------------------
    chain, nonlinear = _walk_serve_chain(graph, source, sink)
    if nonlinear is not None:
        emit(Diagnostic(
            "KG003", serve_sev, _node_label(graph, nonlinear),
            f"{graph.operators[nonlinear].label()} joins multiple inputs; "
            "the bucketed serving engine compiles one linear program per "
            "bucket and cannot host a join",
            hint="serve the branches separately, or apply the gathered "
                 "pipeline un-compiled (per-shape jit)",
        ))
    for nid in chain:
        op = graph.operators[nid]
        if not isinstance(op, TransformerOperator):
            continue
        t = op.transformer
        if not getattr(t, "jittable", True):
            emit(Diagnostic(
                "KG001", serve_sev, _node_label(graph, nid),
                f"{type(t).__name__} is not jittable; the AOT serving path "
                "compiles the whole chain as one XLA program",
                hint="keep host transformers off the serve path, or serve "
                     "per-shape via Pipeline.apply",
            ))
        if not getattr(t, "row_independent", True):
            emit(Diagnostic(
                "KG002", serve_sev, _node_label(graph, nid),
                f"{type(t).__name__} couples output rows to other input "
                "rows (row_independent=False); bucket padding would change "
                "real outputs",
                hint="serve it per-shape (unset KEYSTONE_SERVE_BUCKETS) or "
                     "keep the row-coupled stage off the bucketed path",
            ))

    # -- shape/dtype propagation: KG101 / KG102 ----------------------------
    source_spec, polymorphic = _input_spec(example)
    specs = propagate_specs(graph, sink, source_spec)

    if have_ladder is None:
        have_ladder = bool(config.serve_buckets)
    jit_consumers = [
        nid for nid in order
        if isinstance(graph.operators[nid], TransformerOperator)
        and getattr(graph.operators[nid].transformer, "jittable", False)
    ]
    if polymorphic and jit_consumers and not have_ladder:
        emit(Diagnostic(
            "KG101", "warning", _node_label(graph, jit_consumers[0]),
            f"shape-polymorphic input feeds {len(jit_consumers)} jit "
            "node(s) with no bucket ladder: every distinct batch size "
            "recompiles the fused chain",
            hint="set KEYSTONE_SERVE_BUCKETS (or serve via "
                 "Pipeline.compiled(), which pads onto a pow-2 ladder)",
        ))

    for nid in order:
        op = graph.operators[nid]
        out = specs.get(nid)
        if out is None:
            continue
        deps = graph.dependencies[nid]
        dep_specs = [
            specs.get(d) if isinstance(d, NodeId) else source_spec
            for d in deps
        ]
        if isinstance(op, GatherOperator):
            dts = {str(s.dtype) for s in dep_specs if s is not None}
            if len(dts) > 1:
                emit(Diagnostic(
                    "KG102", "warning", _node_label(graph, nid),
                    f"gather joins mixed dtypes {sorted(dts)}; XLA silently "
                    f"upcasts the concatenation to {out.dtype}",
                    hint="cast the narrower branch explicitly where the "
                         "width is intended",
                ))
            continue
        if isinstance(op, TransformerOperator):
            d0 = dep_specs[0] if dep_specs else None
            if (
                d0 is not None
                and out.dtype != d0.dtype
                and out.dtype.itemsize > d0.dtype.itemsize
            ):
                emit(Diagnostic(
                    "KG102", "warning", _node_label(graph, nid),
                    f"silent upcast {d0.dtype} -> {out.dtype} across "
                    f"{op.label()}: doubles bytes/HBM for everything "
                    "downstream",
                    hint="cast explicitly if intended, or compute at the "
                         "input dtype",
                ))

    # ONE consumer map shared by KG103 and KG202: the full-graph
    # traversal is paid once per lint pass, not per rule.
    consumers = graph.consumers([sink])

    # -- KG103: shard-pad (batch rows never divide the data mesh) ----------
    # A pure static shape check — no execution, no placement: the device
    # list is only consulted for the mesh width, and failures to resolve
    # one (deviceless backends) simply skip the rule (the classifier
    # answers "inert" there). One classifier shared with the runtime
    # placement (DatasetOperator) and the chain lowering (batch_layout),
    # so the lint can never drift from what execution actually does.
    if config.shard_data_batches:
        from keystone_tpu.utils.mesh import (
            host_batch_shard_class,
            num_data_shards,
        )

        try:
            shards = int(num_data_shards())
        except RuntimeError:  # deviceless backend: no mesh to divide
            shards = 0

        def _feeds_jittable_chain(start: NodeId) -> bool:
            """Does the dataset's row count reach a jittable chain? Walk
            downstream through row-preserving transformer stages (host
            normalizers etc. keep the batch's row count, so the pad cost
            still lands on the first jittable stage after them); stop at
            estimators/gathers-of-other-rows — labels/side inputs
            consumed solely by estimators are re-padded once inside
            RowMatrix regardless, and warning on them would train
            operators to ignore the rule."""
            seen, stack = set(), [start]
            while stack:
                nid = stack.pop()
                for u in consumers.get(nid, ()):
                    if not isinstance(u, NodeId) or u in seen:
                        continue
                    seen.add(u)
                    u_op = graph.operators.get(u)
                    if isinstance(u_op, TransformerOperator):
                        if getattr(u_op.transformer, "jittable", False):
                            return True
                        if getattr(u_op.transformer, "row_independent",
                                   True):
                            stack.append(u)  # rows survive the host stage
                    elif getattr(u_op, "persist", False):
                        stack.append(u)  # identity cache node
            return False

        for nid in (order if shards > 1 else ()):
            op = graph.operators[nid]
            if not isinstance(op, DatasetOperator):
                continue
            if host_batch_shard_class(op.data, shards) != "pad":
                continue
            if not _feeds_jittable_chain(nid):
                continue
            rows = int(op.data.shape[0])
            pad = (-rows) % shards
            emit(Diagnostic(
                "KG103", "warning", _node_label(graph, nid),
                f"batch of {rows} rows can never divide the "
                f"{shards}-shard data mesh: every fused-chain call "
                f"over it mask-pads {pad} row(s) onto the mesh "
                "(the old silent single-device cliff, now padded)",
                hint="size batches to a multiple of the mesh "
                     f"width ({shards}) to shard without padding",
            ))

        # -- KG106: estimator fit chain lowers without donation --------
        # Same classifier, same shape-only discipline as KG103. The
        # "shard" class is placed onto the mesh by DatasetOperator, so
        # the fused chain's input arrives caller-owned: the lowering
        # cannot donate it (placed values can be multi-consumer via
        # gather / the by-hash memo), and an accumulator-carrying fit
        # over it holds batch + chain output live at once while
        # ``config.donate_buffers`` promises one live copy. Host-staged
        # arrivals (streamed batches, the pad class) donate the staging
        # copy the chain call itself creates.
        if config.donate_buffers:

            def _feeds_estimator_via_jittable(start: NodeId) -> bool:
                """Does a jittable chain stand between this dataset and
                an estimator's fit? Walk downstream like KG103's helper,
                but keep going past the first jittable stage until an
                ``EstimatorOperator`` consumes the chain's output."""
                seen = set()
                stack = [(start, False)]
                while stack:
                    nid_, jit_seen = stack.pop()
                    for u in consumers.get(nid_, ()):
                        if not isinstance(u, NodeId) or (u, jit_seen) in seen:
                            continue
                        seen.add((u, jit_seen))
                        u_op = graph.operators.get(u)
                        if isinstance(u_op, EstimatorOperator):
                            if jit_seen:
                                return True
                        elif isinstance(u_op, TransformerOperator):
                            stack.append((
                                u,
                                jit_seen or getattr(
                                    u_op.transformer, "jittable", False
                                ),
                            ))
                        elif getattr(u_op, "persist", False):
                            stack.append((u, jit_seen))
                return False

            for nid in (order if shards > 1 else ()):
                op = graph.operators[nid]
                if not isinstance(op, DatasetOperator):
                    continue
                if host_batch_shard_class(op.data, shards) != "shard":
                    continue
                if not _feeds_estimator_via_jittable(nid):
                    continue
                rows = int(op.data.shape[0])
                emit(Diagnostic(
                    "KG106", "warning", _node_label(graph, nid),
                    f"fit chain over this {rows}-row mesh-placed batch "
                    "lowers WITHOUT donation (the placed input is "
                    "caller-owned), so the fit holds batch + chain "
                    "output live at once while config.donate_buffers "
                    "promises in-place updates",
                    hint="stream the batches (host arrivals stage-and-"
                         "donate their copy), or pin "
                         "KEYSTONE_DONATE_BUFFERS=0 if two live copies "
                         "are intended",
                ))

    # -- KG104: pinned memory plan priced beyond the HBM budget ------------
    # Shape-only pricing off the propagated specs — no execution, no
    # compile, no device work. Only PINNED plans are priced (an explicit
    # serve bucket ladder / an explicit solve chunk size): the un-pinned
    # defaults go through the warmup/optimizer planners, which auto-size
    # them under the same budget fractions, so flagging those would warn
    # about a plan that will never run as written.
    from keystone_tpu.config import (
        resolved_serve_buckets,
        resolved_solve_chunk_rows,
    )
    from keystone_tpu.utils.metrics import device_hbm_bytes

    def _row_bytes(spec, itemsize=None) -> int:
        import numpy as np

        shape = tuple(spec.shape[1:])
        size = itemsize if itemsize is not None else spec.dtype.itemsize
        return int(np.prod(shape, dtype=np.int64)) * int(size)

    budget = device_hbm_bytes()
    ladder = resolved_serve_buckets() or config.serve_buckets
    if ladder and source_spec is not None:
        from keystone_tpu.workflow.rules import SERVE_LADDER_BUDGET_FRAC

        # The storage dtype the ladder warms at: bf16 serving stores the
        # request batch at half the bytes (the precision-ladder boundary
        # cast); f32/f32h keep the spec's dtype.
        in_itemsize = (
            2 if config.serve_precision == "bf16"
            else source_spec.dtype.itemsize
        )
        replicas = config.serve_devices
        if replicas == 0:
            import jax

            try:
                replicas = len(jax.local_devices())
            except Exception:  # lint: broad-ok deviceless backend: price a one-replica pool
                replicas = 1
        # Per-row price = input + EVERY known node output (the runtime
        # planner's conservative all-activations-resident price — the
        # 512-feature intermediate of a featurize chain dominates, and
        # pricing only the in/out boundary would systematically miss
        # genuinely over-budget ladders).
        bpr = _row_bytes(source_spec, in_itemsize) + sum(
            _row_bytes(s) for s in (specs.get(nid) for nid in order)
            if s is not None
        )
        ladder_bytes = (
            sum(int(b) * bpr for b in ladder) * max(1, int(replicas))
        )
        ladder_budget = budget // SERVE_LADDER_BUDGET_FRAC
        if ladder_bytes > ladder_budget:
            emit(Diagnostic(
                "KG104", "warning", "-",
                f"pinned serve ladder {tuple(int(b) for b in ladder)} x "
                f"{replicas} replica(s) at serve_precision="
                f"{config.serve_precision} prices {ladder_bytes} resident "
                f"bytes — beyond the {ladder_budget}-byte ladder budget "
                f"(device HBM {budget} // {SERVE_LADDER_BUDGET_FRAC}); "
                "warmup would pin more executables than the device holds",
                hint="drop rungs from KEYSTONE_SERVE_BUCKETS, serve fewer "
                     "replicas, or unset the ladder so the HBM planner "
                     "sizes it",
            ))
    chunk_rows = resolved_solve_chunk_rows()
    if chunk_rows is None:
        chunk_rows = config.solve_chunk_rows
    if chunk_rows and chunk_rows > 0:
        from keystone_tpu.workflow.rules import PlanResourcesRule

        chunk_budget = budget // PlanResourcesRule.CHUNK_BUDGET_FRAC
        for nid in order:
            if not isinstance(graph.operators[nid], EstimatorOperator):
                continue
            deps = graph.dependencies[nid]
            d0 = deps[0] if deps else None
            spec = (
                specs.get(d0) if isinstance(d0, NodeId) else source_spec
            )
            if spec is None:
                continue
            chunk_bytes = int(chunk_rows) * _row_bytes(spec)
            if chunk_bytes > chunk_budget:
                emit(Diagnostic(
                    "KG104", "warning", _node_label(graph, nid),
                    f"pinned solve chunk of {int(chunk_rows)} rows x "
                    f"{_row_bytes(spec)} B/row prices {chunk_bytes} bytes "
                    f"per H2D transfer — beyond the {chunk_budget}-byte "
                    f"chunk budget (device HBM {budget} // "
                    f"{PlanResourcesRule.CHUNK_BUDGET_FRAC}); the solve "
                    "would fall back to reactive OOM-halving",
                    hint="lower KEYSTONE_SOLVE_CHUNK_ROWS, or unset it so "
                         "the profile-guided planner sizes the chunk",
                ))

    # -- KG108: capacity model enabled under hand-pinned resources ---------
    # Static config reads only (the KG104 discipline): the pin/enable
    # state is entirely resolvable without execution, and only PINNED
    # configurations are flagged — the un-pinned defaults are exactly
    # what the capacity re-plan loop is allowed to size, so they are the
    # healthy configuration, not a finding.
    from keystone_tpu.config import resolved_capacity_model

    if resolved_capacity_model():
        pins = []
        if ladder:
            pins.append(
                f"serve bucket ladder {tuple(int(b) for b in ladder)} "
                "(KEYSTONE_SERVE_BUCKETS / config.serve_buckets)"
            )
        if config.serve_devices != 0:
            pins.append(
                f"replica count {int(config.serve_devices)} "
                "(KEYSTONE_SERVE_DEVICES)"
            )
        if pins:
            emit(Diagnostic(
                "KG108", "warning", "-",
                "the learned capacity model is enabled "
                "(KEYSTONE_CAPACITY_MODEL / telemetry dir configured) but "
                f"{' and '.join(pins)} are hand-pinned: the capacity "
                "re-plan loop refuses pinned resources by contract, so "
                "traffic-aware autoscaling is silently defeated — the "
                "model observes mix shifts it is never allowed to act on",
                hint="unset the pin(s) so the re-plan loop can size the "
                     "replica pool / re-price the ladder from the observed "
                     "traffic mix, or disable the model "
                     "(KEYSTONE_CAPACITY_MODEL=0) if the pins are "
                     "intentional",
            ))

    # -- KG105: refit-stream head without partial_fit ----------------------
    # Only under the refit contract (refit=True): a batch-only head is a
    # perfectly fine BATCH pipeline — the hazard exists solely when the
    # operator intends to stream refits through it. One head definition
    # shared with refit_stream/OnlineTrainer (workflow.online), so the
    # lint can never disagree with what the runtime would do.
    if refit:
        from keystone_tpu.workflow.online import (
            refit_head_estimator,
            supports_partial_fit,
        )

        head_est = refit_head_estimator(graph, sink)
        if head_est is not None and not supports_partial_fit(head_est):
            emit(Diagnostic(
                "KG105", "warning", type(head_est).__name__,
                f"{type(head_est).__name__} does not implement "
                "partial_fit: refit_stream will fall back to a FULL head "
                "refit (over the whole buffered stream) on every cadence "
                "tick instead of a cheap accumulator re-solve",
                hint="use a normal-equation head (LinearMapEstimator / "
                     "BlockLeastSquaresEstimator / LeastSquaresEstimator) "
                     "or accept the counted online.full_refits cost",
            ))

    # -- KG107: checkpoint_dir state recorded under a different mesh -------
    # Pure static read: the checkpoint writers drop a JSON mesh sidecar
    # (utils.mesh.write_mesh_manifest) next to their payloads, so the
    # width comparison is one dict read per checkpointed estimator — no
    # unpickling, no orbax restore, no execution. Absent sidecars
    # (pre-elastic directories, no checkpoint yet) stay silent: the
    # resume-time triage is authoritative; this is the early warning.
    for nid, op in graph.operators.items():
        if not isinstance(op, EstimatorOperator):
            continue
        ckpt_dir = getattr(
            getattr(op, "estimator", None), "checkpoint_dir", None
        )
        if not ckpt_dir:
            continue
        from keystone_tpu.utils.mesh import (
            num_data_shards,
            read_mesh_manifest,
        )

        manifest = read_mesh_manifest(ckpt_dir)
        if manifest is None:
            continue
        recorded = manifest.get("device_count")
        if recorded is None:
            continue
        try:
            active = int(num_data_shards())
        except RuntimeError:  # deviceless backend: no mesh to drift from
            continue
        if int(recorded) == active:
            continue
        emit(Diagnostic(
            "KG107", "warning", _node_label(graph, nid),
            f"checkpoint_dir {ckpt_dir} holds solver state recorded "
            f"under a {int(recorded)}-shard mesh, but the active data "
            f"mesh has {active} shards: the fit will migrate the state "
            "at resume (elastic mesh, counted in the 'elastic' metrics "
            "family) — or refuse with MeshMismatchError under "
            "KEYSTONE_ELASTIC_MESH=0",
            hint="expected with an intentional width change (the elastic "
                 "migration is bit-identical); otherwise point "
                 "checkpoint_dir at state recorded on this mesh, or "
                 "migrate it explicitly with utils.mesh.reshard_state",
        ))

    # -- KG202: cache placement advice (consumer map shared with KG103) ----
    for gid, users in consumers.items():
        if not isinstance(gid, NodeId):
            continue
        op = graph.operators[gid]
        if isinstance(op, (DatasetOperator, DatumOperator)):
            continue  # constants are free to "recompute"
        if getattr(op, "persist", False):
            continue
        node_users = [u for u in users if isinstance(u, NodeId)]
        if len(node_users) < 2:
            continue
        if any(
            getattr(graph.operators[u], "persist", False) for u in node_users
        ):
            continue  # one consumer is already a cache node
        emit(Diagnostic(
            "KG202", "info", _node_label(graph, gid),
            f"subchain output is consumed by {len(node_users)} nodes with "
            "no cache node; each consumer recomputes the prefix",
            hint="insert .cache() after the shared prefix (or enable "
                 "config.auto_cache)",
        ))

    # -- KG203: stored measured profile not consumed -----------------------
    # Only when a store is configured: the existence probe is one stat(),
    # and the digest walk is skipped entirely for unstored sessions.
    from keystone_tpu.config import resolved_profile_store

    if resolved_profile_store() and not config.auto_cache:
        from keystone_tpu.workflow.profile_store import (
            has_profile,
            pipeline_profile_digest,
        )

        if has_profile(pipeline_profile_digest(graph, sink)):
            emit(Diagnostic(
                "KG203", "info", "-",
                "a measured profile for this pipeline exists in the "
                "profile store, but config.auto_cache is off — the "
                "cache rule will run model-only and the measured costs "
                "go unused for cache placement (the resource planner "
                "may still consume them)",
                hint="enable config.auto_cache to consume the stored "
                     "profile for cache placement with zero sample runs",
            ))

    return report


# ---------------------------------------------------------------------------
# The opt-in pre-fit / pre-compiled gate
# ---------------------------------------------------------------------------


def enforce_lint(pipeline, stage: str, serve: bool = False,
                 have_ladder: Optional[bool] = None,
                 refit: bool = False) -> Optional[LintReport]:
    """Run the graph lint as a gate when ``KEYSTONE_LINT`` asks for it.

    ``off`` (default): no-op, zero cost beyond one config read.
    ``warn``: log each finding at its severity, never block.
    ``error``: additionally raise ``LintError`` when any error-severity
    finding exists — the pre-execution refusal the rule catalog promises.
    """
    from keystone_tpu.config import config

    mode = config.lint
    if mode == "off":
        return None
    report = lint_graph(
        pipeline.graph, pipeline.source, pipeline.sink,
        serve=serve, have_ladder=have_ladder, refit=refit,
    )
    for d in report:
        log = logger.error if d.severity == "error" else (
            logger.warning if d.severity == "warning" else logger.info
        )
        log("lint[%s] %s %s: %s", stage, d.rule, d.node, d.message)
    errors = report.errors()
    if mode == "error" and errors:
        raise LintError(
            f"KEYSTONE_LINT=error: {len(errors)} error-severity finding(s) "
            f"before {stage}:\n" + "\n".join(
                f"  {d.rule} {d.node}: {d.message}" for d in errors
            )
        )
    return report
