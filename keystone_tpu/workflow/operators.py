"""Graph operators — the node payloads of the pipeline DAG.

Ref: src/main/scala/workflow/Operator.scala (TransformerOperator,
EstimatorOperator, DelegatingOperator, DatasetOperator, DatumOperator)
[unverified]. Expressions in the reference are lazy wrappers over RDDs; here
an "expression" value is simply a batch (jax/numpy array or host sequence), a
single datum, or a fitted Transformer.
"""

from __future__ import annotations

from typing import Any, List, Sequence

import jax.numpy as jnp


class Operator:
    """Base operator. ``execute`` consumes evaluated dependency values."""

    def execute(self, deps: Sequence[Any]) -> Any:
        raise NotImplementedError

    def signature(self) -> Any:
        """Identity key used for structural prefix hashing. The id fallback
        carries the UNSTABLE poison so heap addresses can never leak into a
        cross-process digest (a recycled id must not produce a disk hit)."""
        from keystone_tpu.workflow.fingerprint import UNSTABLE

        return ("op", id(self), UNSTABLE)

    def prefix_hash(self, dep_hashes) -> int:
        """Structural hash of this node given its dependency prefix hashes."""
        return hash((self.signature(), tuple(dep_hashes)))

    def prefix_digest(self, dep_digests):
        """Content-stable digest of this node's prefix (the cross-process
        analog of ``prefix_hash``), or None when any part is id-based."""
        from keystone_tpu.workflow.fingerprint import digest_tree

        if any(d is None for d in dep_digests):
            return None
        return digest_tree((self.signature(), tuple(dep_digests)))

    def pinned_objects(self):
        """Objects whose id() feeds this operator's signature. Cache entries
        keyed on prefixes through this node hold strong references to these so
        CPython id reuse can never alias a stale cache entry."""
        return ()

    def label(self) -> str:
        return type(self).__name__


class DatasetOperator(Operator):
    """A constant batch of data spliced into the graph (the RDD analog).

    Array batches are row-sharded over the default mesh on execution, so
    the jittable transformer chain downstream runs data-parallel across
    chips — the per-partition map of the reference. Divisible batches are
    placed here with the explicit data sharding; non-divisible batches are
    deferred to the fused chain's mask-pad path (``Transformer.batch_call``
    pads onto the mesh and trims, the pad-inert idiom), so a batch that
    doesn't divide the mesh no longer silently degrades to single-device.
    The only surviving fallback — batches below ``config.shard_min_rows``
    — is counted in the metrics registry (``sharding.fallback_small_batch``)
    so it is visible, never silent.
    """

    def __init__(self, data: Any):
        self.data = data

    def execute(self, deps):
        import logging

        import jax

        from keystone_tpu.config import config

        data = self.data
        if not config.shard_data_batches:
            return data
        # One classifier shared with batch_layout and the KG103 lint
        # (utils.mesh.host_batch_shard_class), so placement, lowering,
        # and static analysis can never drift apart. A jax.Array already
        # has a placement (explicit or default) that we must not
        # override; non-numeric arrays belong to host transformers —
        # both are "inert" here.
        from keystone_tpu.utils.mesh import (
            data_sharding,
            host_batch_shard_class,
        )
        from keystone_tpu.utils.metrics import sharding_counters

        klass = host_batch_shard_class(data)
        if klass == "inert":
            return data
        if klass == "small":
            # The ONLY surviving single-device fallback: placement overhead
            # beats the win below the row floor. Counted AND logged so a
            # fit that quietly ran narrow is visible in the registry.
            sharding_counters.bump("fallback_small_batch")
            logging.getLogger("keystone_tpu").info(
                "batch of %d rows is below shard_min_rows=%d; running this "
                "dataset single-device",
                data.shape[0],
                config.shard_min_rows,
            )
            return data
        if klass == "pad":
            # Deferred, not dropped: jax refuses an uneven device_put, so
            # the fused chain's sharded call mask-pads this batch onto the
            # mesh (mesh.SpecLayout.pad_put) and trims the pad rows back
            # out — downstream row counts are unchanged and the chain
            # still lowers with explicit shardings.
            sharding_counters.bump("batches_deferred_pad")
            return data
        sharding_counters.bump("batches_sharded")
        return jax.device_put(data, data_sharding())

    def signature(self):
        """Content fingerprint for numeric host arrays (hashed once per
        operator), id fallback otherwise. Content identity means a rerun —
        or another process — that splices byte-identical data shares cached
        fits downstream."""
        sig = getattr(self, "_sig_cache", None)
        if sig is None:
            import jax
            import numpy as np

            from keystone_tpu.workflow.fingerprint import (
                UNSTABLE,
                array_fingerprint,
            )

            from keystone_tpu.config import config

            data = self.data
            if isinstance(data, jax.Array):
                if data.nbytes > config.fingerprint_max_bytes:
                    # Sampled hashing would still need the full D2H copy
                    # for a device array; not worth it.
                    self._sig_cache = ("dataset", id(self.data), UNSTABLE)
                    return self._sig_cache
                data = np.asarray(data)
            if isinstance(data, np.ndarray) and data.dtype.kind in "biufc":
                # array_fingerprint switches to a bounded chunk-sampled
                # digest above config.fingerprint_max_bytes, so huge fit
                # inputs stay content-addressed at fixed cost.
                sig = ("dataset", array_fingerprint(data))
            elif isinstance(data, (list, tuple)) and data:
                from keystone_tpu.workflow.fingerprint import text_fingerprint

                fp = text_fingerprint(data)
                sig = (
                    ("dataset", fp)
                    if fp is not None
                    else ("dataset", id(self.data), UNSTABLE)
                )
            else:
                sig = ("dataset", id(self.data), UNSTABLE)
            self._sig_cache = sig
        return sig

    def pinned_objects(self):
        return (self.data,)

    def label(self):
        return "Dataset"


class DatumOperator(Operator):
    """A single constant datum."""

    def __init__(self, datum: Any):
        self.datum = datum

    def execute(self, deps):
        return self.datum

    def signature(self):
        from keystone_tpu.workflow.fingerprint import UNSTABLE

        return ("datum", id(self.datum), UNSTABLE)

    def pinned_objects(self):
        return (self.datum,)

    def label(self):
        return "Datum"


class TransformerOperator(Operator):
    """Applies a Transformer to its single input batch."""

    def __init__(self, transformer):
        self.transformer = transformer

    def execute(self, deps):
        return self.transformer.batch_call(deps[0])

    def signature(self):
        return ("transformer", self.transformer.signature())

    def prefix_hash(self, dep_hashes):
        # Delegated so that a fused chain hashes identically to the unfused
        # chain it replaced (FusedTransformer folds stage-by-stage).
        return self.transformer.chain_hash(dep_hashes[0])

    def prefix_digest(self, dep_digests):
        if dep_digests[0] is None:
            return None
        return self.transformer.chain_digest(dep_digests[0])

    def pinned_objects(self):
        return (self.transformer,)

    def label(self):
        # A fused chain names its stages: the profiler/trace attribution
        # row for one XLA program should say WHICH operators it fused,
        # not the anonymous wrapper class.
        stages = getattr(self.transformer, "stages", None)
        if stages:
            return "Fused(" + "|".join(type(s).__name__ for s in stages) + ")"
        return type(self.transformer).__name__


class EstimatorOperator(Operator):
    """Fits an Estimator/LabelEstimator on its input(s); the value produced is
    the fitted Transformer (a TransformerExpression in reference terms)."""

    def __init__(self, estimator):
        self.estimator = estimator

    def execute(self, deps):
        return self.estimator.fit(*deps)

    def signature(self):
        """Content-stable when the estimator's signature is (class +
        hyperparams, see pipeline.Estimator.signature); id-keyed otherwise.
        Memoized at first use so an estimator that mutates its own fields
        while fitting keeps one identity for this node — otherwise the
        post-fit signature could never hit the entry cached under the
        pre-fit one. The estimator stays pinned either way, so id-based
        fields can never alias across its lifetime."""
        sig = getattr(self, "_sig_cache", None)
        if sig is None:
            sig_fn = getattr(self.estimator, "signature", None)
            if sig_fn is not None:
                sig = ("estimator", sig_fn())
            else:
                from keystone_tpu.workflow.fingerprint import UNSTABLE

                sig = ("estimator", id(self.estimator), UNSTABLE)
            self._sig_cache = sig
        return sig

    def pinned_objects(self):
        return (self.estimator,)

    def label(self):
        return type(self.estimator).__name__ + ".fit"


class DelegatingOperator(Operator):
    """Applies the fitted transformer produced by an estimator node.

    deps = [fitted_transformer, input_batch].
    Ref: workflow/Operator.scala DelegatingOperator [unverified].
    """

    def execute(self, deps):
        fitted, x = deps
        return fitted.batch_call(x)

    def signature(self):
        # The behaviour is fully determined by the estimator dep's hash, so a
        # shared constant signature keeps structurally-equal graphs equal.
        return ("delegating",)

    def label(self):
        return "Delegating"


class GatherOperator(Operator):
    """Concatenates branch outputs along the feature (last) axis.

    Ref: Pipeline.gather building a gather node over branch sinks
    (workflow/Pipeline.scala) [unverified]. On TPU this lowers to one XLA
    concatenate, which typically fuses with downstream consumers.
    """

    def execute(self, deps: Sequence[Any]):
        return jnp.concatenate([jnp.asarray(d) for d in deps], axis=-1)

    def signature(self):
        return ("gather",)

    def label(self):
        return "Gather"
