// Parallel JPEG decode pool — the native ingest path.
//
// The reference feeds ImageNet from JPEG tars through JVM-side decode
// (Ref: loaders/ImageNetLoader.scala [unverified]); the measured Python/PIL
// pool tops out around ~340 images/s/host at 256px, which a TPU-rate
// featurization pipeline outruns. This pool removes both limiters: libjpeg
// DCT-domain scaling cuts the IDCT work to the smallest 1/den >= target
// size, and OpenMP parallelizes across images with no interpreter in the
// loop. Clean-room; uses only the public libjpeg API.

#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

#include <jpeglib.h>

#include "keystone_native.h"

namespace {

struct ErrorTrap {
  jpeg_error_mgr mgr;
  std::jmp_buf jump;
};

void on_error(j_common_ptr cinfo) {
  ErrorTrap* trap = reinterpret_cast<ErrorTrap*>(cinfo->err);
  std::longjmp(trap->jump, 1);
}

void silence(j_common_ptr, int) {}
void silence_msg(j_common_ptr) {}

// Bilinear resize (h, w, 3) uint8 -> (size, size, 3) float32 in [0, 1].
void resize_bilinear(const unsigned char* src, int h, int w, int size,
                     float* dst) {
  const float sy = static_cast<float>(h) / size;
  const float sx = static_cast<float>(w) / size;
  for (int oy = 0; oy < size; ++oy) {
    float fy = (oy + 0.5f) * sy - 0.5f;
    int y0 = fy < 0 ? 0 : static_cast<int>(fy);
    if (y0 > h - 2) y0 = h - 2 < 0 ? 0 : h - 2;
    float wy = fy - y0;
    if (wy < 0) wy = 0;
    if (wy > 1) wy = 1;
    int y1 = y0 + 1 < h ? y0 + 1 : y0;
    for (int ox = 0; ox < size; ++ox) {
      float fx = (ox + 0.5f) * sx - 0.5f;
      int x0 = fx < 0 ? 0 : static_cast<int>(fx);
      if (x0 > w - 2) x0 = w - 2 < 0 ? 0 : w - 2;
      float wx = fx - x0;
      if (wx < 0) wx = 0;
      if (wx > 1) wx = 1;
      int x1 = x0 + 1 < w ? x0 + 1 : x0;
      const unsigned char* p00 = src + (static_cast<size_t>(y0) * w + x0) * 3;
      const unsigned char* p01 = src + (static_cast<size_t>(y0) * w + x1) * 3;
      const unsigned char* p10 = src + (static_cast<size_t>(y1) * w + x0) * 3;
      const unsigned char* p11 = src + (static_cast<size_t>(y1) * w + x1) * 3;
      float* o = dst + (static_cast<size_t>(oy) * size + ox) * 3;
      for (int c = 0; c < 3; ++c) {
        float top = p00[c] + (p01[c] - p00[c]) * wx;
        float bot = p10[c] + (p11[c] - p10[c]) * wx;
        o[c] = (top + (bot - top) * wy) * (1.0f / 255.0f);
      }
    }
  }
}

// Decode one jpeg into (size, size, 3) float32. Returns false on failure.
// noexcept boundary: a C++ exception escaping an OpenMP worker (or the
// extern "C" frame into ctypes) would terminate the process, so everything
// — including bad_alloc from a jpeg header declaring absurd dimensions —
// converts to a per-image failure here.
bool decode_one(const std::uint8_t* buf, std::uint64_t len, int size,
                float* out) noexcept try {
  jpeg_decompress_struct cinfo;
  ErrorTrap trap;
  cinfo.err = jpeg_std_error(&trap.mgr);
  trap.mgr.error_exit = on_error;
  trap.mgr.emit_message = silence;
  trap.mgr.output_message = silence_msg;
  std::vector<unsigned char> pixels;
  if (setjmp(trap.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  // DCT-domain downscale: the largest 1/den in {1,2,4,8} whose output still
  // covers the target — most of the IDCT work disappears before resize.
  cinfo.scale_num = 1;
  cinfo.scale_denom = 1;
  for (int den = 8; den >= 2; den /= 2) {
    if (static_cast<int>(cinfo.image_width) / den >= size &&
        static_cast<int>(cinfo.image_height) / den >= size) {
      cinfo.scale_denom = den;
      break;
    }
  }
  jpeg_start_decompress(&cinfo);
  const int w = cinfo.output_width;
  const int h = cinfo.output_height;
  if (cinfo.output_components != 3 || w <= 0 || h <= 0) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  pixels.resize(static_cast<size_t>(h) * w * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = pixels.data() + static_cast<size_t>(cinfo.output_scanline) * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  resize_bilinear(pixels.data(), h, w, size, out);
  return true;
} catch (...) {
  return false;
}

}  // namespace

extern "C" int ks_decode_jpeg_batch(const std::uint8_t* data,
                                    const std::uint64_t* offsets, int n,
                                    int size, float* out) {
  if (!data || !offsets || !out || n < 0 || size <= 0) return -1000000;
  int failed = 0;  // first failing index + 1 (0 = none)
#pragma omp parallel for schedule(dynamic)
  for (int i = 0; i < n; ++i) {
    const std::uint8_t* buf = data + offsets[i];
    const std::uint64_t len = offsets[i + 1] - offsets[i];
    float* dst = out + static_cast<size_t>(i) * size * size * 3;
    if (!decode_one(buf, len, size, dst)) {
#pragma omp critical
      {
        if (failed == 0 || i + 1 < failed) failed = i + 1;
      }
    }
  }
  return failed ? -failed : 0;
}
