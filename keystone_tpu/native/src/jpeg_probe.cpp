// Build probe: does this host have usable libjpeg dev files?
// Compiled (not linked into the library) by the Makefile's HAVE_JPEG check.
#include <cstdio>

#include <jpeglib.h>

int main() { return JPEG_LIB_VERSION >= 0 ? 0 : 1; }
