// Diagonal-covariance GMM (EM) and Fisher-vector encoding (clean-room).
//
// Parity targets: utils.external.EncEval.{computeGMM, calcAndGetFVs}
// (SURVEY.md §2.3) [unverified]. The math follows the standard
// Perronnin-style improved-Fisher-vector formulation; the normalization
// (signed sqrt, L2) is intentionally left to pipeline nodes, mirroring the
// reference where SignedHellingerMapper is a separate stage.

#include "keystone_native.h"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <limits>
#include <random>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

constexpr float kMinVar = 1e-4f;
constexpr float kTwoPi = 6.28318530717958647692f;

// log sum exp over k contiguous floats.
float logsumexp(const float* v, int k) {
  float m = v[0];
  for (int i = 1; i < k; ++i) m = std::max(m, v[i]);
  float s = 0.0f;
  for (int i = 0; i < k; ++i) s += std::exp(v[i] - m);
  return m + std::log(s);
}

// Per-sample responsibilities into r (n, k); returns total log-likelihood.
double e_step(const float* X, int n, int d, const float* w, const float* mu,
              const float* var, int k, float* r) {
  // Precompute per-component log normalizers.
  std::vector<float> log_norm(k);
  for (int j = 0; j < k; ++j) {
    float ld = 0.0f;
    for (int t = 0; t < d; ++t) ld += std::log(var[j * d + t]);
    log_norm[j] = std::log(std::max(w[j], 1e-12f)) -
                  0.5f * (d * std::log(kTwoPi) + ld);
  }
  double total = 0.0;
#ifdef _OPENMP
#pragma omp parallel for reduction(+ : total) schedule(static)
#endif
  for (int i = 0; i < n; ++i) {
    const float* x = X + static_cast<std::size_t>(i) * d;
    float* ri = r + static_cast<std::size_t>(i) * k;
    for (int j = 0; j < k; ++j) {
      const float* m = mu + static_cast<std::size_t>(j) * d;
      const float* v = var + static_cast<std::size_t>(j) * d;
      float q = 0.0f;
      for (int t = 0; t < d; ++t) {
        const float diff = x[t] - m[t];
        q += diff * diff / v[t];
      }
      ri[j] = log_norm[j] - 0.5f * q;
    }
    const float lse = logsumexp(ri, k);
    total += lse;
    for (int j = 0; j < k; ++j) ri[j] = std::exp(ri[j] - lse);
  }
  return total;
}

}  // namespace

extern "C" {

int ks_gmm_fit(const float* X, int n, int d, int k, int iters,
               std::uint64_t seed, float* weights, float* means, float* vars) {
  if (!X || !weights || !means || !vars || n < k || d <= 0 || k <= 0 ||
      iters < 0)
    return -1;

  // ---- init: distance-weighted (k-means++-style) seeding ----
  std::mt19937_64 rng(seed);
  std::uniform_int_distribution<int> uni(0, n - 1);
  std::vector<float> d2(n, std::numeric_limits<float>::max());
  int first = uni(rng);
  std::memcpy(means, X + static_cast<std::size_t>(first) * d,
              d * sizeof(float));
  for (int j = 1; j < k; ++j) {
    double sum = 0.0;
#ifdef _OPENMP
#pragma omp parallel for reduction(+ : sum) schedule(static)
#endif
    for (int i = 0; i < n; ++i) {
      const float* x = X + static_cast<std::size_t>(i) * d;
      const float* m = means + static_cast<std::size_t>(j - 1) * d;
      float dist = 0.0f;
      for (int t = 0; t < d; ++t) {
        const float diff = x[t] - m[t];
        dist += diff * diff;
      }
      d2[i] = std::min(d2[i], dist);
      sum += d2[i];
    }
    std::uniform_real_distribution<double> u(0.0, sum);
    double target = u(rng), acc = 0.0;
    int pick = n - 1;
    for (int i = 0; i < n; ++i) {
      acc += d2[i];
      if (acc >= target) {
        pick = i;
        break;
      }
    }
    std::memcpy(means + static_cast<std::size_t>(j) * d,
                X + static_cast<std::size_t>(pick) * d, d * sizeof(float));
  }
  // Global variance as the initial spread; uniform weights.
  std::vector<double> gmean(d, 0.0), gvar(d, 0.0);
  for (int i = 0; i < n; ++i)
    for (int t = 0; t < d; ++t) gmean[t] += X[static_cast<std::size_t>(i) * d + t];
  for (int t = 0; t < d; ++t) gmean[t] /= n;
  for (int i = 0; i < n; ++i)
    for (int t = 0; t < d; ++t) {
      const double diff = X[static_cast<std::size_t>(i) * d + t] - gmean[t];
      gvar[t] += diff * diff;
    }
  for (int j = 0; j < k; ++j) {
    weights[j] = 1.0f / k;
    for (int t = 0; t < d; ++t)
      vars[static_cast<std::size_t>(j) * d + t] =
          std::max(static_cast<float>(gvar[t] / n), kMinVar);
  }

  // ---- EM ----
  std::vector<float> r(static_cast<std::size_t>(n) * k);
  for (int it = 0; it < iters; ++it) {
    e_step(X, n, d, weights, means, vars, k, r.data());
    // M-step: accumulate per-component moments.
    std::vector<double> nk(k, 0.0);
    std::vector<double> sum1(static_cast<std::size_t>(k) * d, 0.0);
    std::vector<double> sum2(static_cast<std::size_t>(k) * d, 0.0);
#ifdef _OPENMP
#pragma omp parallel
    {
      std::vector<double> lnk(k, 0.0);
      std::vector<double> ls1(static_cast<std::size_t>(k) * d, 0.0);
      std::vector<double> ls2(static_cast<std::size_t>(k) * d, 0.0);
#pragma omp for schedule(static) nowait
      for (int i = 0; i < n; ++i) {
        const float* x = X + static_cast<std::size_t>(i) * d;
        const float* ri = r.data() + static_cast<std::size_t>(i) * k;
        for (int j = 0; j < k; ++j) {
          const double g = ri[j];
          if (g < 1e-10) continue;
          lnk[j] += g;
          double* s1 = ls1.data() + static_cast<std::size_t>(j) * d;
          double* s2 = ls2.data() + static_cast<std::size_t>(j) * d;
          for (int t = 0; t < d; ++t) {
            const double gx = g * x[t];
            s1[t] += gx;
            s2[t] += gx * x[t];
          }
        }
      }
#pragma omp critical
      {
        for (int j = 0; j < k; ++j) nk[j] += lnk[j];
        for (std::size_t idx = 0; idx < sum1.size(); ++idx) {
          sum1[idx] += ls1[idx];
          sum2[idx] += ls2[idx];
        }
      }
    }
#else
    for (int i = 0; i < n; ++i) {
      const float* x = X + static_cast<std::size_t>(i) * d;
      const float* ri = r.data() + static_cast<std::size_t>(i) * k;
      for (int j = 0; j < k; ++j) {
        const double g = ri[j];
        if (g < 1e-10) continue;
        nk[j] += g;
        double* s1 = sum1.data() + static_cast<std::size_t>(j) * d;
        double* s2 = sum2.data() + static_cast<std::size_t>(j) * d;
        for (int t = 0; t < d; ++t) {
          const double gx = g * x[t];
          s1[t] += gx;
          s2[t] += gx * x[t];
        }
      }
    }
#endif
    for (int j = 0; j < k; ++j) {
      const double denom = std::max(nk[j], 1e-10);
      weights[j] = static_cast<float>(nk[j] / n);
      float* m = means + static_cast<std::size_t>(j) * d;
      float* v = vars + static_cast<std::size_t>(j) * d;
      const double* s1 = sum1.data() + static_cast<std::size_t>(j) * d;
      const double* s2 = sum2.data() + static_cast<std::size_t>(j) * d;
      for (int t = 0; t < d; ++t) {
        const double mean = s1[t] / denom;
        m[t] = static_cast<float>(mean);
        v[t] = std::max(
            static_cast<float>(s2[t] / denom - mean * mean), kMinVar);
      }
    }
  }
  return 0;
}

int ks_fisher_vector(const float* X, int n, int d, const float* weights,
                     const float* means, const float* vars, int k,
                     float* out) {
  if (!X || !weights || !means || !vars || !out || n <= 0 || d <= 0 || k <= 0)
    return -1;
  std::vector<float> r(static_cast<std::size_t>(n) * k);
  e_step(X, n, d, weights, means, vars, k, r.data());
  std::memset(out, 0, static_cast<std::size_t>(2) * k * d * sizeof(float));
  float* gmu = out;            // (k, d)
  float* gvar = out + static_cast<std::size_t>(k) * d;  // (k, d)
  for (int i = 0; i < n; ++i) {
    const float* x = X + static_cast<std::size_t>(i) * d;
    const float* ri = r.data() + static_cast<std::size_t>(i) * k;
    for (int j = 0; j < k; ++j) {
      const float g = ri[j];
      if (g < 1e-10f) continue;
      const float* m = means + static_cast<std::size_t>(j) * d;
      const float* v = vars + static_cast<std::size_t>(j) * d;
      float* gm = gmu + static_cast<std::size_t>(j) * d;
      float* gv = gvar + static_cast<std::size_t>(j) * d;
      for (int t = 0; t < d; ++t) {
        const float u = (x[t] - m[t]) / std::sqrt(v[t]);
        gm[t] += g * u;
        gv[t] += g * (u * u - 1.0f);
      }
    }
  }
  for (int j = 0; j < k; ++j) {
    const float sw = std::sqrt(std::max(weights[j], 1e-12f));
    const float cm = 1.0f / (n * sw);
    const float cv = 1.0f / (n * sw * std::sqrt(2.0f));
    float* gm = gmu + static_cast<std::size_t>(j) * d;
    float* gv = gvar + static_cast<std::size_t>(j) * d;
    for (int t = 0; t < d; ++t) {
      gm[t] *= cm;
      gv[t] *= cv;
    }
  }
  return 0;
}

}  // extern "C"
