// C API of the keystone_tpu native kernel library.
//
// TPU-native rebuild of the reference's non-JVM layer (SURVEY.md §2.3):
// the JNI-wrapped VLFeat dense SIFT and the EncEval GMM/Fisher-Vector
// toolkit become a self-contained C++ library exposed through a plain C
// ABI for ctypes (no pybind11 in this environment). Clean-room
// implementations — no reference code was available or used.
//
// Ref (interface parity targets, [unverified]):
//   utils.external.VLFeat.getSIFTs          -> ks_dense_sift
//   utils.external.EncEval.computeGMM       -> ks_gmm_fit
//   utils.external.EncEval.calcAndGetFVs    -> ks_fisher_vector
//
// All matrices are row-major float32. Every function returns 0 on success,
// negative on argument errors.

#ifndef KEYSTONE_NATIVE_H_
#define KEYSTONE_NATIVE_H_

#include <cstdint>

extern "C" {

// Number of dense-grid keypoints for an (h, w) image with the given step
// and spatial bin size (descriptor support is 4 bins => 4*bin_size px).
int ks_sift_num_keypoints(int h, int w, int step, int bin_size);

// Dense SIFT over a batch of grayscale images.
//   images: (n, h, w) in [0, 1]
//   out:    (n, num_keypoints, 128)
// Descriptors: 4x4 spatial bins x 8 orientation bins, bilinear soft
// binning, Gaussian spatial weighting, L2 -> 0.2 clamp -> re-L2.
int ks_dense_sift(const float* images, int n, int h, int w, int step,
                  int bin_size, float* out);

// Diagonal-covariance GMM fit by EM (k-means++-style seeded).
//   X: (n, d); out: weights (k), means (k, d), vars (k, d)
int ks_gmm_fit(const float* X, int n, int d, int k, int iters,
               std::uint64_t seed, float* weights, float* means, float* vars);

// Fisher-vector encoding of a descriptor set against a fitted GMM.
//   X: (n, d); out: (2*k*d) — mean gradients then variance gradients.
// Raw (un-normalized) FV; signed-sqrt/L2 are pipeline nodes downstream.
int ks_fisher_vector(const float* X, int n, int d, const float* weights,
                     const float* means, const float* vars, int k,
                     float* out);

// Parallel JPEG decode pool: n images -> RGB float32 NHWC at (size, size),
// values scaled to [0, 1]. The ingest-side replacement for a Python-thread
// PIL pool (SURVEY.md §7 hard part 4): libjpeg DCT-scaled decode + bilinear
// resize, OpenMP across images, no GIL anywhere.
//   data:    concatenation of all jpeg byte streams
//   offsets: (n+1) prefix offsets into data (offsets[0] == 0)
//   out:     (n, size, size, 3) float32
// Returns 0, or -(i+1) where i is the first image that failed to decode.
int ks_decode_jpeg_batch(const std::uint8_t* data,
                         const std::uint64_t* offsets, int n, int size,
                         float* out);

// Library ABI version (bump on struct/signature changes).
int ks_abi_version();

}  // extern "C"

#endif  // KEYSTONE_NATIVE_H_
