// Dense SIFT descriptor extraction (clean-room).
//
// Standard dense SIFT: per-pixel gradients -> 8 soft-assigned orientation
// channels -> per-cell weighted sums over a 4x4 grid of spatial bins ->
// 128-dim descriptor with L2 / 0.2-clamp / re-L2 normalization.
// Parity target: utils.external.VLFeat.getSIFTs (SURVEY.md §2.3)
// [unverified].

#include "keystone_native.h"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

constexpr int kOriBins = 8;
constexpr int kSpatialBins = 4;  // 4x4 grid
constexpr int kDescDim = kSpatialBins * kSpatialBins * kOriBins;  // 128

struct Grid {
  int nx, ny, x0, y0, span;
};

// Keypoints are centers of a 4*bin_size-pixel support placed on a dense
// grid with the given step, fully inside the image.
Grid grid_for(int h, int w, int step, int bin_size) {
  Grid g;
  g.span = kSpatialBins * bin_size;  // descriptor support in pixels
  int usable_x = w - g.span;
  int usable_y = h - g.span;
  g.nx = usable_x >= 0 ? usable_x / step + 1 : 0;
  g.ny = usable_y >= 0 ? usable_y / step + 1 : 0;
  g.x0 = 0;
  g.y0 = 0;
  return g;
}

void descriptor_at(const float* gx, const float* gy, int w, int top,
                   int left, int bin_size, float* desc) {
  const int span = kSpatialBins * bin_size;
  const float center = 0.5f * (span - 1);
  const float sigma = 0.5f * span;  // Gaussian spatial window
  const float inv2s2 = 1.0f / (2.0f * sigma * sigma);
  std::memset(desc, 0, kDescDim * sizeof(float));

  for (int yy = 0; yy < span; ++yy) {
    const int iy = top + yy;
    for (int xx = 0; xx < span; ++xx) {
      const int ix = left + xx;
      const float dx = gx[iy * w + ix];
      const float dy = gy[iy * w + ix];
      const float mag = std::sqrt(dx * dx + dy * dy);
      if (mag == 0.0f) continue;
      float theta = std::atan2(dy, dx);  // [-pi, pi]
      if (theta < 0) theta += 2.0f * static_cast<float>(M_PI);
      // Soft orientation binning (linear interp between adjacent bins).
      const float fbin = theta * kOriBins / (2.0f * static_cast<float>(M_PI));
      int b0 = static_cast<int>(fbin) % kOriBins;
      int b1 = (b0 + 1) % kOriBins;
      const float w1 = fbin - std::floor(fbin);
      const float w0 = 1.0f - w1;
      // Soft spatial binning: position in bin units, bilinear over the
      // 4x4 cell grid.
      const float bx = (xx + 0.5f) / bin_size - 0.5f;
      const float by = (yy + 0.5f) / bin_size - 0.5f;
      const int cx0 = static_cast<int>(std::floor(bx));
      const int cy0 = static_cast<int>(std::floor(by));
      const float fx = bx - cx0;
      const float fy = by - cy0;
      // Gaussian weight from the patch center.
      const float rx = xx - center;
      const float ry = yy - center;
      const float gw = std::exp(-(rx * rx + ry * ry) * inv2s2);
      const float wm = mag * gw;

      for (int dyc = 0; dyc <= 1; ++dyc) {
        const int cy = cy0 + dyc;
        if (cy < 0 || cy >= kSpatialBins) continue;
        const float wy = dyc ? fy : 1.0f - fy;
        for (int dxc = 0; dxc <= 1; ++dxc) {
          const int cx = cx0 + dxc;
          if (cx < 0 || cx >= kSpatialBins) continue;
          const float wx = dxc ? fx : 1.0f - fx;
          float* cell = desc + (cy * kSpatialBins + cx) * kOriBins;
          const float wcell = wm * wy * wx;
          cell[b0] += wcell * w0;
          cell[b1] += wcell * w1;
        }
      }
    }
  }

  // L2 normalize -> clamp 0.2 -> renormalize (the standard SIFT step that
  // tames gradient-magnitude bursts).
  float norm = 0.0f;
  for (int i = 0; i < kDescDim; ++i) norm += desc[i] * desc[i];
  norm = std::sqrt(norm);
  if (norm > 1e-12f) {
    const float inv = 1.0f / norm;
    float norm2 = 0.0f;
    for (int i = 0; i < kDescDim; ++i) {
      desc[i] = std::min(desc[i] * inv, 0.2f);
      norm2 += desc[i] * desc[i];
    }
    norm2 = std::sqrt(norm2);
    if (norm2 > 1e-12f) {
      const float inv2 = 1.0f / norm2;
      for (int i = 0; i < kDescDim; ++i) desc[i] *= inv2;
    }
  }
}

}  // namespace

extern "C" {

int ks_abi_version() { return 2; }

int ks_sift_num_keypoints(int h, int w, int step, int bin_size) {
  if (h <= 0 || w <= 0 || step <= 0 || bin_size <= 0) return -1;
  Grid g = grid_for(h, w, step, bin_size);
  return g.nx * g.ny;
}

int ks_dense_sift(const float* images, int n, int h, int w, int step,
                  int bin_size, float* out) {
  if (!images || !out || n <= 0 || h <= 0 || w <= 0 || step <= 0 ||
      bin_size <= 0)
    return -1;
  Grid g = grid_for(h, w, step, bin_size);
  const int nkp = g.nx * g.ny;
  if (nkp == 0) return -2;

#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic)
#endif
  for (int img = 0; img < n; ++img) {
    const float* im = images + static_cast<std::size_t>(img) * h * w;
    std::vector<float> gx(static_cast<std::size_t>(h) * w, 0.0f);
    std::vector<float> gy(static_cast<std::size_t>(h) * w, 0.0f);
    for (int y = 0; y < h; ++y) {
      for (int x = 0; x < w; ++x) {
        const int xm = x > 0 ? x - 1 : x;
        const int xp = x < w - 1 ? x + 1 : x;
        const int ym = y > 0 ? y - 1 : y;
        const int yp = y < h - 1 ? y + 1 : y;
        gx[y * w + x] = 0.5f * (im[y * w + xp] - im[y * w + xm]);
        gy[y * w + x] = 0.5f * (im[yp * w + x] - im[ym * w + x]);
      }
    }
    float* img_out = out + static_cast<std::size_t>(img) * nkp * kDescDim;
    for (int ky = 0; ky < g.ny; ++ky) {
      for (int kx = 0; kx < g.nx; ++kx) {
        descriptor_at(gx.data(), gy.data(), w, g.y0 + ky * step,
                      g.x0 + kx * step, bin_size,
                      img_out + (ky * g.nx + kx) * kDescDim);
      }
    }
  }
  return 0;
}

}  // extern "C"
