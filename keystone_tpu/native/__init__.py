"""ctypes bindings for the native kernel library.

Replaces the reference's JNI wrappers `utils.external.{VLFeat, EncEval}`
(SURVEY.md §2.3) [unverified]. The library is built on demand from the
in-tree C++ (`make` in this directory); when the toolchain is unavailable
the callers gate on `available()` — mirroring the reference's
"skip if the native lib is missing" test pattern (SURVEY.md §4).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libkeystone_native.so")
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None
_has_jpeg: bool = False


def _f32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.float32)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    if _lib is not None:
        return _lib
    # Always invoke make: it no-ops when up to date and rebuilds after source
    # edits; binaries are gitignored so a foreign-machine .so never ships.
    try:
        subprocess.run(
            ["make", "-s"],
            cwd=_DIR,
            check=True,
            capture_output=True,
            text=True,
        )
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        _build_error = getattr(e, "stderr", str(e)) or str(e)
        if not os.path.exists(_LIB_PATH):
            return None
    # Binding/ABI failures (stale .so from an older build + a failed make,
    # missing optional symbols) must degrade to unavailable(), never raise —
    # the auto ingest backend depends on a clean False to fall back to PIL.
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.ks_abi_version.restype = ctypes.c_int
        if lib.ks_abi_version() != 2:
            _build_error = "native ABI mismatch — run make clean"
            return None
        lib.ks_sift_num_keypoints.restype = ctypes.c_int
        lib.ks_sift_num_keypoints.argtypes = [ctypes.c_int] * 4
        lib.ks_dense_sift.restype = ctypes.c_int
        lib.ks_dense_sift.argtypes = [f32p] + [ctypes.c_int] * 5 + [f32p]
        lib.ks_gmm_fit.restype = ctypes.c_int
        lib.ks_gmm_fit.argtypes = (
            [f32p] + [ctypes.c_int] * 4 + [ctypes.c_uint64, f32p, f32p, f32p]
        )
        lib.ks_fisher_vector.restype = ctypes.c_int
        lib.ks_fisher_vector.argtypes = (
            [f32p, ctypes.c_int, ctypes.c_int, f32p, f32p, f32p, ctypes.c_int, f32p]
        )
        # Optional: compiled out when the host lacks libjpeg (Makefile gate).
        global _has_jpeg
        _has_jpeg = hasattr(lib, "ks_decode_jpeg_batch")
        if _has_jpeg:
            lib.ks_decode_jpeg_batch.restype = ctypes.c_int
            lib.ks_decode_jpeg_batch.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_int,
                ctypes.c_int,
                f32p,
            ]
    except Exception as e:  # lint: broad-ok ctypes probe: any load/signature failure means 'no native backend'
        _build_error = f"native binding failed: {e}"
        return None
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def jpeg_available() -> bool:
    """True when the library was built against libjpeg."""
    return _load() is not None and _has_jpeg


def build_error() -> Optional[str]:
    _load()
    return _build_error


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def sift_num_keypoints(h: int, w: int, step: int, bin_size: int) -> int:
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    n = lib.ks_sift_num_keypoints(h, w, step, bin_size)
    if n < 0:
        raise ValueError("bad SIFT grid parameters")
    return n


def dense_sift(
    images: np.ndarray, step: int = 4, bin_size: int = 4
) -> np.ndarray:
    """(n, h, w) grayscale in [0,1] → (n, num_keypoints, 128) float32."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    images = _f32(images)
    n, h, w = images.shape
    nkp = sift_num_keypoints(h, w, step, bin_size)
    out = np.empty((n, nkp, 128), dtype=np.float32)
    rc = lib.ks_dense_sift(_ptr(images), n, h, w, step, bin_size, _ptr(out))
    if rc != 0:
        raise RuntimeError(f"ks_dense_sift failed ({rc})")
    return out


def gmm_fit(
    X: np.ndarray, k: int, iters: int = 25, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(n, d) → (weights (k,), means (k, d), vars (k, d))."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    X = _f32(X)
    n, d = X.shape
    weights = np.empty(k, dtype=np.float32)
    means = np.empty((k, d), dtype=np.float32)
    variances = np.empty((k, d), dtype=np.float32)
    rc = lib.ks_gmm_fit(
        _ptr(X), n, d, k, iters, seed, _ptr(weights), _ptr(means), _ptr(variances)
    )
    if rc != 0:
        raise RuntimeError(f"ks_gmm_fit failed ({rc})")
    return weights, means, variances


def decode_jpeg_batch(bufs, size: int) -> np.ndarray:
    """list of jpeg byte strings → (n, size, size, 3) float32 NHWC in [0,1].

    libjpeg DCT-scaled decode + bilinear resize, OpenMP across images —
    the native replacement for the PIL thread pool on the ingest path.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    if not _has_jpeg:
        raise RuntimeError("native library was built without libjpeg")
    n = len(bufs)
    if n == 0:
        return np.empty((0, size, size, 3), dtype=np.float32)
    offsets = np.zeros(n + 1, dtype=np.uint64)
    for i, b in enumerate(bufs):
        offsets[i + 1] = offsets[i] + len(b)
    data = np.frombuffer(b"".join(bufs), dtype=np.uint8)
    out = np.empty((n, size, size, 3), dtype=np.float32)
    rc = lib.ks_decode_jpeg_batch(
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n,
        size,
        _ptr(out),
    )
    if rc != 0:
        raise ValueError(f"jpeg decode failed at image {-rc - 1}")
    return out


def fisher_vector(
    X: np.ndarray,
    weights: np.ndarray,
    means: np.ndarray,
    variances: np.ndarray,
) -> np.ndarray:
    """Descriptor set (n, d) against a GMM (k) → raw FV (2·k·d,)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    X = _f32(X)
    weights = _f32(weights)
    means = _f32(means)
    variances = _f32(variances)
    n, d = X.shape
    k = weights.shape[0]
    out = np.empty(2 * k * d, dtype=np.float32)
    rc = lib.ks_fisher_vector(
        _ptr(X), n, d, _ptr(weights), _ptr(means), _ptr(variances), k, _ptr(out)
    )
    if rc != 0:
        raise RuntimeError(f"ks_fisher_vector failed ({rc})")
    return out
