"""Global configuration for keystone_tpu.

The reference computes in float64 via Breeze/netlib BLAS. On TPU, float64 is
emulated and slow; the MXU wants float32 (with bfloat16 inputs where quality
permits). We default to float32 end-to-end and expose a switch for tests that
compare against float64 NumPy oracles on CPU.

Ref: build.sbt (Breeze/netlib deps) [unverified].
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


def env_flag(name: str) -> bool:
    """True unless the var is unset or a falsy spelling ('', '0', 'false',
    'no') — the one env-knob convention used across the framework."""
    return os.environ.get(name, "").lower() not in ("", "0", "false", "no")


def _env_int(name: str, default: int) -> int:
    """Validated integer env knob: a bad value fails AT IMPORT naming the
    variable — the same diagnostic contract as _env_choice."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected an integer") from None


def _env_float(name: str, default: float) -> float:
    """Validated float env knob: a bad value fails AT IMPORT naming the
    variable — the same diagnostic contract as _env_int."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected a number") from None


def pow2_ladder(max_batch: int) -> tuple:
    """Power-of-two bucket ladder up to (and always including) max_batch —
    the default shape set the serving layer pads batches onto."""
    if max_batch <= 0:
        raise ValueError(f"max_batch must be positive, got {max_batch}")
    ladder = []
    b = 1
    while b < max_batch:
        ladder.append(b)
        b *= 2
    ladder.append(max_batch)
    return tuple(ladder)


def _env_buckets() -> tuple:
    """Parse KEYSTONE_SERVE_BUCKETS: empty/unset = () (today's per-shape
    jit), 'pow2' = power-of-two ladder up to serve_max_batch, else a
    comma-separated ascending bucket list. Bad values fail AT IMPORT naming
    the variable (same contract as _env_choice)."""
    raw = os.environ.get("KEYSTONE_SERVE_BUCKETS")
    if raw is None or not raw.strip():
        return ()
    if raw.strip().lower() == "pow2":
        return pow2_ladder(_env_int("KEYSTONE_SERVE_MAX_BATCH", 1024))
    try:
        vals = tuple(
            sorted({int(tok) for tok in raw.split(",") if tok.strip()})
        )
    except ValueError:
        raise ValueError(
            f"KEYSTONE_SERVE_BUCKETS={raw!r}: expected 'pow2' or "
            "comma-separated integers"
        ) from None
    if not vals or vals[0] <= 0:
        raise ValueError(
            f"KEYSTONE_SERVE_BUCKETS={raw!r}: buckets must be positive"
        )
    return vals


def _env_choice(name: str, choices: tuple, default: str) -> str:
    """Validated enum env knob: case-insensitive, and a bad value fails AT
    IMPORT naming the variable — not as a bare KeyError deep in a solve."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    val = raw.strip().lower()
    if val not in choices:
        raise ValueError(f"{name}={raw!r}: expected one of {choices}")
    return val


@dataclass
class Config:
    # Default dtype for dense compute (solvers, featurization).
    default_dtype: str = "float32"
    # dtype used for matmul accumulation-sensitive reductions (grams). XLA on
    # TPU accumulates fp32; this is the storage dtype of gram matrices.
    accum_dtype: str = "float32"
    # Matmul precision for solver-path compute (grams, QR, residuals). TPU
    # default matmul precision is bf16-class and loses ~3 decimal digits;
    # solvers default to full fp32 ("highest" = 6-pass bf16 emulation,
    # ~1/6 MXU peak). "high" (3-pass) doubles gemm throughput at ~f32-ish
    # accuracy — BCD's per-epoch residual re-solve self-corrects, so the
    # bench measures it as the f32h mode; flip the default only on
    # silicon evidence. Env: KEYSTONE_SOLVER_PRECISION.
    solver_precision: str = field(
        default_factory=lambda: _env_choice(
            "KEYSTONE_SOLVER_PRECISION", ("highest", "high", "default"),
            "highest",
        )
    )
    # Storage dtype for the solver's BIG operands (the feature matrix A and
    # streamed blocks). None = default_dtype. "bfloat16" is the v5e
    # throughput mode: A is stored (and streamed) at half the bytes and
    # every matmul touching it takes the MXU's native bf16-multiply /
    # f32-accumulate path; grams, Cholesky factors, weights, and residuals
    # stay in accum_dtype. Set via KEYSTONE_SOLVER_DTYPE or per-run config.
    solver_storage_dtype: str | None = field(
        default_factory=lambda: os.environ.get("KEYSTONE_SOLVER_DTYPE") or None
    )
    # Canonical block count for the width-independent solver row fold
    # (utils.mesh.fold_blocks). Row reductions (grams, AᵀB, column sums)
    # are summed over this many fixed row blocks in a balanced-tree order
    # regardless of mesh width, so a solve accumulated on W devices is
    # BIT-identical to the same solve on W' devices — the property the
    # elastic mesh migration's resume gate relies on. Must be a power of
    # two; meshes whose width does not divide it fall back to the plain
    # psum fold (order differs per width). Rows pad to a multiple of this
    # count instead of the mesh width. 0 pins the legacy psum fold
    # everywhere. Env: KEYSTONE_GRAM_FOLD_BLOCKS.
    gram_fold_blocks: int = field(
        default_factory=lambda: int(
            os.environ.get("KEYSTONE_GRAM_FOLD_BLOCKS", "16")
        )
    )
    # Mesh axis name used for data (row) parallelism throughout.
    data_axis: str = "data"
    # Mesh axis name used for model (feature-block) parallelism.
    model_axis: str = "model"
    # HBM budget (bytes) assumed by the auto-caching rule when no device is
    # queried. v5e = 16 GiB; leave headroom for XLA scratch.
    hbm_budget_bytes: int = 12 * (1 << 30)
    # Row-shard array batches over the mesh when they enter the graph (the
    # RDD-partitioning analog): divisible batches are placed with the
    # explicit data sharding, and fused jittable chains lower ONCE with
    # the SpecLayout convention's in_shardings/out_shardings
    # (utils/mesh.py) — not just the solvers. Batches whose row count
    # doesn't divide the mesh are mask-padded onto it by the chain call
    # and trimmed (bit-identical, counted in the "sharding" registry);
    # only sub-shard_min_rows batches fall back to single-device, and
    # that fallback is counted too. KEYSTONE_SHARD_DATA=0 pins the
    # single-device walk (the bench's A/B control and the escape hatch).
    shard_data_batches: bool = field(
        default_factory=lambda: os.environ.get(
            "KEYSTONE_SHARD_DATA", ""
        ).lower() not in ("0", "false", "no")
    )
    # Minimum rows before sharding is worth the placement overhead — the
    # ONLY batch class still allowed to run single-device (visible via
    # sharding.fallback_small_batch). Env: KEYSTONE_SHARD_MIN_ROWS.
    shard_min_rows: int = field(
        default_factory=lambda: _env_int("KEYSTONE_SHARD_MIN_ROWS", 64)
    )
    # Buffer donation across the fused-fit plumbing: the sharded chain
    # call donates the staging copy it creates for a host batch
    # (utils/mesh.py SpecLayout.jit) when an output can alias it, and the
    # solver hot loops donate their dead accumulator/residual buffers
    # (linalg/row_matrix.py donate_argnums) — each update then holds ONE
    # live copy instead of two, capping the fit's HBM high-water.
    # Donation never touches caller-owned arrays (anything placed
    # upstream can be multi-consumer via gather/memo), and is refused —
    # counted, never silent — when no output matches the buffer's
    # shape/dtype (XLA aliasing is aval-matched, so donating there would
    # be a warning and a no-op). KEYSTONE_DONATE_BUFFERS=0 pins donation
    # off everywhere: the bench's non-donated A/B control and the
    # debugging escape hatch when a deleted-buffer error needs isolating.
    donate_buffers: bool = field(
        default_factory=lambda: os.environ.get(
            "KEYSTONE_DONATE_BUFFERS", ""
        ).lower() not in ("0", "false", "no")
    )
    # Elastic mesh: durable solver/profile state recorded under one mesh
    # width migrates onto the current width at resume time
    # (utils/mesh.reshard_state — the accumulators are placement-free
    # sums, so a migrated resume is bit-identical to an uninterrupted fit
    # at the target width) instead of refusing with MeshMismatchError.
    # Every migration is counted in the "elastic" metrics family — never
    # silent — and truly non-migratable state (torn/partial per-shard
    # payloads) still refuses typed. KEYSTONE_ELASTIC_MESH=0 pins the
    # refuse-only contract everywhere (the pre-elastic behavior and the
    # escape hatch when a migration needs isolating).
    elastic_mesh: bool = field(
        default_factory=lambda: os.environ.get(
            "KEYSTONE_ELASTIC_MESH", ""
        ).lower() not in ("0", "false", "no")
    )
    # Feature blocks whose gram ridge inverses are factorized together in
    # ONE batched XLA program (batched Cholesky + triangular solves over a
    # leading block axis). TPU lowers a single b×b factorization to a
    # sequential panel loop; batching amortizes that loop across blocks —
    # the dominant cost of many-block solves (d ≫ block). Transient memory
    # per batched call: factor_batch · b² · 4B on top of the inverse cache.
    # None = auto: 16 on accelerators; per-block (fused gram+factor) on CPU,
    # where batched decompositions measured 2.3× SLOWER than independent
    # per-block programs. An explicit int forces that chunk on any backend.
    factor_batch: int | None = None
    # Scan-fused BCD epochs: when feature blocks tile d exactly, the solver
    # runs the whole factor phase + epoch loop as three XLA programs (stack,
    # batched factor, scanned epochs) instead of one dispatch per (block,
    # epoch). Per-program launch latency through the TPU relay rivals the
    # skinny per-epoch gemms it wraps, so dispatch count is a first-order
    # solver cost. None/True = on; False = force the legacy per-block loop.
    fused_epochs: bool | None = None
    # Depth of the bounded host-side prefetch queue in front of the chunked
    # solvers and streamed pipeline application (loaders/stream.py
    # PrefetchIterator): the upstream producer — CSV parse, JPEG decode,
    # map_batches featurization — runs on a background thread up to this
    # many batches ahead, so host ingest leaves the device's critical path
    # while peak host residency stays bounded by depth × batch bytes.
    # 0 restores fully synchronous single-thread ingestion. This is the
    # hand-picked ceiling: on a measured-profile hit PlanResourcesRule
    # CLAMPS the effective depth down when depth × measured per-batch
    # bytes would overrun its budget share (the session plan; an
    # exported KEYSTONE_PREFETCH_DEPTH — including 0 — always wins, see
    # resolved_prefetch_depth). Env: KEYSTONE_PREFETCH_DEPTH.
    prefetch_depth: int = field(
        default_factory=lambda: _env_int("KEYSTONE_PREFETCH_DEPTH", 2)
    )
    # Serving bucket ladder: when non-empty, Transformer.batch_call rounds
    # array batches up to the next bucket (padding with the last real row)
    # so the per-shape jit cache only ever sees ladder shapes — a serving
    # workload with variable request sizes stops recompiling once the
    # ladder is warm. Empty = today's per-shape jit. The AOT serving engine
    # (workflow/serving.py CompiledPipeline) uses this ladder too, falling
    # back to pow-2 up to serve_max_batch when empty. Padding is refused
    # (RowDependenceError) for transformers with row_independent=False.
    # Env: KEYSTONE_SERVE_BUCKETS ('pow2' or comma-separated ints).
    serve_buckets: tuple = field(default_factory=_env_buckets)
    # Top of the default serving ladder: the largest batch a single bucketed
    # device call serves (bigger requests chunk through this bucket).
    # Env: KEYSTONE_SERVE_MAX_BATCH.
    serve_max_batch: int = field(
        default_factory=lambda: _env_int("KEYSTONE_SERVE_MAX_BATCH", 1024)
    )
    # Serving precision ladder (workflow/serving.py CompiledPipeline):
    # the storage/accumulate mode every serve bucket AOT-warms at.
    # "f32" (default) is byte-for-byte today's path — the engine's jit
    # wrapper is constructed exactly as before, so outputs stay
    # bit-identical when the knob is off. "f32h" traces the chain under
    # matmul precision HIGH (3-pass bf16 emulation — ~2x MXU throughput
    # at ~f32-ish accuracy; a no-op on CPU). "bf16" is the MXU-native
    # throughput mode: the request batch is cast to bfloat16 at the
    # chain boundary (bf16 storage) and every matmul traces at DEFAULT
    # precision (one bf16 pass, f32 accumulation — the
    # tests/test_bf16_mode.py storage/accumulate contract); fitted
    # weights stay f32 and any bf16 leaf is cast back to the request
    # dtype at the boundary. Non-f32 modes should be gated per pipeline
    # with CompiledPipeline.qualify() — evaluation/ metrics within a
    # declared tolerance of the f32 oracle, or the knob refuses with a
    # typed PrecisionQualityError. Env: KEYSTONE_SERVE_PRECISION.
    serve_precision: str = field(
        default_factory=lambda: _env_choice(
            "KEYSTONE_SERVE_PRECISION", ("f32", "f32h", "bf16"), "f32"
        )
    )
    # Serving replica pool width: how many local devices CompiledPipeline
    # AOT-warms its bucket ladder onto (one replica per device, each owning
    # its own compiled executables). 0 = all local devices — the training
    # side already spans the whole mesh; serving should too. 1 pins the
    # pre-replica single-device behavior exactly.
    # Env: KEYSTONE_SERVE_DEVICES.
    serve_devices: int = field(
        default_factory=lambda: _env_int("KEYSTONE_SERVE_DEVICES", 0)
    )
    # Per-replica in-flight window for pipelined serving dispatch: the
    # micro-batcher launches up to this many flush groups per replica
    # before waiting on a completion, riding JAX async dispatch so replica
    # B computes while replica A's results materialize. 1 serializes
    # launch->materialize per replica (with one replica, exactly the
    # pre-pipelining flush loop). Env: KEYSTONE_SERVE_INFLIGHT.
    serve_inflight: int = field(
        default_factory=lambda: _env_int("KEYSTONE_SERVE_INFLIGHT", 2)
    )
    # Host worker threads for the executor's stage-parallel DAG walk
    # (workflow/executor.py): when > 0, nodes whose inputs are resolved
    # dispatch concurrently onto a bounded pool — independent branches
    # (the two-branch ImageNet featurizer, parallel text encoders) run
    # side by side, and a host-bound node (native SIFT, JPEG decode,
    # tokenize) no longer blocks device work on a sibling branch.
    # Jittable device nodes keep riding JAX async dispatch (launch
    # without materializing); only estimator fits and host consumers
    # block. 0 (default) = the byte-identical legacy serial topological
    # walk — nothing changes until opted in. Outputs are bit-identical
    # at any worker count: the scheduler reorders only provably
    # independent nodes. Env: KEYSTONE_EXEC_WORKERS.
    exec_workers: int = field(
        default_factory=lambda: _env_int("KEYSTONE_EXEC_WORKERS", 0)
    )
    # Whole-pipeline auto-caching (profile a sample run, persist the best
    # time-saved-per-byte intermediates under a budget). Opt-in: profiling
    # costs a sample execution per optimization — unless a MEASURED
    # profile for the pipeline exists in the profile store, in which case
    # the rule consumes that and skips the sample run entirely.
    auto_cache: bool = False
    # Directory of the measured-profile store (workflow/profile_store.py):
    # `Pipeline.fit(profile=True)` persists per-node wall/bytes rows keyed
    # by the pipeline's structural digest + runtime fingerprint; the
    # optimizer rules consume matching entries instead of sample-run
    # extrapolation. None = disabled; the KEYSTONE_PROFILE_STORE env var
    # takes precedence (presence, not truthiness — an exported empty var
    # disables, the resolved_cache_dir convention).
    profile_store: str | None = None
    # Profile-guided resource planning (workflow/rules.py
    # PlanResourcesRule): on a measured-profile hit, pick the executor
    # worker count from the graph's branch width + measured queue-wait
    # attribution and plan solver chunk rows against the HBM budget
    # (PR-3's reactive OOM-halving becomes a planned size). The plan is
    # scoped to the optimized pipeline's own walk
    # (PipelineEnv.resource_plan, saved/restored around nested passes)
    # and never overrides an explicitly EXPORTED KEYSTONE_EXEC_WORKERS /
    # KEYSTONE_SOLVE_CHUNK_ROWS (presence wins, including an explicit
    # 0). A programmatic pin (config.exec_workers = 0 in code, no env)
    # cannot be told apart from the unset default — to pin
    # programmatically, disable the planner: config.plan_resources =
    # False. Env: KEYSTONE_PLAN_RESOURCES=0 disables.
    plan_resources: bool = field(
        default_factory=lambda: os.environ.get(
            "KEYSTONE_PLAN_RESOURCES", ""
        ).lower() not in ("0", "false", "no")
    )
    # Planned row count per solver chunk H2D transfer: chunks larger than
    # this are split BEFORE the transfer (linalg/normal_equations.py), so
    # a chunk that could not fit HBM never triggers the reactive
    # OOM-halving path. 0 = unplanned (reactive halving only, or the
    # session plan from PlanResourcesRule). Env: KEYSTONE_SOLVE_CHUNK_ROWS.
    solve_chunk_rows: int = field(
        default_factory=lambda: _env_int("KEYSTONE_SOLVE_CHUNK_ROWS", 0)
    )
    # Raise on NaNs inside jitted computations (jax debug_nans; the
    # sanitizer analog — SURVEY.md §5 race-detection row).
    debug_nans: bool = False
    # Arrays above this size are fingerprinted from a deterministic chunk
    # sample instead of a full scan: multi-GB fit inputs stay
    # content-addressed (the cross-process cache keeps working at real
    # scale) without paying full-buffer hashing per streamed batch.
    fingerprint_max_bytes: int = 128 << 20
    # Vocabulary size at which text vectorizers switch from dense (batch, K)
    # output to a host-side CSR SparseBatch (consumers densify per column
    # block). Below this, dense batches feed the MXU classifiers directly.
    text_sparse_threshold: int = 16384
    # Directory for the cross-process fitted-prefix store (None = disabled;
    # the KEYSTONE_CACHE_DIR env var takes precedence). Content-addressed, so
    # it never serves stale fits — see workflow/disk_cache.py.
    cache_dir: str | None = None
    # Fault-injection plan (utils/reliability.py FaultPlan): a
    # 'site:value,...' spec, e.g. 'io:0.05,oom:1,producer_death:1'. Integer
    # values fire on the first N checks of the site; fractions are per-check
    # probabilities drawn from a stream seeded by faults_seed, so a fixed
    # seed reproduces the exact fault sequence. Empty = injection disabled,
    # zero overhead. Env: KEYSTONE_FAULTS / KEYSTONE_FAULTS_SEED.
    faults: str = field(
        default_factory=lambda: os.environ.get("KEYSTONE_FAULTS", "")
    )
    faults_seed: int = field(
        default_factory=lambda: _env_int("KEYSTONE_FAULTS_SEED", 0)
    )
    # Transient-failure retry budget (utils/reliability.py RetryPolicy):
    # total attempts per operation, and the exponential-backoff base/cap in
    # milliseconds (full jitter: each pause is uniform over [0, cap]).
    # Used by the prefetch producer (flaky record reads) and the chunked
    # solvers (device RESOURCE_EXHAUSTED at the H2D step).
    # Env: KEYSTONE_RETRY_ATTEMPTS / KEYSTONE_RETRY_BASE_MS /
    # KEYSTONE_RETRY_MAX_MS.
    retry_attempts: int = field(
        default_factory=lambda: _env_int("KEYSTONE_RETRY_ATTEMPTS", 4)
    )
    retry_base_ms: float = field(
        default_factory=lambda: _env_float("KEYSTONE_RETRY_BASE_MS", 5.0)
    )
    retry_max_ms: float = field(
        default_factory=lambda: _env_float("KEYSTONE_RETRY_MAX_MS", 1000.0)
    )
    # Checkpoint cadence for the streaming solvers: snapshot accumulator
    # state (gram/AᵀB, resp. W/R blocks) every K chunks/blocks into the
    # solve's checkpoint_dir, so a killed fit recomputes at most K chunks on
    # resume. 0 disables mid-stream snapshots in BOTH solvers (resume from
    # an existing snapshot still works; the streamed BCD epoch-boundary
    # orbax saves are independent and keep happening).
    # Env: KEYSTONE_CHECKPOINT_EVERY.
    checkpoint_every: int = field(
        default_factory=lambda: _env_int("KEYSTONE_CHECKPOINT_EVERY", 8)
    )
    # Serving backpressure: the most requests PipelineService holds pending
    # before submit() fast-fails with QueueFullError — bounded queues turn
    # overload into fast rejections instead of unbounded latency cliffs.
    # Env: KEYSTONE_SERVE_MAX_PENDING.
    serve_max_pending: int = field(
        default_factory=lambda: _env_int("KEYSTONE_SERVE_MAX_PENDING", 1024)
    )
    # Default per-request deadline for PipelineService submits, in
    # milliseconds: a request still queued past its deadline fails its
    # future with DeadlineExceeded BEFORE wasting a device call. 0 = no
    # deadline. Env: KEYSTONE_SERVE_DEADLINE_MS.
    serve_deadline_ms: float = field(
        default_factory=lambda: _env_float("KEYSTONE_SERVE_DEADLINE_MS", 0.0)
    )
    # Whether executor fuses jittable transformer chains into one XLA program.
    # Disabled by KEYSTONE_NO_FUSE set to a truthy value (anything except
    # "", "0", "false", "no").
    fuse_chains: bool = field(
        default_factory=lambda: not env_flag("KEYSTONE_NO_FUSE")
    )
    # Process-wide span tracing (utils/metrics.py Tracer): executor nodes,
    # solver chunks, prefetch queue residency, and serving request
    # lifecycle record into a bounded ring buffer, exportable as
    # Chrome-trace JSON (Perfetto-viewable; tools/trace_report.py). Off by
    # default: call sites resolve ``active_tracer()`` ONCE per
    # stream/solve/service — like ``active_plan()`` — so the disabled
    # tracer is a None check, never a per-record cost. Env: KEYSTONE_TRACE.
    trace: bool = field(default_factory=lambda: env_flag("KEYSTONE_TRACE"))
    # Span ring-buffer capacity: the tracer keeps the most recent N spans,
    # so a long-running traced process holds bounded memory instead of an
    # unbounded event log. Env: KEYSTONE_TRACE_BUFFER.
    trace_buffer: int = field(
        default_factory=lambda: _env_int("KEYSTONE_TRACE_BUFFER", 65536)
    )
    # Tail-sampling threshold for request-scoped tracing, in milliseconds:
    # when tracing is on, a request whose end-to-end latency breaches this
    # keeps its FULL span tree in the tracer's retained store (survives
    # ring churn; exported under "tailSampled"). 0 = auto: the running p99
    # of the service's always-on e2e histogram (so ~the slowest 1% are
    # retained once enough samples exist); negative disables tail
    # sampling entirely. Env: KEYSTONE_TRACE_TAIL_MS.
    trace_tail_ms: float = field(
        default_factory=lambda: _env_float("KEYSTONE_TRACE_TAIL_MS", 0.0)
    )
    # Serving stall watchdog (workflow/serving.py): a background thread
    # per service that fires when the pending queue is non-empty but no
    # dispatch progress (group pop / completion) has happened for this
    # many milliseconds — bumping the serve.stalls counter and dumping the
    # flight recorder instead of hanging silently. 0 disables the thread.
    # Env: KEYSTONE_WATCHDOG_MS.
    serve_watchdog_ms: float = field(
        default_factory=lambda: _env_float("KEYSTONE_WATCHDOG_MS", 10000.0)
    )
    # Deadline-storm dump trigger: this many DeadlineExceeded failures
    # inside one second auto-dumps the flight recorder (the post-mortem
    # for "why did everything suddenly expire"). 0 disables the trigger.
    # Env: KEYSTONE_STORM_EXPIRED.
    serve_storm_expired: int = field(
        default_factory=lambda: _env_int("KEYSTONE_STORM_EXPIRED", 8)
    )
    # Flight-recorder ring capacity: the most recent N per-request journey
    # records each PipelineService keeps for post-mortem dumps (always on;
    # one record per accepted request). 0 disables the journey ring
    # (error events and dump triggers keep working).
    # Env: KEYSTONE_FLIGHT_RECORDS.
    flight_records: int = field(
        default_factory=lambda: _env_int("KEYSTONE_FLIGHT_RECORDS", 2048)
    )
    # Where flight-recorder dumps land ('' = the platform tempdir). Each
    # dump is one JSON file named for the service, trigger reason, pid,
    # and sequence number. Env: KEYSTONE_FLIGHT_DIR.
    flight_dir: str = field(
        default_factory=lambda: os.environ.get("KEYSTONE_FLIGHT_DIR", "")
    )
    # Durable telemetry export (utils/telemetry.py TelemetryLog): where
    # resolved request journeys + tail-retained span trees append as
    # JSONL, written by a dedicated writer thread off the serving hot
    # path. '' (default) = telemetry export off — the daemon keeps only
    # its in-memory rings. Env: KEYSTONE_TELEMETRY_DIR.
    telemetry_dir: str = field(
        default_factory=lambda: os.environ.get("KEYSTONE_TELEMETRY_DIR", "")
    )
    # Telemetry segment rotation threshold (MB): when the active JSONL
    # segment grows past this, the writer rotates to a new sequence-
    # numbered segment file. Env: KEYSTONE_TELEMETRY_ROTATE_MB.
    telemetry_rotate_mb: float = field(
        default_factory=lambda: _env_float("KEYSTONE_TELEMETRY_ROTATE_MB",
                                           64.0)
    )
    # Bounded telemetry retention: keep the newest N rotated segments per
    # process, delete the rest (the keep_artifacts precedent — a steady
    # flood must not fill the volume). Env: KEYSTONE_TELEMETRY_KEEP.
    telemetry_keep: int = field(
        default_factory=lambda: _env_int("KEYSTONE_TELEMETRY_KEEP", 8)
    )
    # Telemetry writer-queue capacity: journeys enqueue to the writer
    # thread through a bounded queue; a full queue DROPS the record and
    # counts it (telemetry family, records_dropped) — export never
    # blocks admission. Env: KEYSTONE_TELEMETRY_QUEUE.
    telemetry_queue: int = field(
        default_factory=lambda: _env_int("KEYSTONE_TELEMETRY_QUEUE", 4096)
    )
    # Per-tenant SLO accounting (workflow/daemon.py): rolling-window
    # length in seconds over which deadline-hit rate and error-budget
    # burn are computed for /stats + /metrics. Env: KEYSTONE_SLO_WINDOW_S.
    slo_window_s: float = field(
        default_factory=lambda: _env_float("KEYSTONE_SLO_WINDOW_S", 300.0)
    )
    # SLO objective: the target fraction of in-deadline, non-error
    # responses per tenant/tier. Error-budget burn is the ratio of the
    # observed failure rate to the budget this objective leaves
    # (burn > 1.0 = burning budget faster than sustainable).
    # Env: KEYSTONE_SLO_TARGET.
    slo_target: float = field(
        default_factory=lambda: _env_float("KEYSTONE_SLO_TARGET", 0.99)
    )
    # TCP port for tools/metrics_server.py (the /metrics + /healthz pull
    # surface). 0 = bind an ephemeral port (the smoke-test default; the
    # chosen port is printed/returned). Env: KEYSTONE_METRICS_PORT.
    metrics_port: int = field(
        default_factory=lambda: _env_int("KEYSTONE_METRICS_PORT", 0)
    )
    # Per-node resource attribution (utils/metrics.py ResourceProfile):
    # when on, every executor walk records wall time, device wait,
    # cost-model FLOPs/bytes (one AOT lower+compile per executable,
    # memoized), output nbytes, and the HBM high-water delta per pipeline
    # node into the process-wide profile (registry name "profile",
    # exported over /metrics). Off by default: call sites resolve
    # ``active_profile()`` ONCE per execution walk — the
    # ``active_plan()`` discipline — so the disabled profiler is a None
    # check. ``Pipeline.fit(profile=True)`` forces it for one fit.
    # Env: KEYSTONE_PROFILE.
    profile: bool = field(default_factory=lambda: env_flag("KEYSTONE_PROFILE"))
    # Streaming-solve stall watchdog (utils/flight_recorder.py
    # ProgressReporter): each streaming solve gets a watchdog thread that
    # fires when no chunk/block completes for this many milliseconds —
    # bumping the solver stall counters and dumping the solver flight
    # recorder, so a dead producer mid-fit leaves forensics exactly like
    # a dead serving worker. 0 disables the per-solve thread.
    # Env: KEYSTONE_SOLVE_WATCHDOG_MS.
    solve_watchdog_ms: float = field(
        default_factory=lambda: _env_float("KEYSTONE_SOLVE_WATCHDOG_MS",
                                           30000.0)
    )
    # Progress-event cadence for streaming solves: every K completed
    # chunks/blocks appends one structured event (unit, rows/s, ETA,
    # residual when cheap) to the solve's journey record. 1 = every
    # unit; higher thins the bounded event ring for hour-scale solves.
    # Env: KEYSTONE_SOLVE_PROGRESS_EVERY.
    solve_progress_every: int = field(
        default_factory=lambda: _env_int("KEYSTONE_SOLVE_PROGRESS_EVERY", 1)
    )
    # Network serving daemon (workflow/daemon.py) — bind address for
    # BOTH ingresses. Default loopback (safe: nothing is exposed until
    # the operator says so); set 0.0.0.0 to serve real external traffic
    # behind a load balancer. Env: KEYSTONE_SERVE_HOST.
    serve_host: str = field(
        default_factory=lambda: os.environ.get("KEYSTONE_SERVE_HOST",
                                               "127.0.0.1")
    )
    # HTTP/JSON ingress port. 0 = bind an ephemeral port (tests/smokes;
    # the chosen port is reported on the daemon object).
    # Env: KEYSTONE_SERVE_PORT.
    serve_port: int = field(
        default_factory=lambda: _env_int("KEYSTONE_SERVE_PORT", 0)
    )
    # Length-prefixed socket ingress port for the daemon (the low-overhead
    # wire: 4-byte big-endian frame length + JSON payload, persistent
    # connections). 0 = ephemeral. Env: KEYSTONE_SERVE_SOCKET_PORT.
    serve_socket_port: int = field(
        default_factory=lambda: _env_int("KEYSTONE_SERVE_SOCKET_PORT", 0)
    )
    # Tenant/quota/SLA table for daemon admission control:
    # 'name:api_key:qps:tier,...' entries — qps is the token-bucket refill
    # rate (0 = unlimited), tier is 'gold' or 'best_effort'. Empty = open
    # mode (no API keys; every request is an anonymous best-effort
    # tenant). Env: KEYSTONE_TENANTS.
    tenants: str = field(
        default_factory=lambda: os.environ.get("KEYSTONE_TENANTS", "")
    )
    # Global admission budget: the most requests the daemon holds admitted
    # (accepted but not yet responded) across every tenant before
    # fast-failing with 429. Best-effort tenants are refused earlier (at
    # BE_BUDGET_FRAC of this) so gold always has reserved headroom — the
    # queue-priority half of the SLA tiers.
    # Env: KEYSTONE_SERVE_PENDING_BUDGET.
    serve_pending_budget: int = field(
        default_factory=lambda: _env_int("KEYSTONE_SERVE_PENDING_BUDGET", 256)
    )
    # Per-tier default deadlines (ms) the daemon stamps on each admitted
    # request: gold = the latency SLA (0 = none); best_effort usually
    # runs without one. An explicit per-request deadline overrides.
    # Env: KEYSTONE_SERVE_GOLD_DEADLINE_MS / KEYSTONE_SERVE_BE_DEADLINE_MS.
    serve_gold_deadline_ms: float = field(
        default_factory=lambda: _env_float(
            "KEYSTONE_SERVE_GOLD_DEADLINE_MS", 500.0
        )
    )
    serve_be_deadline_ms: float = field(
        default_factory=lambda: _env_float("KEYSTONE_SERVE_BE_DEADLINE_MS",
                                           0.0)
    )
    # Hot-swap drain bound (ms): how long the generation flip waits for
    # the OLD generation's service to drain its queued + in-flight
    # requests before failing the stragglers with ServiceClosed (the
    # daemon then transparently re-submits them on the new generation).
    # Env: KEYSTONE_SWAP_DRAIN_MS.
    swap_drain_ms: float = field(
        default_factory=lambda: _env_float("KEYSTONE_SWAP_DRAIN_MS", 30000.0)
    )
    # Upper bound (ms) a synchronous /swap request waits for the swap
    # worker before reporting 504 (the swap itself keeps running).
    # Env: KEYSTONE_SWAP_TIMEOUT_MS.
    swap_timeout_ms: float = field(
        default_factory=lambda: _env_float("KEYSTONE_SWAP_TIMEOUT_MS",
                                           120000.0)
    )
    # Control-plane credential: when set, POST /swap requires a matching
    # X-Swap-Token header and /stats serves its full (tenant-naming)
    # payload only to token holders. When UNSET while KEYSTONE_TENANTS
    # is configured, /swap over HTTP is refused outright (403) — a
    # data-plane key must never be able to replace the model, and an
    # admission-controlled daemon must not ship with an open control
    # plane. Open dev mode (no tenants, no token) leaves /swap open.
    # Env: KEYSTONE_SWAP_TOKEN.
    swap_token: str = field(
        default_factory=lambda: os.environ.get("KEYSTONE_SWAP_TOKEN", "")
    )
    # Online learning (workflow/online.py OnlineTrainer) — refresh
    # cadence in milliseconds: the trainer's _refresh_loop thread
    # re-solves the retained accumulators, writes a versioned artifact,
    # and hot-swaps it into the wired daemon whenever new batches were
    # folded since the last tick. 0 = no background thread (manual
    # refresh() only — the bench/test mode).
    # Env: KEYSTONE_ONLINE_REFRESH_MS.
    online_refresh_ms: float = field(
        default_factory=lambda: _env_float("KEYSTONE_ONLINE_REFRESH_MS",
                                           5000.0)
    )
    # Online time-decay γ ∈ (0, 1]: each partial_fit call scales the
    # retained sums by γ first, so a batch folded a calls ago carries
    # weight γ^a (exponentially-weighted ridge — the drift-tracking
    # mode). 1.0 = no forgetting. Exclusive with online_window.
    # Env: KEYSTONE_ONLINE_DECAY.
    online_decay: float = field(
        default_factory=lambda: _env_float("KEYSTONE_ONLINE_DECAY", 1.0)
    )
    # Online sliding window (batches): keep a per-window accumulator
    # ring of the most recent k partial_fit calls, subtracting the
    # oldest window's sums on evict (counted as windows_evicted).
    # 0 = unbounded horizon. Exclusive with online_decay.
    # Env: KEYSTONE_ONLINE_WINDOW.
    online_window: int = field(
        default_factory=lambda: _env_int("KEYSTONE_ONLINE_WINDOW", 0)
    )
    # Pipeline-graph lint gate (workflow/analysis.py): run the static
    # graph linter before every fit()/compiled(). "off" (default) = never;
    # "warn" = log findings at their severity; "error" = additionally
    # raise LintError on error-severity findings (serveability violations
    # on the pre-compiled() path), so a pipeline the serving engine would
    # refuse at trace time is refused BEFORE any device work.
    # Env: KEYSTONE_LINT.
    lint: str = field(
        default_factory=lambda: _env_choice(
            "KEYSTONE_LINT", ("warn", "error", "off"), "off"
        )
    )
    # Learned serving-capacity model (workflow/capacity.py) — re-plan
    # cadence of the daemon's traffic-aware autoscaling loop, seconds.
    # The loop wakes on this period, compares the observed bucket mix
    # with the mix at the last re-plan, and re-sizes replicas /
    # re-prices the ladder when the shift crosses its threshold. The
    # same window backs the no-flap guard (a second re-plan inside one
    # window is refused, counted). Env: KEYSTONE_CAPACITY_REPLAN_S.
    capacity_replan_s: float = field(
        default_factory=lambda: _env_float("KEYSTONE_CAPACITY_REPLAN_S", 5.0)
    )
    # Journeys the capacity model must observe before ANY consumer
    # (predicted admission, autoscaling, micro-batching) acts on it;
    # below this the model is "cold" and every consumer no-ops
    # bit-identically to KEYSTONE_CAPACITY_MODEL=0 (counted as
    # capacity.model_cold_skips). Env: KEYSTONE_CAPACITY_MIN_SAMPLES.
    capacity_min_samples: int = field(
        default_factory=lambda: _env_int("KEYSTONE_CAPACITY_MIN_SAMPLES", 64)
    )


config = Config()


def resolved_cache_dir() -> str | None:
    """The cross-process fit-cache directory: env presence (not
    truthiness) takes precedence over ``config.cache_dir``, so an
    exported empty KEYSTONE_CACHE_DIR explicitly disables the store.
    Lives here so the env read stays inside config.py (keystone-lint
    KL003: hot paths must not consult os.environ directly)."""
    if "KEYSTONE_CACHE_DIR" in os.environ:
        return os.environ["KEYSTONE_CACHE_DIR"]
    return config.cache_dir


def resolved_exec_workers() -> int | None:
    """The LIVE env value of KEYSTONE_EXEC_WORKERS when it is exported,
    else None. Presence, not truthiness: an explicitly exported 0 pins
    the byte-identical legacy serial walk against the profile-guided
    session plan (PlanResourcesRule); only the unset default falls
    through to the plan. Read live (not the config-instantiation
    snapshot) so a late export behaves like the resolved_cache_dir
    convention. Lives here so the env read stays inside config.py
    (keystone-lint KL003)."""
    if "KEYSTONE_EXEC_WORKERS" in os.environ:
        return _env_int("KEYSTONE_EXEC_WORKERS", 0)
    return None


def resolved_solve_chunk_rows() -> int | None:
    """The LIVE env value of KEYSTONE_SOLVE_CHUNK_ROWS when exported,
    else None — same presence-over-truthiness contract as
    ``resolved_exec_workers``: an explicit 0 pins reactive-halving-only
    against the planner's session plan."""
    if "KEYSTONE_SOLVE_CHUNK_ROWS" in os.environ:
        return _env_int("KEYSTONE_SOLVE_CHUNK_ROWS", 0)
    return None


def resolved_serve_buckets() -> tuple | None:
    """The LIVE env value of KEYSTONE_SERVE_BUCKETS when it is exported
    non-empty, else None — the serve-ladder planner's env pin: an
    explicitly exported bucket list always wins over the HBM-planned
    ladder (the resolved_exec_workers convention). An exported EMPTY
    value reads as unset here (it spells "no in-graph bucketing", not a
    ladder pin). Lives here so the env read stays inside config.py
    (keystone-lint KL003)."""
    if "KEYSTONE_SERVE_BUCKETS" in os.environ:
        return _env_buckets() or None
    return None


def resolved_prefetch_depth() -> int | None:
    """The LIVE env value of KEYSTONE_PREFETCH_DEPTH when exported, else
    None — presence over truthiness: an explicitly exported 0 pins the
    synchronous ingest path against the planner's session clamp
    (PlanResourcesRule); only the unset default falls through to the
    plan, then to ``config.prefetch_depth``."""
    if "KEYSTONE_PREFETCH_DEPTH" in os.environ:
        return _env_int("KEYSTONE_PREFETCH_DEPTH", 2)
    return None


def resolved_telemetry_dir() -> str | None:
    """The durable telemetry export directory: env presence (not
    truthiness) takes precedence over ``config.telemetry_dir``, so an
    exported empty KEYSTONE_TELEMETRY_DIR explicitly disables the
    export (the ``resolved_cache_dir`` convention). Returns None when
    telemetry export is off. Lives here so the env read stays inside
    config.py (keystone-lint KL003)."""
    if "KEYSTONE_TELEMETRY_DIR" in os.environ:
        return os.environ["KEYSTONE_TELEMETRY_DIR"] or None
    return config.telemetry_dir or None


def resolved_capacity_model() -> bool:
    """Whether the learned serving-capacity model is enabled. Resolution
    order (documented contract): an exported KEYSTONE_CAPACITY_MODEL
    wins outright (env_flag spelling — '', '0', 'false', 'no' disable,
    anything else enables); unset, the model defaults ON exactly when a
    telemetry directory is configured (the model trains on and persists
    through those segments — without them it would relearn from zero
    every restart) and OFF otherwise. Lives here so the env read stays
    inside config.py (keystone-lint KL003)."""
    if "KEYSTONE_CAPACITY_MODEL" in os.environ:
        return env_flag("KEYSTONE_CAPACITY_MODEL")
    return resolved_telemetry_dir() is not None


def resolved_profile_store() -> str | None:
    """The measured-profile store directory: env presence (not
    truthiness) takes precedence over ``config.profile_store``, exactly
    like ``resolved_cache_dir`` — an exported empty KEYSTONE_PROFILE_STORE
    explicitly disables the store. Lives here so the env read stays
    inside config.py (keystone-lint KL003)."""
    if "KEYSTONE_PROFILE_STORE" in os.environ:
        return os.environ["KEYSTONE_PROFILE_STORE"] or None
    return config.profile_store
