"""Fault injection, retry/backoff, and the failure taxonomy.

The reference survives executor loss because Spark recomputes lost RDD
partitions from lineage; a KeystoneML ``treeAggregate`` solve shrugs off a
dead task. This JAX port has no scheduler underneath it, so the long-lived
execution surfaces — the prefetch producer (loaders/stream.py), the
chunk-accumulating solvers (linalg/normal_equations.py, linalg/bcd.py),
and the serving micro-batcher (workflow/serving.py) — carry their own
reliability: a transient-error classifier + exponential-backoff retry, a
quarantine path for irrecoverably corrupt records, adaptive chunk
splitting on device OOM (*Memory Safe Computations with XLA* motivates
treating RESOURCE_EXHAUSTED as plannable, not fatal), and checkpointed
accumulator state for restartable solves.

Everything here is tested against the **fault-injection harness**: a
seeded, deterministic ``FaultPlan`` parsed from ``KEYSTONE_FAULTS``
(e.g. ``io:0.05,oom:1,producer_death:1``) that fires synthetic faults at
the exact seams the recovery code guards. Off by default: when the env
var is unset ``active_plan()`` is None and the hot paths hold no plan
reference, so the disabled harness costs nothing per record.

Sites (consumed where the seam lives):

- ``io`` — transient ``InjectedIOError`` at the loader/record boundary
  (probability per record, or a count). Retried by the prefetch producer.
- ``corrupt`` — ``RecordCorruptError`` at the record boundary: the record
  is irrecoverable; the producer quarantines (skips + counts) it.
- ``oom`` — ``InjectedOOM`` (message carries RESOURCE_EXHAUSTED) at the
  chunked solvers' H2D/accumulation step. Retried, then chunk-split.
- ``producer_death`` — the prefetch producer thread exits silently, as a
  killed thread would. The consumer detects and restarts it.
- ``worker_death`` — the serving worker thread dies; ``submit`` detects,
  fails in-flight futures, and restarts it.
- ``replica_death`` — one serving replica's completion thread dies; its
  in-flight flush groups re-queue and re-dispatch to the surviving
  replicas (a fully dead pool revives itself). Zero stranded futures.
- ``conn_drop`` — the serving daemon's client connection drops before
  the response is written (workflow/daemon.py). The request WAS served
  (the future resolved — never stranded); only the response write is
  lost, and the journey records outcome ``conn_drop``.
- ``swap_abort`` — a model hot-swap dies mid-handoff (after the new
  artifact loaded, before the generation flip). The daemon rolls back:
  the old generation keeps serving, the half-warmed successor is
  discarded, and the flight recorder force-dumps naming the generation
  and every in-flight request id.

Counts (``oom:1``) fire on the first N checks of the site; probabilities
(``io:0.05``) draw from a per-site ``random.Random`` seeded from
``KEYSTONE_FAULTS_SEED`` + the site name, so a fixed seed reproduces the
exact fault sequence — the determinism the chaos-equivalence tests pin.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from keystone_tpu.config import config

logger = logging.getLogger("keystone_tpu")


# ---------------------------------------------------------------------------
# Failure taxonomy
# ---------------------------------------------------------------------------


class DeadlineExceeded(TimeoutError):
    """A serving request's deadline passed before a device call ran it."""


class QueueFullError(RuntimeError):
    """Fast-fail backpressure: the serving pending queue is at capacity."""


class ServiceClosed(RuntimeError):
    """The request hit a PipelineService that is (or has been) closed."""


class WorkerDiedError(RuntimeError):
    """The serving worker died while this request was in flight; the
    request may or may not have executed. Safe to retry idempotent work."""


class QuotaExceeded(QueueFullError):
    """Fast-fail admission: the tenant's token-bucket QPS quota is
    exhausted. A subclass of QueueFullError so one 429 mapping covers
    both over-quota and over-budget rejections."""


class AuthError(PermissionError):
    """The request named no tenant the daemon knows (missing or unknown
    API key while tenant admission is configured)."""


class ConnectionDropped(ConnectionError):
    """The client connection dropped before the daemon could write the
    response (real broken pipe, or the harness's ``conn_drop`` site).
    The serve itself completed; only the answer was lost."""


class SwapAborted(RuntimeError):
    """A model hot-swap failed mid-handoff (the harness's ``swap_abort``
    site, or a real warmup/load failure). The daemon rolls back to the
    old generation — an aborted swap is a rollback, never an outage."""


class RefreshAborted(RuntimeError):
    """An online-learning refresh died before publishing (the harness's
    ``refresh_abort`` site, or a real solve/serialize failure). Serving
    keeps answering on the current generation and the retained
    accumulators (plus their checkpoint) are untouched — the next
    cadence tick retries from identical state."""


class RecordCorruptError(ValueError):
    """A record is irrecoverably corrupt — no retry can fix it. The stream
    quarantines (skips + counts) it instead of dying."""


class InjectedIOError(IOError):
    """Harness-injected transient I/O failure (site ``io``)."""


class InjectedOOM(RuntimeError):
    """Harness-injected device allocation failure (site ``oom``). The
    message carries RESOURCE_EXHAUSTED so the one OOM classifier covers
    injected and real failures alike."""


def is_oom(exc: BaseException) -> bool:
    """Device out-of-memory, real (XLA RESOURCE_EXHAUSTED) or injected."""
    if isinstance(exc, InjectedOOM):
        return True
    if isinstance(exc, MemoryError):
        return True
    return "RESOURCE_EXHAUSTED" in str(exc)


def is_transient(exc: BaseException) -> bool:
    """Worth retrying: the same operation may succeed on a fresh attempt.
    Corrupt records are explicitly NOT transient — retrying a bad byte
    stream reproduces it; quarantine is the only recovery."""
    if isinstance(exc, RecordCorruptError):
        return False
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError)):
        return True
    if isinstance(exc, OSError):
        # I/O hiccups (NFS blips, closed sockets) retry; a missing file
        # will be just as missing on attempt two.
        return not isinstance(exc, (FileNotFoundError, IsADirectoryError, NotADirectoryError))
    return is_oom(exc)


# ---------------------------------------------------------------------------
# Fault injection
# ---------------------------------------------------------------------------


class FaultPlan:
    """Seeded, deterministic fault schedule parsed from a
    ``site:value,...`` spec. Integer values are counts (fire on the first
    N checks of the site); fractional values are per-check probabilities.
    Thread-safe: producer threads and the serving worker check
    concurrently."""

    #: Exception constructors per site for ``maybe_raise``.
    _RAISES: Dict[str, Callable[[], BaseException]] = {
        "io": lambda: InjectedIOError(
            "injected transient I/O fault (KEYSTONE_FAULTS io)"
        ),
        "corrupt": lambda: RecordCorruptError(
            "injected corrupt record (KEYSTONE_FAULTS corrupt)"
        ),
        "oom": lambda: InjectedOOM(
            "RESOURCE_EXHAUSTED: injected device OOM (KEYSTONE_FAULTS oom)"
        ),
        "conn_drop": lambda: ConnectionDropped(
            "injected client connection drop (KEYSTONE_FAULTS conn_drop)"
        ),
        "swap_abort": lambda: SwapAborted(
            "injected mid-swap abort (KEYSTONE_FAULTS swap_abort)"
        ),
        "refresh_abort": lambda: RefreshAborted(
            "injected mid-refresh abort (KEYSTONE_FAULTS refresh_abort)"
        ),
    }

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._prob: Dict[str, float] = {}
        self._count: Dict[str, int] = {}
        self._rng: Dict[str, random.Random] = {}
        self.fired: Dict[str, int] = {}
        self.checked: Dict[str, int] = {}
        for token in spec.split(","):
            token = token.strip()
            if not token:
                continue
            try:
                site, _, raw = token.partition(":")
                site = site.strip()
                raw = raw.strip()
                if not site or not raw:
                    raise ValueError
                if "." in raw or "e" in raw.lower():
                    p = float(raw)
                    if not 0.0 <= p <= 1.0:
                        raise ValueError
                    self._prob[site] = p
                else:
                    n = int(raw)
                    if n < 0:
                        raise ValueError
                    self._count[site] = n
            except ValueError:
                raise ValueError(
                    f"KEYSTONE_FAULTS token {token!r}: expected "
                    "'site:count' (int) or 'site:probability' (0..1 float)"
                ) from None
        for site in self._prob:
            # Per-site stream: the fire pattern at one seam is a pure
            # function of (seed, site, check index), independent of what
            # other seams draw.
            self._rng[site] = random.Random(f"{self.seed}:{site}")

    @property
    def sites(self) -> tuple:
        return tuple(sorted(set(self._prob) | set(self._count)))

    def check(self, site: str) -> bool:
        """True when the plan injects a fault at this check."""
        with self._lock:
            self.checked[site] = self.checked.get(site, 0) + 1
            fire = False
            if site in self._count:
                if self._count[site] > 0:
                    self._count[site] -= 1
                    fire = True
            elif site in self._prob:
                fire = self._rng[site].random() < self._prob[site]
            if fire:
                self.fired[site] = self.fired.get(site, 0) + 1
                from keystone_tpu.utils.metrics import reliability_counters

                reliability_counters.bump(f"faults_injected_{site}")
            return fire

    def maybe_raise(self, site: str) -> None:
        """Raise the site's synthetic exception when the plan fires."""
        if self.check(site):
            raise self._RAISES[site]()


_plan_lock = threading.Lock()
_plan: Optional[FaultPlan] = None
_plan_key: Optional[tuple] = None


def active_plan() -> Optional[FaultPlan]:
    """The process-wide FaultPlan, or None when injection is disabled.

    Built from ``config.faults`` / ``config.faults_seed`` (env
    ``KEYSTONE_FAULTS`` / ``KEYSTONE_FAULTS_SEED``) and rebuilt whenever
    those change, so tests flip the knobs without a reload. Call sites
    grab the plan ONCE per stream/solve/service — never per record — so
    the disabled harness (None) adds nothing to hot loops."""
    global _plan, _plan_key
    spec = config.faults or ""
    key = (spec, config.faults_seed)
    with _plan_lock:
        if key != _plan_key:
            _plan = FaultPlan(spec, config.faults_seed) if spec.strip() else None
            _plan_key = key
        return _plan


def reset_fault_plan() -> None:
    """Drop the cached plan (fresh counts/RNG on next ``active_plan``)."""
    global _plan, _plan_key
    with _plan_lock:
        _plan = None
        _plan_key = None


# ---------------------------------------------------------------------------
# Retry/backoff
# ---------------------------------------------------------------------------


@dataclass
class RetryPolicy:
    """Exponential backoff with full jitter and an attempt cap.

    ``delay(i)`` for retry i (0-based) is uniform over
    ``[0, min(max_delay, base * 2**i)]`` — full jitter decorrelates
    retry storms (many producers hitting the same flaky source don't
    resynchronize). The jitter RNG is seeded so a fixed seed reproduces
    the exact backoff schedule; sleeps never affect VALUES, only timing,
    so chaos-equivalence stays bit-identical regardless.
    """

    max_attempts: int = field(
        default_factory=lambda: max(1, config.retry_attempts)
    )
    base_delay: float = field(
        default_factory=lambda: config.retry_base_ms / 1e3
    )
    max_delay: float = field(
        default_factory=lambda: config.retry_max_ms / 1e3
    )
    classify: Callable[[BaseException], bool] = is_transient
    seed: Optional[int] = None
    sleep: Callable[[float], None] = time.sleep

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def delay(self, attempt: int) -> float:
        cap = min(self.max_delay, self.base_delay * (2.0 ** attempt))
        return self._rng.uniform(0.0, cap)

    def call(self, fn: Callable, *, site: str = "op", counter: Optional[str] = None):
        """Run ``fn()`` with up to ``max_attempts`` tries. Transient
        failures (per ``classify``) back off and retry, bumping
        ``reliability_counters[counter or f"{site}_retries"]``; the last
        attempt's error (or any non-transient error) propagates."""
        from keystone_tpu.utils.metrics import reliability_counters

        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except BaseException as exc:
                if not self.classify(exc) or attempt == self.max_attempts - 1:
                    raise
                last = exc
                reliability_counters.bump(counter or f"{site}_retries")
                pause = self.delay(attempt)
                logger.debug(
                    "retrying %s after %s (attempt %d/%d, backoff %.1f ms)",
                    site, type(exc).__name__, attempt + 1,
                    self.max_attempts, pause * 1e3,
                )
                if pause > 0:
                    self.sleep(pause)
        raise last  # unreachable; keeps the type-checker honest
