"""Tolerant numeric comparison — the backbone of the reference's test suite.

Ref: src/main/scala/utils/Stats.scala `aboutEq` [unverified].
"""

from __future__ import annotations

import numpy as np


def about_eq(a, b, tol: float = 1e-6) -> bool:
    """True if every element of |a - b| is within tol (absolute).

    Mirrors `Stats.aboutEq(a, b, tol)`. Accepts scalars, arrays, or nested
    sequences; uses max-abs difference like the reference.
    """
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    if a.shape != b.shape:
        return False
    return bool(np.max(np.abs(a - b), initial=0.0) <= tol)
