"""Observability: tracing, latency histograms, the unified metrics
registry, stage timers, and XLA cost introspection.

Ref: the reference's `Logging` trait with per-stage wall times in pipeline
mains + Spark metrics (SURVEY.md §5 metrics row) [unverified]. KeystoneML
attributed per-stage wall time to every pipeline node to drive its
optimizer; the analog here is three layers:

- ``Tracer`` — nested spans (name, start, duration, thread, attrs) in a
  bounded ring buffer, exported as Chrome-trace JSON viewable in Perfetto
  next to ``jax.profiler`` captures from ``maybe_trace``. Gated on
  ``KEYSTONE_TRACE`` and resolved ONCE per stream/solve/service via
  ``active_tracer()`` (the ``active_plan()`` discipline), so the disabled
  tracer costs a None check, never a per-record context manager.
- ``LatencyHistogram`` / ``Gauge`` — HdrHistogram-style fixed log buckets
  (p50/p95/p99 within one bucket's ~4% quantization) and point-in-time
  gauges with a high-water mark, both thread-safe.
- ``MetricsRegistry`` — every process-wide metric component (serving
  counters, reliability counters, histograms, gauges) under one
  ``snapshot()``/``reset()``; bench tools and the serving health surface
  read this instead of keeping private copies.

Plus the pre-existing FLOP/byte counts straight from the compiled HLO
(`cost_analysis`), which is what per-chip TFLOPS reporting uses.
"""

from __future__ import annotations

import contextvars
import json
import logging
import math
import os
import re
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

logger = logging.getLogger("keystone_tpu")


@contextmanager
def stage_timer(name: str, sink: Dict[str, float] | None = None):
    """Logs (and optionally records) the wall time of a pipeline stage."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        logger.info("stage=%s seconds=%.4f", name, dt)
        if sink is not None:
            sink[name] = dt


def compiled_cost(compiled) -> Dict[str, Any]:
    """FLOPs / bytes-accessed of an already-compiled executable."""
    cost = compiled.cost_analysis() or {}
    # Older jax returns a one-element list of dicts (per-executable);
    # newer returns the dict directly.
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "raw": dict(cost),
    }


def cost_analysis(fn: Callable, *args) -> Dict[str, Any]:
    """FLOPs / bytes-accessed of `fn` as XLA compiles it for these args."""
    return compiled_cost(jax.jit(fn).lower(*args).compile())


@contextmanager
def maybe_trace(tag: str):
    """Capture a jax profiler trace when KEYSTONE_PROFILE_DIR is set — the
    tensorboard-consumable artifact for MXU-utilization work on hardware.
    No-op (zero overhead) when the knob is absent."""
    import os

    out = os.environ.get("KEYSTONE_PROFILE_DIR")
    if not out:
        yield
        return
    path = os.path.join(out, tag)
    with jax.profiler.trace(path):
        yield
    logger.info("profiler trace written to %s", path)


# ---------------------------------------------------------------------------
# Span tracing
# ---------------------------------------------------------------------------


class Tracer:
    """Process-wide span recorder: a bounded ring buffer of (name, cat,
    start, duration, thread, attrs) spans, exported as Chrome-trace JSON.

    Spans nest two ways: timestamps on one thread track contain each other
    (which is all Perfetto needs to draw the flame), and ``span()``
    additionally records the per-thread parent name so tests and the
    report CLI can assert nesting without reconstructing it from time.
    ``record()`` takes externally-captured endpoints — the shape the hot
    paths use (one ``now()`` before, one ``record()`` after, no generator
    frame in the timed region) and the shape cross-thread spans need
    (queue residency starts on the producer, ends on the consumer).

    Thread-safe; the ring (``deque(maxlen=...)``) keeps the most recent
    ``capacity`` spans so a long traced run holds bounded memory.

    Request-scoped spans carry a ``req_id`` (single-request spans:
    ``serve.queued``, ``serve.request``) or ``req_ids`` (group spans:
    ``serve.flush``, ``serve.device``) attr — the serving layer mints one
    monotonic id per request and threads it across the dispatcher,
    replica, and completion threads, so ``spans_for_request()`` can
    reconstruct one request's full cross-thread journey from the ring.
    ``retain_request()`` is the tail-sampling hook: it copies a slow
    request's span tree into a small bounded store that survives ring
    churn (the ring keeps the most recent spans of ALL traffic; the
    retained store keeps the interesting outliers).
    """

    #: How many tail-sampled requests keep their full span trees.
    RETAIN_CAPACITY = 64

    def __init__(self, capacity: int = 65536):
        self.capacity = int(capacity)
        self.epoch_ns = time.perf_counter_ns()
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=self.capacity)
        self._retained: "OrderedDict[int, List[dict]]" = OrderedDict()
        self._tls = threading.local()
        self.dropped = 0  # spans evicted by the ring bound

    @staticmethod
    def now() -> int:
        """Monotonic timestamp (ns) on the tracer's clock."""
        return time.perf_counter_ns()

    def record(
        self,
        name: str,
        cat: str,
        start_ns: int,
        end_ns: Optional[int] = None,
        **attrs,
    ) -> None:
        """Record one completed span from explicit endpoints (``end_ns``
        None = now). ``attrs`` must be JSON-representable."""
        if end_ns is None:
            end_ns = time.perf_counter_ns()
        t = threading.current_thread()
        with self._lock:
            if len(self._spans) == self.capacity:
                self.dropped += 1
            self._spans.append(
                {
                    "name": name,
                    "cat": cat,
                    "start_ns": start_ns,
                    "dur_ns": max(0, end_ns - start_ns),
                    "tid": t.ident,
                    "thread": t.name,
                    "args": attrs,
                }
            )

    def instant(self, name: str, cat: str = "app", **attrs) -> None:
        """A zero-duration marker (cache hits, rejections)."""
        now = time.perf_counter_ns()
        self.record(name, cat, now, now, **attrs)

    @contextmanager
    def span(self, name: str, cat: str = "app", **attrs):
        """Context-managed span; yields the attrs dict so the body can add
        keys it only knows afterwards (e.g. an output shape). Tracks the
        per-thread span stack and stamps the parent name."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        if stack:
            attrs.setdefault("parent", stack[-1])
        stack.append(name)
        t0 = time.perf_counter_ns()
        try:
            yield attrs
        finally:
            end = time.perf_counter_ns()
            stack.pop()
            self.record(name, cat, t0, end, **attrs)

    def spans(self) -> List[dict]:
        """Snapshot of the ring's current spans (oldest first)."""
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._retained.clear()
            self.dropped = 0

    @staticmethod
    def _mentions(span: dict, rid: int) -> bool:
        """Does this span belong to request ``rid`` (``req_id`` attr, or
        membership in a group span's ``req_ids`` list)?"""
        args = span["args"]
        return args.get("req_id") == rid or rid in args.get("req_ids", ())

    def spans_for_request(self, rid: int) -> List[dict]:
        """Every span in the ring OR the retained store that mentions
        request ``rid`` — the cross-thread journey of one request."""
        with self._lock:
            found = [s for s in self._spans if self._mentions(s, rid)]
            kept = self._retained.get(rid)
        if kept:
            seen = {(s["name"], s["start_ns"]) for s in found}
            found.extend(
                s for s in kept if (s["name"], s["start_ns"]) not in seen
            )
        found.sort(key=lambda s: s["start_ns"])
        return found

    #: Slack (ns) on the ``since_ns`` early-exit of ``retain_request``:
    #: the ring is ordered by record() call, which can trail a span's end
    #: timestamp by scheduler jitter across threads.
    RETAIN_SCAN_SLACK_NS = 5_000_000

    def retain_request(self, rid: int,
                       since_ns: Optional[int] = None) -> int:
        """Tail-sampling: copy request ``rid``'s spans from the ring into
        the bounded retained store (oldest retained request evicted past
        ``RETAIN_CAPACITY``), so a slow request's full span tree survives
        ring churn. Returns how many spans were retained.

        ``since_ns`` (the request's submit timestamp) bounds the scan: a
        span that ENDED before the request existed cannot mention it, and
        the ring is ordered by record time, so the newest-first walk
        stops at the first span ending more than a slack margin before
        ``since_ns`` — an expiry storm with tracing armed then scans one
        request's lifetime of spans, not the whole 65536-entry ring,
        while this may run under the serving lock. Without ``since_ns``
        the full ring is scanned (O(ring))."""
        cutoff = (
            since_ns - self.RETAIN_SCAN_SLACK_NS
            if since_ns is not None else None
        )
        with self._lock:
            matched = []
            for s in reversed(self._spans):
                if (
                    cutoff is not None
                    and s["start_ns"] + s["dur_ns"] < cutoff
                ):
                    break
                if self._mentions(s, rid):
                    matched.append(dict(s))
            matched.reverse()
            if not matched:
                return 0
            self._retained[rid] = matched
            self._retained.move_to_end(rid)
            while len(self._retained) > self.RETAIN_CAPACITY:
                self._retained.popitem(last=False)
            return len(matched)

    def retained(self) -> Dict[int, List[dict]]:
        """Snapshot of the tail-sampled store: req id -> its span tree."""
        with self._lock:
            return {rid: list(spans) for rid, spans in self._retained.items()}

    def _as_event(self, s: dict, pid: int) -> dict:
        """One ring span as a Chrome-trace X event (µs timestamps)."""
        return {
            "name": s["name"],
            "cat": s["cat"],
            "ph": "X",
            "ts": (s["start_ns"] - self.epoch_ns) / 1e3,
            "dur": s["dur_ns"] / 1e3,
            "pid": pid,
            "tid": s["tid"],
            "args": s["args"],
        }

    def export(self, path: Optional[str] = None) -> dict:
        """The ring as a Chrome-trace document (``{"traceEvents": [...]}``,
        timestamps/durations in microseconds) — loadable by Perfetto /
        chrome://tracing alongside ``maybe_trace``'s jax profiler capture.
        Tail-sampled span trees ride along under a ``tailSampled`` key
        (req id -> events) so ``tools/trace_report.py --request`` can
        reconstruct a slow request even after the ring churned past it;
        Chrome-trace consumers ignore unknown top-level keys. With
        ``path``, also written as JSON to that file."""
        pid = os.getpid()
        events = []
        threads: Dict[int, str] = {}
        for s in self.spans():
            threads.setdefault(s["tid"], s["thread"])
            events.append(self._as_event(s, pid))
        for tid, tname in threads.items():
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        tail = self.retained()
        if tail:
            doc["tailSampled"] = {
                str(rid): [self._as_event(s, pid) for s in spans]
                for rid, spans in tail.items()
            }
        if path is not None:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
            logger.info("chrome trace (%d events) written to %s",
                        len(events), path)
        return doc


_tracer_lock = threading.Lock()
_tracer: Optional[Tracer] = None
_tracer_key: Optional[tuple] = None


def active_tracer() -> Optional[Tracer]:
    """The process-wide Tracer, or None when tracing is disabled.

    Built from ``config.trace`` / ``config.trace_buffer`` (env
    ``KEYSTONE_TRACE`` / ``KEYSTONE_TRACE_BUFFER``) and rebuilt when those
    change, so tests flip the knob without a reload. Call sites grab the
    tracer ONCE per stream/solve/service/execution — never per record —
    so the disabled tracer (None) adds nothing to hot loops (the
    ``active_plan()`` discipline)."""
    global _tracer, _tracer_key
    from keystone_tpu.config import config

    if not config.trace:
        return None
    key = (True, config.trace_buffer)
    with _tracer_lock:
        if key != _tracer_key or _tracer is None:
            _tracer = Tracer(config.trace_buffer)
            _tracer_key = key
        return _tracer


def reset_tracer() -> None:
    """Drop the cached tracer (a fresh empty ring on next resolve)."""
    global _tracer, _tracer_key
    with _tracer_lock:
        _tracer = None
        _tracer_key = None


class _TracerLoss:
    """Registry adapter exporting the tracer's loss/occupancy numbers
    on /metrics (``keystone_tracer_*`` gauges): a ring that silently
    evicts spans is telemetry lying by omission, so ``Tracer.dropped``
    must be a scrape-able number, not a private attribute. Reads the
    CACHED tracer only — scraping /metrics never arms tracing."""

    def snapshot(self) -> Dict[str, int]:
        with _tracer_lock:
            t = _tracer
        if t is None:
            return {"enabled": 0, "dropped": 0, "spans_held": 0,
                    "retained_requests": 0}
        with t._lock:
            return {
                "enabled": 1,
                "dropped": t.dropped,
                "spans_held": len(t._spans),
                "retained_requests": len(t._retained),
            }

    def reset(self) -> None:
        pass  # stateless view; the tracer itself owns reset


def validate_chrome_trace(doc: Any) -> List[str]:
    """Schema check of a Chrome-trace document; returns the list of
    problems (empty = valid). Shared by ``tools/trace_report.py`` and the
    tier-1 trace-demo test so the exporter and its validator can't
    drift."""
    errors: List[str] = []
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        return ["document must be an object with a 'traceEvents' list"]
    for i, ev in enumerate(doc["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "M", "i", "B", "E"):
            errors.append(f"{where}: bad phase {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or "pid" not in ev:
            errors.append(f"{where}: missing name/pid")
        if ph == "X":
            ts, dur = ev.get("ts"), ev.get("dur")
            if not isinstance(ts, (int, float)) or not isinstance(
                dur, (int, float)
            ):
                errors.append(f"{where}: X event needs numeric ts/dur")
            elif dur < 0:
                errors.append(f"{where}: negative duration")
            if "args" in ev and not isinstance(ev["args"], dict):
                errors.append(f"{where}: args must be an object")
    return errors


# ---------------------------------------------------------------------------
# Latency histograms and gauges
# ---------------------------------------------------------------------------


class LatencyHistogram:
    """Fixed log-bucket latency histogram, HdrHistogram-style.

    Buckets grow geometrically by ``2**(1/sub)`` from ``min_s`` to
    ``max_s`` (defaults: 1 µs → 1000 s at sub=16 ≈ 480 buckets, ~4.4%
    quantization per bucket — well inside the 10% agreement budget the
    serving acceptance check demands). ``record()`` is one ``log2`` + a
    locked bucket increment; min/max/sum are tracked exactly, so mean and
    the extreme percentiles don't pay the quantization. Thread-safe:
    client threads and the serving worker record concurrently."""

    def __init__(self, min_s: float = 1e-6, max_s: float = 1e3, sub: int = 16):
        assert min_s > 0 and max_s > min_s and sub >= 1
        self._lo = float(min_s)
        self._sub = int(sub)
        self._nbuckets = int(math.ceil(math.log2(max_s / min_s) * sub)) + 2
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * self._nbuckets
            self._n = 0
            self._sum = 0.0
            self._min = math.inf
            self._max = 0.0
            self._nonpositive = 0

    def _index(self, seconds: float) -> int:
        if seconds <= self._lo:
            return 0
        i = int(math.log2(seconds / self._lo) * self._sub) + 1
        return min(i, self._nbuckets - 1)

    def _value(self, index: int) -> float:
        """Representative (geometric-midpoint) value of a bucket."""
        if index <= 0:
            return self._lo
        return self._lo * 2.0 ** ((index - 0.5) / self._sub)

    def record(self, seconds: float) -> None:
        # Non-positive samples (clock skew, double-resolution races) are
        # clamped to the minimum bucket AND counted separately: log-bucket
        # math must never see them, and the snapshot's
        # ``dropped_nonpositive`` names how often the clock misbehaved
        # instead of silently polluting the distribution's low tail.
        nonpos = seconds <= 0.0
        if nonpos:
            seconds = self._lo
        i = self._index(seconds)
        with self._lock:
            self._counts[i] += 1
            self._n += 1
            self._sum += seconds
            if nonpos:
                self._nonpositive += 1
            if seconds < self._min:
                self._min = seconds
            if seconds > self._max:
                self._max = seconds

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    def _percentile_locked(self, p: float) -> float:
        """Nearest-rank percentile (caller holds the lock, _n > 0)."""
        target = max(1, math.ceil(self._n * p / 100.0))
        acc = 0
        for i, c in enumerate(self._counts):
            acc += c
            if acc >= target:
                return min(max(self._value(i), self._min), self._max)
        return self._max  # unreachable

    def percentile(self, p: float) -> Optional[float]:
        """The p-th percentile in seconds (nearest-rank over buckets), or
        None when empty. Clamped to the exactly-tracked min/max so p0/p100
        don't carry bucket quantization."""
        with self._lock:
            if self._n == 0:
                return None
            return self._percentile_locked(p)

    def snapshot(self) -> Dict[str, Any]:
        # ONE lock acquisition for counts AND percentiles: a concurrent
        # reset() between them would hand a poller percentile()=None.
        with self._lock:
            if self._n == 0:
                return {"count": 0}
            to_ms = lambda s: round(s * 1e3, 4)  # noqa: E731
            snap = {
                "count": self._n,
                "mean_ms": to_ms(self._sum / self._n),
                "min_ms": to_ms(self._min),
                "p50_ms": to_ms(self._percentile_locked(50)),
                "p95_ms": to_ms(self._percentile_locked(95)),
                "p99_ms": to_ms(self._percentile_locked(99)),
                "max_ms": to_ms(self._max),
            }
            if self._nonpositive:
                snap["dropped_nonpositive"] = self._nonpositive
            return snap

    def buckets(self) -> Dict[str, Any]:
        """The raw distribution for exposition formats: occupied buckets
        as ``(upper_bound_seconds, cumulative_count)`` pairs (sparse —
        empty buckets are omitted; cumulative counts stay valid), plus
        the exact count/sum. This is what ``MetricsRegistry.prometheus()``
        renders as ``_bucket{le=...}`` lines."""
        with self._lock:
            pairs: List[Tuple[float, int]] = []
            cum = 0
            for i, c in enumerate(self._counts):
                cum += c
                if c:
                    le = self._lo * 2.0 ** (i / self._sub) if i else self._lo
                    pairs.append((le, cum))
            return {
                "buckets": pairs,
                "count": self._n,
                "sum": self._sum,
                "dropped_nonpositive": self._nonpositive,
            }


class Gauge:
    """A point-in-time value with a high-water mark (queue depth,
    in-flight requests). Thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0
            self._max = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value
            if value > self._max:
                self._max = value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {"value": self._value, "max": self._max}


class CounterSet:
    """Thread-safe string-keyed monotonic counters — the registry's
    generic tally component (request outcomes, dispatch balance,
    reliability events). Keys are created on first ``bump``; ``snapshot``
    returns whatever was bumped, sorted."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def get(self, key: str) -> int:
        with self._lock:
            return self._counts.get(key, 0)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(sorted(self._counts.items()))


class MetricsRegistry:
    """THE process-wide metrics surface: every counter set, histogram, and
    gauge registers here, and one ``snapshot()``/``reset()`` covers them
    all — bench tools and ``PipelineService.stats()`` read this instead of
    keeping private copies that drift. Components need only
    ``snapshot()``/``reset()`` methods."""

    def __init__(self):
        self._lock = threading.Lock()
        self._parts: Dict[str, Any] = {}

    def register(self, name: str, part: Any) -> Any:
        with self._lock:
            existing = self._parts.get(name)
            if existing is not None and existing is not part:
                raise ValueError(f"metric {name!r} already registered")
            self._parts[name] = part
        return part

    def _get_or_create(self, name: str, factory: Callable[[], Any]) -> Any:
        with self._lock:
            part = self._parts.get(name)
            if part is None:
                part = self._parts[name] = factory()
            return part

    def histogram(self, name: str, **kwargs) -> LatencyHistogram:
        """Get-or-create a named latency histogram."""
        part = self._get_or_create(name, lambda: LatencyHistogram(**kwargs))
        if not isinstance(part, LatencyHistogram):
            raise TypeError(f"metric {name!r} is a {type(part).__name__}")
        return part

    def gauge(self, name: str) -> Gauge:
        """Get-or-create a named gauge."""
        part = self._get_or_create(name, Gauge)
        if not isinstance(part, Gauge):
            raise TypeError(f"metric {name!r} is a {type(part).__name__}")
        return part

    def counters(self, name: str) -> CounterSet:
        """Get-or-create a named counter set (outcome tallies, dispatch
        balance). Per-instance serving metrics use ``base[instance]``
        names — ``serve.requests[svc0]`` — so two services in one process
        never overwrite each other's readings."""
        part = self._get_or_create(name, CounterSet)
        if not isinstance(part, CounterSet):
            raise TypeError(f"metric {name!r} is a {type(part).__name__}")
        return part

    def part(self, name: str, factory: Callable[[], Any]) -> Any:
        """Get-or-create an arbitrary ``snapshot()``/``reset()`` part —
        for adapter views (the daemon's SLO gauges) that need the same
        get-or-create semantics histograms and counter sets enjoy: two
        daemons reusing one name share the family instead of raising."""
        return self._get_or_create(name, factory)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._parts)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            parts = dict(self._parts)
        return {name: part.snapshot() for name, part in sorted(parts.items())}

    def reset(self) -> None:
        with self._lock:
            parts = list(self._parts.values())
        for part in parts:
            part.reset()

    def prometheus(self) -> str:
        """The whole registry as Prometheus text exposition (format 0.0.4)
        — what ``tools/metrics_server.py`` serves at ``/metrics``.

        Naming: every family is prefixed ``keystone_``, dots become
        underscores, and the PR-5 per-instance namespacing
        (``serve.queue_depth[svc0]``) becomes an ``instance`` label
        instead of a distinct family, so one scrape config covers every
        engine/service in the process. Per component type:

        - ``LatencyHistogram`` -> a ``<name>_seconds`` histogram family
          (sparse ``_bucket{le=...}`` lines over the occupied log buckets,
          exact ``_sum``/``_count``), a ``<name>_quantile_seconds`` gauge
          family (p50/p95/p99, the same nearest-rank numbers
          ``snapshot()`` reports), and a ``_dropped_nonpositive_total``
          counter;
        - ``Gauge`` -> ``<name>`` and ``<name>_max`` gauges;
        - ``CounterSet`` -> ``<name>_total`` counters, keys as a ``key``
          label;
        - anything else (e.g. the serving compile counters) -> its
          ``snapshot()`` dict flattened to gauges, one level of nested
          dict becoming a ``key`` label.

        The output always parses under ``validate_prometheus_text`` and
        agrees with ``snapshot()`` — both are pinned by tier-1.
        """
        with self._lock:
            parts = dict(self._parts)
        fams: "OrderedDict[str, dict]" = OrderedDict()

        def fam(name: str, typ: str) -> List[tuple]:
            entry = fams.setdefault(name, {"type": typ, "samples": []})
            return entry["samples"]

        for name, part in sorted(parts.items()):
            base, instance = _split_instance(name)
            mname = _prom_name(base)
            labels = {"instance": instance} if instance else {}
            if isinstance(part, LatencyHistogram):
                dist = part.buckets()
                hname = f"{mname}_seconds"
                samples = fam(hname, "histogram")
                for le, cum in dist["buckets"]:
                    samples.append((
                        f"{hname}_bucket",
                        {**labels, "le": _format_value(le)},
                        cum,
                    ))
                samples.append((
                    f"{hname}_bucket", {**labels, "le": "+Inf"},
                    dist["count"],
                ))
                samples.append((f"{hname}_sum", labels, dist["sum"]))
                samples.append((f"{hname}_count", labels, dist["count"]))
                qsamples = fam(f"{mname}_quantile_seconds", "gauge")
                snap = part.snapshot()
                for q, key in ((0.5, "p50_ms"), (0.95, "p95_ms"),
                               (0.99, "p99_ms")):
                    if key in snap:
                        qsamples.append((
                            f"{mname}_quantile_seconds",
                            {**labels, "quantile": str(q)},
                            snap[key] / 1e3,
                        ))
                dname = f"{mname}_dropped_nonpositive_total"
                fam(dname, "counter").append(
                    (dname, labels, dist["dropped_nonpositive"])
                )
            elif isinstance(part, Gauge):
                snap = part.snapshot()
                fam(mname, "gauge").append((mname, labels, snap["value"]))
                fam(f"{mname}_max", "gauge").append(
                    (f"{mname}_max", labels, snap["max"])
                )
            elif isinstance(part, CounterSet):
                cname = f"{mname}_total"
                samples = fam(cname, "counter")
                for key, count in part.snapshot().items():
                    samples.append((cname, {**labels, "key": key}, count))
            else:
                for key, val in part.snapshot().items():
                    sub = f"{mname}_{_PROM_BAD.sub('_', str(key))}"
                    if isinstance(val, bool) or val is None:
                        continue
                    if isinstance(val, (int, float)):
                        fam(sub, "gauge").append((sub, labels, val))
                    elif isinstance(val, dict):
                        samples = fam(sub, "gauge")
                        for k2, v2 in val.items():
                            if isinstance(v2, (int, float)) and not isinstance(
                                v2, bool
                            ):
                                samples.append(
                                    (sub, {**labels, "key": str(k2)}, v2)
                                )
        lines: List[str] = []
        for fname, entry in fams.items():
            if not entry["samples"]:
                continue
            lines.append(f"# TYPE {fname} {entry['type']}")
            for sname, labels, value in entry["samples"]:
                lines.append(
                    f"{sname}{_prom_labels(labels)} {_format_value(value)}"
                )
        return "\n".join(lines) + "\n"


metrics_registry = MetricsRegistry()


# ---------------------------------------------------------------------------
# Prometheus text exposition helpers (stdlib only — the export surface)
# ---------------------------------------------------------------------------

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_INSTANCE_RE = re.compile(r"^(?P<base>.+?)\[(?P<instance>[^\]]+)\]$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>\S+)$"
)
_LABEL_RE = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\.)*)"'
)
_PROM_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def _split_instance(name: str) -> Tuple[str, Optional[str]]:
    """Split the registry's ``base[instance]`` namespacing into a family
    base and an instance label value."""
    m = _INSTANCE_RE.match(name)
    if m:
        return m.group("base"), m.group("instance")
    return name, None


def _prom_name(base: str) -> str:
    name = _PROM_BAD.sub("_", base)
    if name and name[0].isdigit():
        name = "_" + name
    return f"keystone_{name}"


def _prom_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        '{}="{}"'.format(
            k,
            str(v).replace("\\", r"\\").replace('"', r"\"").replace(
                "\n", r"\n"
            ),
        )
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _format_value(v) -> str:
    """A float/int as Prometheus spells it (no trailing .0 on ints, repr
    precision on floats so the scrape agrees with ``snapshot()``)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f == math.inf:
        return "+Inf"
    if f == -math.inf:
        return "-Inf"
    return repr(f)


_LABEL_ESCAPES = {"n": "\n", "\\": "\\", '"': '"'}


def _unescape_label(value: str) -> str:
    """Decode label-value escapes in ONE pass: sequential str.replace
    would let the tail of an escaped backslash re-match as the head of
    another escape (``dir\\\\name`` -> ``dir\\<newline>ame``)."""
    return re.sub(
        r"\\(.)", lambda m: _LABEL_ESCAPES.get(m.group(1), m.group(1)),
        value,
    )


def _parse_prom_value(raw: str) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    return float(raw)  # raises ValueError on garbage; NaN parses


def parse_prometheus_text(text: str) -> List[Dict[str, Any]]:
    """Parse text exposition into sample dicts (``name``, ``labels``,
    ``value``). Raises ValueError naming the first malformed line —
    ``validate_prometheus_text`` is the error-list wrapper."""
    samples: List[Dict[str, Any]] = []
    for i, line in enumerate(text.splitlines(), 1):
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] == "TYPE":
                if len(parts) != 4 or parts[3] not in _PROM_TYPES:
                    raise ValueError(f"line {i}: malformed TYPE: {line!r}")
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {i}: malformed sample: {line!r}")
        raw_labels = m.group("labels") or ""
        labels: Dict[str, str] = {}
        if raw_labels:
            for lm in _LABEL_RE.finditer(raw_labels):
                labels[lm.group("key")] = _unescape_label(lm.group("value"))
            leftovers = _LABEL_RE.sub("", raw_labels).strip(", \t")
            if leftovers:
                raise ValueError(
                    f"line {i}: malformed labels: {raw_labels!r}"
                )
        try:
            value = _parse_prom_value(m.group("value"))
        except ValueError:
            raise ValueError(
                f"line {i}: bad sample value {m.group('value')!r}"
            ) from None
        samples.append({"name": m.group("name"), "labels": labels,
                        "value": value})
    return samples


def validate_prometheus_text(text: str) -> List[str]:
    """Schema check of a Prometheus text exposition; returns the list of
    problems (empty = valid). Shared by ``tools/metrics_server.py``'s
    smoke mode and the tier-1 export tests so the renderer and its
    validator can't drift. Beyond line syntax, histogram families are
    checked for cumulative, ``+Inf``-terminated buckets that agree with
    ``_count``."""
    errors: List[str] = []
    try:
        samples = parse_prometheus_text(text)
    except ValueError as e:
        return [str(e)]
    types: Dict[str, str] = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) == 4:
                if parts[2] in types:
                    errors.append(f"duplicate TYPE for {parts[2]}")
                types[parts[2]] = parts[3]
    by_name: Dict[str, List[dict]] = {}
    for s in samples:
        by_name.setdefault(s["name"], []).append(s)
    for fname, typ in types.items():
        if typ != "histogram":
            continue
        series: Dict[tuple, List[tuple]] = {}
        for s in by_name.get(f"{fname}_bucket", []):
            key = tuple(sorted(
                (k, v) for k, v in s["labels"].items() if k != "le"
            ))
            le = s["labels"].get("le")
            if le is None:
                errors.append(f"{fname}_bucket sample missing le label")
                continue
            try:
                le_val = _parse_prom_value(le)
            except ValueError:
                # A validator must report, never raise: that is its
                # whole contract against untrusted exposition text.
                errors.append(f"{fname}_bucket: non-numeric le {le!r}")
                continue
            series.setdefault(key, []).append((le_val, s["value"]))
        counts = {
            tuple(sorted(s["labels"].items())): s["value"]
            for s in by_name.get(f"{fname}_count", [])
        }
        for key, pairs in series.items():
            les = [p[0] for p in pairs]
            cums = [p[1] for p in pairs]
            if les != sorted(les):
                errors.append(f"{fname}{dict(key)}: le bounds not sorted")
            if any(b < a for a, b in zip(cums, cums[1:])):
                errors.append(
                    f"{fname}{dict(key)}: bucket counts not cumulative"
                )
            if not les or les[-1] != math.inf:
                errors.append(f"{fname}{dict(key)}: no le=\"+Inf\" bucket")
            elif counts and counts.get(key) != cums[-1]:
                errors.append(
                    f"{fname}{dict(key)}: _count disagrees with +Inf bucket"
                )
    return errors


def environment_fingerprint(devices: bool = True) -> Dict[str, Any]:
    """Provenance block for every bench JSON writer: the jax/runtime
    identity plus whichever ``KEYSTONE_*`` knobs were in effect, so
    cross-run comparisons (e.g. a p99 delta between rounds) are
    interpretable instead of mystery noise.

    ``devices=False`` skips the device probe — for orchestrator processes
    (bench.py's driver, tools/bench_mfu.py) that deliberately never
    initialize the backend in-process because a dead TPU plugin can HANG
    initialization, not just fail it."""
    import platform as _platform

    def _redact(name: str, value: str) -> str:
        # Secrets must never ride the fingerprint into committed bench
        # JSON: KEYSTONE_SWAP_TOKEN is the control-plane credential
        # (fully masked), KEYSTONE_TENANTS carries tenant API KEYS
        # ('name:api_key:qps[:tier[:burst]]' — the key field is masked,
        # the name/qps/tier provenance survives).
        if name == "KEYSTONE_SWAP_TOKEN" and value:
            return "****"
        if name != "KEYSTONE_TENANTS" or not value.strip():
            return value
        masked = []
        for token in value.split(","):
            parts = token.split(":")
            if len(parts) >= 2:
                parts[1] = "****"
            masked.append(":".join(parts))
        return ",".join(masked)

    fp: Dict[str, Any] = {
        "jax": getattr(jax, "__version__", None),
        "python": _platform.python_version(),
        "cpu_count": os.cpu_count(),
        "keystone_env": {
            k: _redact(k, v) for k, v in sorted(os.environ.items())
            if k.startswith("KEYSTONE_")
        },
    }
    try:
        import numpy as _np

        fp["numpy"] = _np.__version__
    except ImportError:  # fingerprint stays useful without numpy
        pass
    if not devices:
        return fp
    try:
        devs = jax.local_devices()
        fp["backend"] = jax.default_backend()
        fp["device_kind"] = devs[0].device_kind if devs else None
        fp["device_count"] = jax.device_count()
    except Exception as e:  # lint: broad-ok deviceless/dead backend raises backend-specific types: record, don't die
        fp["backend_error"] = str(e)[:200]
    return fp


# Device memory probes, memoized per process: ``jax.local_devices()`` and
# an unsupported ``memory_stats()`` are host syncs, and once the profiler
# wires these onto the per-node hot path they must cost a dict read, not a
# runtime round-trip per node. ``False`` = probed and unavailable.
_memprobe_lock = threading.Lock()
_memprobe_device: Any = None
_hbm_limit_memo: Any = None  # None=unprobed, False=not reported, else int
_peak_supported: Optional[bool] = None


def reset_memory_probe() -> None:
    """Drop the memoized device/limit probes (tests, backend swaps)."""
    global _memprobe_device, _hbm_limit_memo, _peak_supported
    with _memprobe_lock:
        _memprobe_device = None
        _hbm_limit_memo = None
        _peak_supported = None


def _memory_stats_device():
    """Device 0 for ``memory_stats`` probes, resolved ONCE per process
    (None when the backend is dead or deviceless)."""
    global _memprobe_device
    dev = _memprobe_device
    if dev is None:
        with _memprobe_lock:
            if _memprobe_device is None:
                try:
                    devs = jax.local_devices()
                    _memprobe_device = devs[0] if devs else False
                except Exception:  # lint: broad-ok a dead/deviceless backend raises backend-specific types; all mean 'nothing to probe'
                    _memprobe_device = False
            dev = _memprobe_device
    return dev if dev is not False else None


def device_hbm_bytes(default: int | None = None) -> int:
    """Memory budget of device 0 as the runtime reports it (``bytes_limit``
    from ``memory_stats``), falling back to ``config.hbm_budget_bytes`` for
    backends that don't report one (notably CPU). The device probe AND the
    reported limit are memoized per process — the limit is static, and
    re-asking the runtime per call is a host sync. Always returns an int."""
    from keystone_tpu.config import config

    global _hbm_limit_memo
    limit = _hbm_limit_memo
    if limit is None:
        dev = _memory_stats_device()
        found: Any = False
        if dev is not None:
            try:
                stats = dev.memory_stats() or {}
                raw = stats.get("bytes_limit")
                if raw:
                    found = int(raw)
            except Exception:  # lint: broad-ok backend-specific probe failures all mean 'no reported limit'
                pass
        with _memprobe_lock:
            _hbm_limit_memo = found
        limit = found
    if limit is not False:
        return int(limit)
    return int(default) if default is not None else config.hbm_budget_bytes


def peak_hbm_bytes() -> int | None:
    """HBM high-water of device 0 (``peak_bytes_in_use``), or None where
    the runtime doesn't report it (notably CPU). Shared by the
    single-number evidence rows (bench line, streamed-overlap step) and
    the profiler's per-node HBM deltas; the checkride ``memory_stats``
    step deliberately keeps its own multi-key probe — it exists to record
    the runtime's whole key set, including whatever a different runtime
    names the peak.

    The device handle and the does-this-runtime-report-a-peak verdict are
    memoized per process (the CPU backend answers None forever; asking it
    again per profiled node would put a host sync on the hot path). The
    peak VALUE itself is re-read on every call where supported."""
    global _peak_supported
    if _peak_supported is False:
        return None
    dev = _memory_stats_device()
    peak = None
    if dev is not None:
        try:
            stats = dev.memory_stats() or {}
            peak = stats.get("peak_bytes_in_use")
        except Exception:  # lint: broad-ok backend-specific probe failures all mean 'no reported peak'
            peak = None
    if peak is None:
        with _memprobe_lock:
            _peak_supported = False
        return None
    if _peak_supported is None:
        with _memprobe_lock:
            _peak_supported = True
    return int(peak)


_runtime_fp_lock = threading.Lock()
_runtime_fp: Optional[Dict[str, Any]] = None


def runtime_fingerprint() -> Dict[str, Any]:
    """The small memoized backend-identity subset of
    ``environment_fingerprint`` (jax version, backend, device kind/count)
    that profile snapshots and solver journey records carry, so
    ``tools/bench_watch.py`` can refuse to compare rows recorded under
    different backends or device counts. Memoized per process: the full
    fingerprint probes devices per call, which is a host sync once this
    rides every solve record."""
    global _runtime_fp
    fp = _runtime_fp
    if fp is None:
        fp = {
            "jax": getattr(jax, "__version__", None),
            "backend": None,
            "device_kind": None,
            "device_count": None,
        }
        try:
            fp["backend"] = jax.default_backend()
            fp["device_count"] = int(jax.device_count())
            devs = jax.local_devices()
            fp["device_kind"] = devs[0].device_kind if devs else None
        except Exception as e:  # lint: broad-ok deviceless/dead backend raises backend-specific types: record, don't die
            fp["backend_error"] = str(e)[:200]
        with _runtime_fp_lock:
            _runtime_fp = fp
    return dict(fp)


# ---------------------------------------------------------------------------
# Per-node resource attribution (the training-side profiler)
# ---------------------------------------------------------------------------

#: FIFO bound on the per-(transformer, shape, dtype) cost-model memo.
_NODE_COST_CAP = 256
_node_cost_lock = threading.Lock()
#: key -> (estimate dict | None, transformer pin). The pin keeps the
#: transformer alive while its id() keys the memo, so CPython id reuse
#: can never alias a stale entry (the _prefix_pins discipline).
_node_cost_memo: "OrderedDict[tuple, tuple]" = OrderedDict()


def _memory_analysis(compiled) -> Dict[str, float]:
    """Whatever ``memory_analysis`` the backend reports for a compiled
    executable, as plain floats (empty where unsupported)."""
    try:
        mem = compiled.memory_analysis()
    except Exception:  # lint: broad-ok memory_analysis is backend-optional; absence means 'no estimate'
        return {}
    out: Dict[str, float] = {}
    for attr, key in (
        ("temp_size_in_bytes", "temp_bytes"),
        ("argument_size_in_bytes", "argument_bytes"),
        ("output_size_in_bytes", "output_bytes"),
        ("generated_code_size_in_bytes", "code_bytes"),
        # Donated-and-aliased input bytes: a donated lowering's working
        # set is argument+output+temp MINUS alias (the aliased buffers
        # are the same memory counted twice) — the backend-portable
        # evidence that donation lowered the high-water, usable where
        # peak_bytes_in_use isn't reported (CPU fake-device runs).
        ("alias_size_in_bytes", "alias_bytes"),
    ):
        v = getattr(mem, attr, None)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = float(v)
    return out


def node_cost_analysis(transformer, X) -> Optional[Dict[str, float]]:
    """Cost-model estimate (FLOPs, bytes accessed, memory analysis) of
    running ``transformer.apply_batch`` at ``X``'s shape — computed ONCE
    per (transformer, shape, dtype) via an abstract AOT lower+compile
    (``ShapeDtypeStruct``: no data touched, nothing executed) and
    memoized, so a profiled fit pays one extra compile per distinct
    executable, never one per node execution. Returns None where the
    transformer can't lower (host nodes, non-array inputs) — those rows
    stay measured-only."""
    shape = tuple(getattr(X, "shape", ()) or ())
    dtype = getattr(X, "dtype", None)
    if not shape or dtype is None or not getattr(transformer, "jittable", False):
        return None
    key = (id(transformer), shape, str(dtype))
    with _node_cost_lock:
        hit = _node_cost_memo.get(key)
    if hit is not None:
        est = hit[0]
        return dict(est) if est else None
    try:
        spec = jax.ShapeDtypeStruct(shape, dtype)
        # The transformer's own cached jit wrapper (built lazily by
        # batch_call) keeps this the SAME executable identity the traced
        # path runs where the runtime caches by avals.
        jitted = getattr(transformer, "_jitted", None)
        fn = jitted() if jitted is not None else jax.jit(transformer.apply_batch)
        compiled = fn.lower(spec).compile()
        cost = compiled_cost(compiled)
        est = {
            "flops": cost["flops"],
            "bytes_accessed": cost["bytes_accessed"],
        }
        est.update(_memory_analysis(compiled))
    except Exception:  # lint: broad-ok the cost model is best-effort; any lowering/compile failure means 'no estimate', never a failed fit
        est = None
    with _node_cost_lock:
        _node_cost_memo[key] = (est, transformer)
        while len(_node_cost_memo) > _NODE_COST_CAP:
            _node_cost_memo.popitem(last=False)
    return dict(est) if est else None


class ResourceProfile:
    """Per-node resource attribution for executor walks — the
    training-side answer to "what does each operator cost", the
    measurement substrate KeystoneML's cost-based optimization presumes.

    One process-wide instance aggregates rows keyed by node label:
    per-node call count, wall time (covering device completion — the
    profiled path blocks on array outputs), dispatch time, cost-model
    FLOPs / bytes accessed (from the memoized ``node_cost_analysis``
    AOT compile — estimates, not measurements), output nbytes, the HBM
    high-water delta where the runtime reports one, and cache-status
    tallies (hit / memo / miss). Registered in ``metrics_registry`` as
    ``"profile"`` so ``snapshot()`` and the Prometheus exposition carry
    the per-node families (``keystone_profile_node_*{key="<label>"}``).

    Thread-safe; populated only when ``active_profile()`` resolves
    non-None (KEYSTONE_PROFILE, or a ``profile_scope()`` forced by
    ``Pipeline.fit(profile=True)``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._nodes: "OrderedDict[str, dict]" = OrderedDict()
        # Content-addressed measured aggregates: prefix digest ->
        # {label, calls, wall_ns, out_bytes, out_rows, queue_wait_ns}.
        # This is what the profile store persists and the optimizer rules
        # re-match to graph nodes — digests survive graph copies, fusion
        # (chain_digest folds stage-by-stage), and process restarts,
        # where labels collide and ids die.
        self._digests: "OrderedDict[str, dict]" = OrderedDict()

    def reset(self) -> None:
        with self._lock:
            self._nodes.clear()
            self._digests.clear()

    def record_node(
        self,
        label: str,
        wall_ns: int = 0,
        dispatch_ns: Optional[int] = None,
        flops: Optional[float] = None,
        bytes_accessed: Optional[float] = None,
        out_nbytes: Optional[int] = None,
        hbm_delta: Optional[int] = None,
        cache: str = "miss",
        queue_wait_ns: Optional[int] = None,
        worker: Optional[str] = None,
        digest: Optional[str] = None,
        out_rows: Optional[int] = None,
        out_shape: Optional[list] = None,
        data_shards: Optional[int] = None,
    ) -> None:
        """Fold one node execution into the label's aggregate row.

        Safe under concurrent callers: the parallel executor walk records
        from every pool thread, and each fold is one atomic
        read-modify-write under the profile lock — call counts and wall
        sums stay exact at any worker count. ``queue_wait_ns`` (ready →
        picked up by a worker) and ``worker`` (pool thread name) are the
        parallel walk's scheduling attribution; the serial walk passes
        neither."""
        with self._lock:
            agg = self._nodes.get(label)
            if agg is None:
                agg = self._nodes[label] = {
                    "calls": 0, "wall_ns": 0, "dispatch_ns": 0,
                    "flops": 0.0, "bytes_accessed": 0.0, "output_bytes": 0,
                    "hbm_delta_bytes": 0, "cost_modeled": 0,
                    "hbm_known": False, "queue_wait_ns": 0,
                    "workers": set(), "data_shards": None,
                    "cache": {"hit": 0, "memo": 0, "miss": 0},
                }
            agg["calls"] += 1
            agg["wall_ns"] += int(wall_ns)
            if dispatch_ns is not None:
                agg["dispatch_ns"] += int(dispatch_ns)
            if flops is not None:
                agg["flops"] += float(flops)
                agg["cost_modeled"] += 1
            if bytes_accessed is not None:
                agg["bytes_accessed"] += float(bytes_accessed)
            if out_nbytes is not None:
                agg["output_bytes"] += int(out_nbytes)
            if hbm_delta is not None:
                agg["hbm_delta_bytes"] += int(hbm_delta)
                agg["hbm_known"] = True
            if queue_wait_ns is not None:
                agg["queue_wait_ns"] += int(queue_wait_ns)
            if worker is not None:
                agg["workers"].add(str(worker))
            if data_shards is not None:
                # Last-write (like out_shape): how many data shards the
                # node's output spanned — the profile row's mesh-width
                # provenance, so a 1-shard row is visibly 1-shard.
                agg["data_shards"] = int(data_shards)
            agg["cache"][cache] = agg["cache"].get(cache, 0) + 1
            # Digest aggregation covers EXECUTED nodes only (cache
            # hits/memos carry no digest): the stored profile must
            # describe what computing the node costs, not what skipping
            # it cost.
            if digest is not None:
                dagg = self._digests.get(digest)
                if dagg is None:
                    dagg = self._digests[digest] = {
                        "label": label, "calls": 0, "wall_ns": 0,
                        "out_bytes": 0, "out_rows": 0, "queue_wait_ns": 0,
                        "out_shape": None, "data_shards": None,
                    }
                dagg["calls"] += 1
                dagg["wall_ns"] += int(wall_ns)
                if queue_wait_ns is not None:
                    dagg["queue_wait_ns"] += int(queue_wait_ns)
                if out_nbytes is not None:
                    dagg["out_bytes"] = int(out_nbytes)
                if out_rows is not None:
                    dagg["out_rows"] = int(out_rows)
                if out_shape is not None:
                    dagg["out_shape"] = list(out_shape)
                if data_shards is not None:
                    dagg["data_shards"] = int(data_shards)

    #: Numeric aggregate fields a ``mark()`` delta subtracts.
    _DELTA_FIELDS = ("calls", "wall_ns", "dispatch_ns", "flops",
                     "bytes_accessed", "output_bytes", "hbm_delta_bytes",
                     "cost_modeled", "queue_wait_ns")

    def mark(self) -> Dict[str, dict]:
        """Opaque snapshot of the per-label aggregates, for delta views:
        ``rows(since=mark)`` / ``table(since=mark)`` report only what was
        recorded AFTER the mark — how ``Pipeline.fit(profile=True)``
        logs one fit's attribution without resetting the process-wide
        profile other readers (Prometheus) are watching."""
        with self._lock:
            return {
                label: dict(agg, cache=dict(agg["cache"]),
                            workers=set(agg["workers"]))
                for label, agg in self._nodes.items()
            }

    def mark_digests(self) -> Dict[str, dict]:
        """``mark()`` for the digest-keyed aggregates: ``digest_rows``
        with this snapshot reports only executions recorded AFTER it —
        how one fit's measurements are carved out of the process-wide
        accumulation for the profile store."""
        with self._lock:
            return {d: dict(agg) for d, agg in self._digests.items()}

    #: Numeric digest-aggregate fields a ``mark_digests()`` delta
    #: subtracts (out_bytes / out_rows are last-write sizes, not sums).
    _DIGEST_DELTA_FIELDS = ("calls", "wall_ns", "queue_wait_ns")

    def digest_rows(
        self, since: Optional[Dict[str, dict]] = None
    ) -> Dict[str, Dict[str, Any]]:
        """The content-addressed measured aggregates ({prefix digest ->
        {label, calls, wall_ns, out_bytes, out_rows, queue_wait_ns}}) the
        profile store persists. ``since`` (a ``mark_digests()``) restricts
        to the delta; digests untouched after the mark are dropped."""
        with self._lock:
            items = {d: dict(agg) for d, agg in self._digests.items()}
        if since is None:
            return items
        out: Dict[str, Dict[str, Any]] = {}
        for d, agg in items.items():
            base = since.get(d)
            if base is not None:
                agg = dict(agg)
                for f in self._DIGEST_DELTA_FIELDS:
                    agg[f] = agg[f] - base[f]
            if agg["calls"] > 0:
                out[d] = agg
        return out

    def rows(
        self, since: Optional[Dict[str, dict]] = None
    ) -> List[Dict[str, Any]]:
        """Attribution rows (one per node label, heaviest wall first) in
        the shape ``render_attribution_table`` and
        ``tools/profile_report.py`` consume. FLOPs/bytes are cost-model
        ESTIMATES (provenance ``cost-model``); wall/dispatch/output are
        measured. ``since`` (a ``mark()``) restricts to the delta —
        labels untouched after the mark are dropped."""
        with self._lock:
            # workers is copied under the lock (like mark()): the live set
            # keeps mutating under concurrent record_node calls, and
            # sorting it outside the lock would iterate a changing set.
            items = [(label, dict(agg, workers=set(agg["workers"])),
                      dict(agg["cache"]))
                     for label, agg in self._nodes.items()]
        if since is not None:
            delta_items = []
            for label, agg, cache in items:
                base = since.get(label)
                if base is not None:
                    agg = dict(agg)
                    for f in self._DELTA_FIELDS:
                        agg[f] = agg[f] - base[f]
                    # workers is a set, not a counter: the delta view
                    # names only pool threads first seen AFTER the mark.
                    agg["workers"] = agg["workers"] - base.get(
                        "workers", set()
                    )
                    cache = {
                        k: v - base["cache"].get(k, 0)
                        for k, v in cache.items()
                    }
                if agg["calls"] > 0:
                    delta_items.append((label, agg, cache))
            items = delta_items
        rows = []
        for label, agg, cache in items:
            executed = cache.get("miss", 0)
            rows.append({
                "node": label,
                "calls": agg["calls"],
                "wall_ms": round(agg["wall_ns"] / 1e6, 4),
                "dispatch_ms": round(agg["dispatch_ns"] / 1e6, 4),
                "device_wait_ms": round(
                    max(0, agg["wall_ns"] - agg["dispatch_ns"]) / 1e6, 4
                ),
                "flops": agg["flops"] if agg["cost_modeled"] else None,
                "bytes_accessed": (
                    agg["bytes_accessed"] if agg["cost_modeled"] else None
                ),
                "output_bytes": agg["output_bytes"] or None,
                "hbm_delta_bytes": (
                    agg["hbm_delta_bytes"] if agg["hbm_known"] else None
                ),
                "cache_hits": cache.get("hit", 0) + cache.get("memo", 0),
                "executed": executed,
                # Parallel-walk scheduling attribution: time spent ready
                # but unclaimed, and which pool threads ran the label.
                # None/empty under the serial walk.
                "queue_wait_ms": (
                    round(agg["queue_wait_ns"] / 1e6, 4)
                    if agg["queue_wait_ns"] else None
                ),
                "workers": sorted(agg["workers"]) or None,
                # Mesh-width provenance: how many data shards the node's
                # output spanned (None where never observed/arrayless).
                "data_shards": agg.get("data_shards"),
                "provenance": (
                    "cost-model" if agg["cost_modeled"] else "measured"
                ),
            })
        rows.sort(key=lambda r: -r["wall_ms"])
        return rows

    def snapshot(self) -> Dict[str, Any]:
        """Registry-shape snapshot: per-label numeric families (flattened
        by the Prometheus exposition into ``key``-labelled gauges) plus
        the memoized runtime fingerprint for cross-run comparability."""
        with self._lock:
            items = [(label, dict(agg)) for label, agg in self._nodes.items()]
        snap: Dict[str, Any] = {
            "nodes": len(items),
            "node_calls": {}, "node_wall_seconds": {},
            "node_device_wait_seconds": {}, "node_flops": {},
            "node_bytes_accessed": {}, "node_output_bytes": {},
            "node_hbm_delta_bytes": {}, "node_queue_wait_seconds": {},
            "node_workers": {},
        }
        for label, agg in items:
            snap["node_calls"][label] = agg["calls"]
            snap["node_wall_seconds"][label] = agg["wall_ns"] / 1e9
            snap["node_device_wait_seconds"][label] = (
                max(0, agg["wall_ns"] - agg["dispatch_ns"]) / 1e9
            )
            if agg["queue_wait_ns"]:
                snap["node_queue_wait_seconds"][label] = (
                    agg["queue_wait_ns"] / 1e9
                )
            if agg["workers"]:
                snap["node_workers"][label] = len(agg["workers"])
            if agg["cost_modeled"]:
                snap["node_flops"][label] = agg["flops"]
                snap["node_bytes_accessed"][label] = agg["bytes_accessed"]
            if agg["output_bytes"]:
                snap["node_output_bytes"][label] = agg["output_bytes"]
            if agg["hbm_known"]:
                snap["node_hbm_delta_bytes"][label] = agg["hbm_delta_bytes"]
        snap["fingerprint"] = runtime_fingerprint()
        return snap

    def table(self, since: Optional[Dict[str, dict]] = None) -> str:
        """The attribution table, rendered (see
        ``render_attribution_table``); ``since`` as in ``rows``."""
        return render_attribution_table(self.rows(since=since))

    def export(self, path: str) -> dict:
        """Write rows + snapshot as JSON (atomic), for
        ``tools/profile_report.py`` to render offline."""
        doc = {
            "profile": self.snapshot(),
            "rows": self.rows(),
            "digests": self.digest_rows(),
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        return doc


def render_attribution_table(rows: List[Dict[str, Any]]) -> str:
    """The trace_report-style attribution table over profile rows — ONE
    renderer shared by ``Pipeline.fit(profile=True)``'s log line,
    ``tools/profile_report.py``, and ``tools/trace_report.py --fit``, so
    a live profile and a Chrome trace of the same fit render identically.
    Missing columns (a trace has no cost model) print as ``-``."""

    def num(v, scale=1.0, fmt="{:.3f}"):
        if v is None:
            return "-"
        return fmt.format(v / scale)

    header = (
        f"{'node':<40} {'calls':>5} {'wall ms':>10} {'wait ms':>9} "
        f"{'MFLOP':>10} {'MB moved':>9} {'out MB':>8} {'hbm Δ MB':>9} "
        f"{'cache':>6}  src"
    )
    lines = [header, "-" * len(header)]
    for r in rows:
        lines.append(
            f"{r['node'][:40]:<40} {r['calls']:>5} "
            f"{num(r.get('wall_ms')):>10} {num(r.get('device_wait_ms')):>9} "
            f"{num(r.get('flops'), 1e6):>10} "
            f"{num(r.get('bytes_accessed'), 1e6):>9} "
            f"{num(r.get('output_bytes'), 1e6):>8} "
            f"{num(r.get('hbm_delta_bytes'), 1e6):>9} "
            f"{r.get('cache_hits', 0):>6}  {r.get('provenance', 'measured')}"
        )
    return "\n".join(lines)


resource_profile = ResourceProfile()
metrics_registry.register("profile", resource_profile)

#: profile_scope() nesting depth, CONTEXT-local (contextvar, not a
#: process global): one thread's fit(profile=True) must not flip every
#: concurrently executing walk in the process into forced-profiling mode
#: (double-executing their nodes for the warmed re-time and persisting
#: store entries for unrelated graphs). The parallel walk copies its
#: build-thread context into each pool task, so nested estimator
#: sub-fits inside a profiled walk stay inside the scope.
_profile_force: "contextvars.ContextVar[int]" = contextvars.ContextVar(
    "keystone_profile_force", default=0
)


@contextmanager
def profile_scope():
    """Force per-node profiling on for the dynamic extent of one fit /
    apply (``Pipeline.fit(profile=True)``) in THIS context, yielding the
    process-wide ``ResourceProfile``. Nests; restores on exit."""
    token = _profile_force.set(_profile_force.get() + 1)
    try:
        yield resource_profile
    finally:
        _profile_force.reset(token)


def profile_forced() -> bool:
    """True inside an explicit ``profile_scope()`` (fit(profile=True) or
    a user scope) — the opt-in the profile store's per-apply auto-save
    keys on, distinct from ambient KEYSTONE_PROFILE=1 observation."""
    return bool(_profile_force.get())


def active_profile() -> Optional[ResourceProfile]:
    """The process-wide ``ResourceProfile``, or None when profiling is
    disabled (``config.profile`` / KEYSTONE_PROFILE off and no
    ``profile_scope()`` active in this context). Resolve ONCE per
    executor walk — the ``active_plan()`` discipline — so the unprofiled
    walk pays one None check per node."""
    from keystone_tpu.config import config

    if config.profile or _profile_force.get():
        return resource_profile
    return None


def achieved_tflops(fn: Callable, *args, repeats: int = 3) -> Dict[str, float]:
    """Compile, time, and convert to achieved TFLOPS (per process).

    One lowered/compiled executable serves both the timing loop and the
    FLOP count — lowering the function a second time through
    ``cost_analysis`` would double compile cost for the same HLO.
    """
    compiled = jax.jit(fn).lower(*args).compile()
    out = compiled(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = compiled(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / repeats
    flops = compiled_cost(compiled)["flops"]
    return {
        "seconds": dt,
        "flops": flops,
        "tflops": flops / dt / 1e12 if dt > 0 else 0.0,
    }


class CompileEventCounter:
    """Counts XLA backend compiles via ``jax.monitoring`` — each compile
    emits one compile-cache event. THE process's compile oracle, shared by
    the serving bench and the zero-post-warmup-compile tests so they can't
    drift apart if a jax upgrade renames the event. Listener registration
    is global and permanent: create one per process and snapshot
    ``.count`` around phases."""

    EVENT = "/jax/compilation_cache/compile_requests_use_cache"

    def __init__(self):
        self.count = 0
        jax.monitoring.register_event_listener(self._on_event)

    def _on_event(self, name, **kwargs):
        if name == self.EVENT:
            self.count += 1


class ServingCounters:
    """Process-wide serving observability: how many XLA compiles the
    bucketed apply path performed, and which buckets traffic actually
    lands in (the evidence behind 'zero steady-state recompiles' — after
    warmup the compile counter must not move). Thread-safe: the
    micro-batcher worker and client threads both record here."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.compiles = 0
            self.calls = 0
            self.rows_in = 0
            self.rows_padded = 0
            self.bucket_hits: Dict[int, int] = {}
            self.compiles_by_bucket: Dict[int, int] = {}

    def record_compile(self, bucket: int) -> None:
        with self._lock:
            self.compiles += 1
            # Per-bucket attribution: warmup evidence can then NAME which
            # bucket compiled instead of reporting an anonymous total.
            self.compiles_by_bucket[bucket] = (
                self.compiles_by_bucket.get(bucket, 0) + 1
            )

    def record_call(self, bucket: int, rows: int) -> None:
        with self._lock:
            self.calls += 1
            self.rows_in += rows
            self.rows_padded += bucket - rows
            self.bucket_hits[bucket] = self.bucket_hits.get(bucket, 0) + 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "compiles": self.compiles,
                "calls": self.calls,
                "rows_in": self.rows_in,
                "rows_padded": self.rows_padded,
                "pad_overhead": (
                    self.rows_padded / self.rows_in if self.rows_in else 0.0
                ),
                "bucket_hits": dict(sorted(self.bucket_hits.items())),
                "compiles_by_bucket": dict(
                    sorted(self.compiles_by_bucket.items())
                ),
            }


serving_counters = ServingCounters()
metrics_registry.register("serving", serving_counters)


class ReliabilityCounters(CounterSet):
    """Process-wide failure/recovery observability: every reliability event
    (utils/reliability.py and its call sites) lands here, so a chaos run
    can assert which recoveries fired and an operator can see whether a
    'healthy' fit was actually limping on retries. Thread-safe: producer
    threads, the serving worker, and client threads all record.

    Well-known keys (call sites may add more; snapshot returns whatever
    was bumped):

    - ``faults_injected_<site>`` — harness injections per FaultPlan site
    - ``io_retries`` / ``h2d_retries`` — transient-failure retries at the
      record boundary resp. the solvers' H2D step
    - ``records_quarantined`` — irrecoverably corrupt records skipped
    - ``producer_restarts`` / ``producer_leaks`` — prefetch producer
      threads restarted after silent death / still alive after the join
      timeout
    - ``oom_downshifts`` — chunks halved after repeated RESOURCE_EXHAUSTED
    - ``checkpoints_written`` / ``checkpoints_resumed`` /
      ``chunks_skipped_on_resume`` — streaming-solver snapshot traffic
    - ``requests_rejected`` / ``deadline_expired`` — serving fast-fail
      backpressure and expired-before-run requests
    - ``worker_restarts`` / ``futures_failed_on_close`` /
      ``futures_failed_on_worker_death`` — serving worker lifecycle
    - ``replica_deaths`` / ``replica_revivals`` /
      ``serve_groups_redispatched`` — serving replica-pool lifecycle (a
      dead replica's in-flight groups re-dispatch to survivors)
    """


reliability_counters = ReliabilityCounters()
metrics_registry.register("reliability", reliability_counters)


class ShardingCounters(CounterSet):
    """Process-wide data-parallel placement observability: every batch
    entering the graph (and every fused-chain lowering decision) lands
    here, so 'the fit ran data-parallel' is a counter assertion instead
    of a hope — the registry-verified 'no silent single-device cliff'
    gate of the multichip bench. Thread-safe (CounterSet).

    Well-known keys:

    - ``batches_sharded`` — divisible host batches row-sharded over the
      mesh at graph entry (DatasetOperator)
    - ``batches_deferred_pad`` — non-divisible host batches left to the
      fused chain's mask-pad path (placement deferred, NOT a fallback)
    - ``batches_padded`` / ``pad_rows_added`` — fused-chain calls that
      mask-padded a non-divisible batch onto the mesh, and how many
      zero rows the padding added in total
    - ``sharded_chain_calls`` — fused-chain executions lowered with the
      explicit SpecLayout shardings (vs inheriting input placement)
    - ``fallback_small_batch`` — batches below ``config.shard_min_rows``
      that genuinely ran single-device (the ONLY surviving fallback)
    - ``fallback_row_coupled`` — pad-unsound (row_independent=False)
      chains that kept the propagation path for a non-divisible batch
    - ``buffers_donated`` — staged chain inputs donated into the lowered
      chain (the buffer aliases an output; one live copy, not two)
    - ``donation_refused`` — staged calls under ``config.donate_buffers``
      where no output aval could alias the buffer (shrinking/growing
      chains): donation would be a warning and a no-op, so it is refused
      up front and counted instead of silently dropped
    - ``pallas_sharded_calls`` — sharded chain executions whose lowered
      body runs a Pallas kernel (``uses_pallas``) — the 'kernel actually
      active on the sharded path' evidence the ImageNet bench gates on
    """


sharding_counters = ShardingCounters()
metrics_registry.register("sharding", sharding_counters)


class ServePlanCounters(CounterSet):
    """Process-wide serve-planner observability: every memory-bounded
    serving decision lands here, so "the planner trimmed the ladder" is
    a counter assertion instead of a log line someone may have read —
    the no-silent-trim contract of the HBM-planned bucket ladder.
    Thread-safe (CounterSet).

    Well-known keys:

    - ``ladders_planned`` — ladder plans priced by the HBM planner at
      warmup: one per (engine, traffic signature) — a re-warm at a new
      feature shape/dtype re-prices and counts again
    - ``ladders_pinned`` — plans skipped because the ladder was explicit
      (buckets=, KEYSTONE_SERVE_BUCKETS, or config.serve_buckets — the
      env-pin-wins convention); per (engine, signature) like
      ``ladders_planned``
    - ``buckets_trimmed`` — ladder rungs dropped because their AOT-warmed
      executables could not coexist under the HBM headroom
    - ``top_bucket_capped`` — plans whose LARGEST rung was among the
      trims (oversize batches now chunk through a smaller top bucket)
    - ``plans_unpriced`` — plans skipped because no bytes-per-row could
      be priced (no measured profile and no abstract estimate)
    - ``plans_over_budget`` — plans still over budget after trimming to
      the minimum one-rung ladder (serving proceeds; KG104 flags it)
    - ``prefetch_clamped`` — session plans that clamped the hand-picked
      prefetch depth down against the budget share
    """


serve_plan_counters = ServePlanCounters()
metrics_registry.register("serve_plan", serve_plan_counters)


class OnlineCounters(CounterSet):
    """Process-wide online-learning observability: every incremental-fit
    decision (workflow/online.py) lands here, so "the model is current"
    is a counter assertion — folds happened, re-solves ran, refreshes
    reached the daemon — instead of a log line. Thread-safe (CounterSet);
    rides ``/metrics`` like every registry family.

    Well-known keys:

    - ``batches_folded`` — labeled batches folded into retained
      gram/AᵀB/mean accumulators (``OnlineState.fold``; both the trainer
      path and direct ``partial_fit`` calls)
    - ``resolves`` — cheap re-solves of the retained state through the
      Cholesky path (``OnlineState.solve``)
    - ``refreshes_pushed`` — completed trainer refreshes: re-solve +
      versioned artifact + (when wired) daemon hot-swap
    - ``refreshes_failed`` — refreshes that died anywhere (fault sites,
      failed swap, full disk): serving keeps the old generation, the
      accumulators are untouched, the next cadence tick retries
    - ``windows_evicted`` — sliding-window units whose sums were
      subtracted from the running totals (subtract-on-evict)
    - ``full_refits`` — ``Pipeline.refit_stream`` cadence ticks that
      fell back to a FULL head refit because the head estimator lacks
      ``partial_fit`` (the KG105 hazard, counted at runtime)
    - ``batches_buffered`` — batches a partial_fit-less
      ``refit_stream`` buffered for those full refits (distinct from
      ``batches_folded``: nothing reached retained accumulators)
    """


online_counters = OnlineCounters()
metrics_registry.register("online", online_counters)


class ElasticCounters(CounterSet):
    """Process-wide elastic-mesh observability: every durable-state
    migration across a mesh-width change (``utils.mesh.reshard_state``)
    lands here, so "the resume was migrated, not refused and not
    silently restarted" is a counter assertion — the never-silent half
    of the ``KEYSTONE_ELASTIC_MESH`` contract. Thread-safe (CounterSet);
    rides ``/metrics`` like every registry family.

    Well-known keys:

    - ``states_migrated`` — total successful ``reshard_state``
      migrations, any family
    - ``stream_solve_migrated`` — chunked-solve snapshots
      (``solve_least_squares_chunked`` checkpoints) re-manifested onto a
      new mesh width
    - ``bcd_epoch_migrated`` / ``bcd_stream_migrated`` — BCD epoch
      checkpoints (orbax) and mid-epoch block snapshots whose residual
      was re-padded and manifest rewritten
    - ``online_state_migrated`` — ``OnlineState`` snapshots resumed
      across a width change
    - ``profile_migrated`` — profile-store entries whose per-shard rows
      were re-scaled onto the new width
    - ``migrations_refused`` — same-problem/different-mesh state that
      could NOT be migrated (torn/partial per-shard payload, unknown
      family): kept the typed ``MeshMismatchError`` refusal
    """


elastic_counters = ElasticCounters()
metrics_registry.register("elastic", elastic_counters)


class TelemetryCounters(CounterSet):
    """Process-wide telemetry-pipeline observability: every durable-
    export decision (utils/telemetry.py TelemetryLog) and every loss
    the in-memory rings take lands here — telemetry that silently
    loses data is worse than none, so the losses themselves are
    first-class counters riding ``/metrics`` like every registry
    family. Thread-safe (CounterSet).

    Well-known keys:

    - ``records_enqueued`` — journeys/span-tree records accepted onto
      the writer queue
    - ``records_written`` — records the writer thread landed on disk
    - ``records_dropped`` — records lost WITHOUT blocking: queue full,
      log closed, or a write error (the never-blocks-admission
      contract, measured)
    - ``segments_rotated`` — size-triggered segment rotations
    - ``segments_pruned`` — rotated segments deleted by bounded
      retention (``KEYSTONE_TELEMETRY_KEEP``)
    - ``journeys_evicted`` — FlightRecorder journey-ring evictions: a
      resolved-but-unexported journey pushed out by ring capacity
      (the flight-recorder half of the no-silent-loss satellite;
      ``Tracer.dropped`` rides the ``tracer`` gauges)
    """


telemetry_counters = TelemetryCounters()
metrics_registry.register("telemetry", telemetry_counters)
metrics_registry.register("tracer", _TracerLoss())


class CapacityCounters(CounterSet):
    """Process-wide learned-capacity-model observability
    (workflow/capacity.py and its three consumers): every refusal,
    coalesce, and re-plan the model drives is a counted decision —
    nothing the model does to traffic is silent. Thread-safe
    (CounterSet).

    Well-known keys:

    - ``predicted_refusals`` — requests 429'd because the model
      predicted their completion past the deadline
      (``predicted_infeasible``), before any device work
    - ``microbatches_formed`` — gold-anchored flush groups that
      absorbed at least one best-effort request into padding slack
    - ``microbatch_rows_filled`` — best-effort rows served inside
      gold groups' pad slack (free device time, measured)
    - ``replans`` — autoscale re-plans executed (mix shift past the
      threshold; decision-logged in the optimizer ring)
    - ``replans_suppressed`` — re-plans refused by the no-flap guard
      (a second trigger inside the re-plan window)
    - ``replicas_resized`` — replica-pool grow/shrink operations the
      re-plan loop performed
    - ``model_cold_skips`` — consumer consultations that no-op'd
      because the model had fewer than ``KEYSTONE_CAPACITY_MIN_SAMPLES``
      journeys (the cold contract, measured)
    - ``guard_violations`` — strict-accuracy guard hits: a refusal the
      matured model would call feasible (a bug gate, not a tuning knob)
    """


capacity_counters = CapacityCounters()
metrics_registry.register("capacity", capacity_counters)
