"""Observability: stage timers and XLA cost introspection.

Ref: the reference's `Logging` trait with per-stage wall times in pipeline
mains + Spark metrics (SURVEY.md §5 metrics row) [unverified]. Here:
structured stage timing plus FLOP/byte counts straight from the compiled
HLO (`cost_analysis`), which is what per-chip TFLOPS reporting uses.
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict

import jax

logger = logging.getLogger("keystone_tpu")


@contextmanager
def stage_timer(name: str, sink: Dict[str, float] | None = None):
    """Logs (and optionally records) the wall time of a pipeline stage."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        logger.info("stage=%s seconds=%.4f", name, dt)
        if sink is not None:
            sink[name] = dt


def compiled_cost(compiled) -> Dict[str, Any]:
    """FLOPs / bytes-accessed of an already-compiled executable."""
    cost = compiled.cost_analysis() or {}
    # Older jax returns a one-element list of dicts (per-executable);
    # newer returns the dict directly.
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "raw": dict(cost),
    }


def cost_analysis(fn: Callable, *args) -> Dict[str, Any]:
    """FLOPs / bytes-accessed of `fn` as XLA compiles it for these args."""
    return compiled_cost(jax.jit(fn).lower(*args).compile())


@contextmanager
def maybe_trace(tag: str):
    """Capture a jax profiler trace when KEYSTONE_PROFILE_DIR is set — the
    tensorboard-consumable artifact for MXU-utilization work on hardware.
    No-op (zero overhead) when the knob is absent."""
    import os

    out = os.environ.get("KEYSTONE_PROFILE_DIR")
    if not out:
        yield
        return
    path = os.path.join(out, tag)
    with jax.profiler.trace(path):
        yield
    logger.info("profiler trace written to %s", path)


def device_hbm_bytes(default: int | None = None) -> int:
    """Memory budget of device 0 as the runtime reports it (``bytes_limit``
    from ``memory_stats``), falling back to ``config.hbm_budget_bytes`` for
    backends that don't report one (notably CPU)."""
    from keystone_tpu.config import config

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        if limit:
            return int(limit)
    except Exception:
        pass
    return default if default is not None else config.hbm_budget_bytes


def peak_hbm_bytes() -> int | None:
    """HBM high-water of device 0 (``peak_bytes_in_use``), or None where
    the runtime doesn't report it (notably CPU). Shared by the
    single-number evidence rows (bench line, streamed-overlap step); the
    checkride ``memory_stats`` step deliberately keeps its own multi-key
    probe — it exists to record the runtime's whole key set, including
    whatever a different runtime names the peak."""
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        return None
    peak = stats.get("peak_bytes_in_use")
    return int(peak) if peak is not None else None


def achieved_tflops(fn: Callable, *args, repeats: int = 3) -> Dict[str, float]:
    """Compile, time, and convert to achieved TFLOPS (per process).

    One lowered/compiled executable serves both the timing loop and the
    FLOP count — lowering the function a second time through
    ``cost_analysis`` would double compile cost for the same HLO.
    """
    compiled = jax.jit(fn).lower(*args).compile()
    out = compiled(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = compiled(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / repeats
    flops = compiled_cost(compiled)["flops"]
    return {
        "seconds": dt,
        "flops": flops,
        "tflops": flops / dt / 1e12 if dt > 0 else 0.0,
    }


class CompileEventCounter:
    """Counts XLA backend compiles via ``jax.monitoring`` — each compile
    emits one compile-cache event. THE process's compile oracle, shared by
    the serving bench and the zero-post-warmup-compile tests so they can't
    drift apart if a jax upgrade renames the event. Listener registration
    is global and permanent: create one per process and snapshot
    ``.count`` around phases."""

    EVENT = "/jax/compilation_cache/compile_requests_use_cache"

    def __init__(self):
        self.count = 0
        jax.monitoring.register_event_listener(self._on_event)

    def _on_event(self, name, **kwargs):
        if name == self.EVENT:
            self.count += 1


class ServingCounters:
    """Process-wide serving observability: how many XLA compiles the
    bucketed apply path performed, and which buckets traffic actually
    lands in (the evidence behind 'zero steady-state recompiles' — after
    warmup the compile counter must not move). Thread-safe: the
    micro-batcher worker and client threads both record here."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.compiles = 0
            self.calls = 0
            self.rows_in = 0
            self.rows_padded = 0
            self.bucket_hits: Dict[int, int] = {}

    def record_compile(self, bucket: int) -> None:
        with self._lock:
            self.compiles += 1

    def record_call(self, bucket: int, rows: int) -> None:
        with self._lock:
            self.calls += 1
            self.rows_in += rows
            self.rows_padded += bucket - rows
            self.bucket_hits[bucket] = self.bucket_hits.get(bucket, 0) + 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "compiles": self.compiles,
                "calls": self.calls,
                "rows_in": self.rows_in,
                "rows_padded": self.rows_padded,
                "pad_overhead": (
                    self.rows_padded / self.rows_in if self.rows_in else 0.0
                ),
                "bucket_hits": dict(sorted(self.bucket_hits.items())),
            }


serving_counters = ServingCounters()


class ReliabilityCounters:
    """Process-wide failure/recovery observability: every reliability event
    (utils/reliability.py and its call sites) lands here, so a chaos run
    can assert which recoveries fired and an operator can see whether a
    'healthy' fit was actually limping on retries. Thread-safe: producer
    threads, the serving worker, and client threads all record.

    Well-known keys (call sites may add more; snapshot returns whatever
    was bumped):

    - ``faults_injected_<site>`` — harness injections per FaultPlan site
    - ``io_retries`` / ``h2d_retries`` — transient-failure retries at the
      record boundary resp. the solvers' H2D step
    - ``records_quarantined`` — irrecoverably corrupt records skipped
    - ``producer_restarts`` / ``producer_leaks`` — prefetch producer
      threads restarted after silent death / still alive after the join
      timeout
    - ``oom_downshifts`` — chunks halved after repeated RESOURCE_EXHAUSTED
    - ``checkpoints_written`` / ``checkpoints_resumed`` /
      ``chunks_skipped_on_resume`` — streaming-solver snapshot traffic
    - ``requests_rejected`` / ``deadline_expired`` — serving fast-fail
      backpressure and expired-before-run requests
    - ``worker_restarts`` / ``futures_failed_on_close`` /
      ``futures_failed_on_worker_death`` — serving worker lifecycle
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def get(self, key: str) -> int:
        with self._lock:
            return self._counts.get(key, 0)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(sorted(self._counts.items()))


reliability_counters = ReliabilityCounters()
