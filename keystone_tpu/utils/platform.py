"""Platform selection for CLI entry points.

`KEYSTONE_PLATFORM=cpu|axon|tpu` forces the JAX platform. Needed because the
axon sitecustomize force-registers the TPU plugin regardless of
JAX_PLATFORMS; config.update after import is the reliable switch.
"""

from __future__ import annotations

import os


def setup_platform() -> None:
    plat = os.environ.get("KEYSTONE_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
