"""Platform selection for CLI entry points.

`KEYSTONE_PLATFORM=cpu|axon|tpu` forces the JAX platform. Needed because the
axon sitecustomize force-registers the TPU plugin regardless of
JAX_PLATFORMS; config.update after import is the reliable switch.
"""

from __future__ import annotations

import os


def setup_platform() -> None:
    plat = os.environ.get("KEYSTONE_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    from keystone_tpu.config import config, env_flag

    if config.debug_nans or env_flag("KEYSTONE_DEBUG_NANS"):
        import jax

        jax.config.update("jax_debug_nans", True)
    # Multi-host rendezvous when the env knobs are present (no-op otherwise).
    from keystone_tpu.utils import distributed

    distributed.initialize()
