"""Platform selection for CLI entry points.

`KEYSTONE_PLATFORM=cpu|axon|tpu` forces the JAX platform. Needed because the
axon sitecustomize force-registers the TPU plugin regardless of
JAX_PLATFORMS; config.update after import is the reliable switch.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Optional


def force_cpu() -> None:
    """Pin this process to the CPU platform. config.update (not env) because
    the sitecustomize-registered TPU plugin ignores JAX_PLATFORMS, and the
    backend is MATERIALIZED immediately: left lazy, the axon get_backend
    wrapper can still initialize the TPU plugin at the first jit lowering —
    a minutes-long hang when the chip is dead (the conftest does the same
    devices() touch for the same reason)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.devices()


def env_forces_cpu() -> bool:
    """True when the ambient env asks for CPU (either spelling)."""
    return (
        os.environ.get("KEYSTONE_PLATFORM") == "cpu"
        or os.environ.get("JAX_PLATFORMS") == "cpu"
    )


def parse_json_line(text: str) -> Optional[dict]:
    """Last parseable JSON object line of ``text`` (subprocess stdout may
    carry log noise around the one structured line)."""
    for line in reversed(text.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(parsed, dict):
            return parsed
    return None


def probe_backend(timeout: float = 120.0) -> Optional[dict]:
    """Check the ambient default JAX backend is *alive* without risking a hang.

    The TPU tunnel in this environment can die mid-session, after which any
    device op (even backend init) blocks forever. Running a tiny jitted op in
    a subprocess with a hard timeout is the only safe liveness test — the
    parent process never touches the suspect backend.

    Returns ``{"platform": str, "n": int}`` on success, ``None`` when the
    backend is dead, hung, or errors out.
    """
    code = (
        "import json, jax, jax.numpy as jnp\n"
        "x = (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()\n"
        "d = jax.devices()\n"
        "print(json.dumps({'platform': d[0].platform, 'n': len(d)}))\n"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except (subprocess.TimeoutExpired, OSError):
        return None
    if out.returncode != 0:
        return None
    info = parse_json_line(out.stdout)
    return info if info is not None and "platform" in info else None


def ensure_live_backend(timeout: float = 120.0) -> str:
    """Probe the ambient backend; fall back to CPU if it is dead or hung.

    Must run before this process initializes any JAX backend (config.update
    has no effect afterwards). Returns the platform this process will use.
    """
    if env_forces_cpu():
        force_cpu()
        return "cpu"
    info = probe_backend(timeout=timeout)
    if info is None:
        force_cpu()
        return "cpu"
    return str(info["platform"])


def cpu_mesh_env(n_devices: int, base: Optional[dict] = None) -> dict:
    """Env for a subprocess that must see ``n_devices`` virtual CPU devices.

    XLA_FLAGS must precede backend init, hence a fresh env rather than
    in-process mutation; KEYSTONE_PLATFORM=cpu makes the child's own
    config.update defeat the sitecustomize-forced TPU plugin.
    """
    import re

    env = dict(base if base is not None else os.environ)
    flags = env.get("XLA_FLAGS", "")
    # Replace any existing count with max(existing, n_devices) — keeping a
    # smaller leftover count would hand the child too few devices.
    pat = r"--xla_force_host_platform_device_count=(\d+)"
    m = re.search(pat, flags)
    if m:
        count = max(int(m.group(1)), n_devices)
        flags = re.sub(pat, f"--xla_force_host_platform_device_count={count}", flags)
    else:
        flags = (flags + f" --xla_force_host_platform_device_count={n_devices}").strip()
    env["XLA_FLAGS"] = flags
    env["JAX_PLATFORMS"] = "cpu"
    env["KEYSTONE_PLATFORM"] = "cpu"
    return env


def setup_platform() -> None:
    plat = os.environ.get("KEYSTONE_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    # Persistent XLA compilation cache: first TPU compiles run 20-40s; with
    # this set, repeat launches load the compiled executable from disk.
    compile_cache = os.environ.get("KEYSTONE_COMPILE_CACHE")
    if compile_cache:
        import jax

        jax.config.update("jax_compilation_cache_dir", compile_cache)
    from keystone_tpu.config import config, env_flag

    if config.debug_nans or env_flag("KEYSTONE_DEBUG_NANS"):
        import jax

        jax.config.update("jax_debug_nans", True)
    if env_flag("KEYSTONE_AUTO_CACHE"):
        config.auto_cache = True
    # Multi-host rendezvous when the env knobs are present (no-op otherwise).
    from keystone_tpu.utils import distributed

    distributed.initialize()
