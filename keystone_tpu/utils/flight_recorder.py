"""Black-box flight recorder: always-on per-request journey forensics.

The PR-4 tracer answers "where does time go" — but it must be armed
before the incident, and its spans are anonymous aggregates once a
request has been coalesced into a flush group. This module is the other
half of production observability: an ALWAYS-ON, lock-light bounded ring
of per-request journey records (id, rows, bucket, replica(s), per-phase
timestamps, final outcome) plus a last-N ring of error events, cheap
enough to leave running under full traffic and dumped to JSON when
something goes wrong — so the first deadline storm or replica death on a
box nobody was watching still leaves a post-mortem artifact behind.

Concurrency model (the "lock-light" part): the recorder's lock guards
only ring membership and dump bookkeeping. ``FlightRecord`` fields are
written WITHOUT the recorder lock by whichever thread currently owns the
request — ownership hands off through the serving locks (submit ->
dispatcher -> completer), which gives the stamps happens-before ordering;
a dump reads records without quiescing writers, so a record mid-flight
serializes exactly as far as its journey has progressed. That is a
feature: the dump taken at the moment of a stall shows WHERE each
request was stuck.

Dump triggers (``PipelineService`` wires these):

- ``worker_death`` / ``replica_death`` — the reliability events;
- ``deadline_storm`` — >= ``config.serve_storm_expired`` requests expired
  within one second;
- ``stall`` — the service's watchdog thread saw a non-empty pending
  queue make no dispatch progress for ``KEYSTONE_WATCHDOG_MS``;
- ``debug`` — an explicit ``PipelineService.debug_dump()``.

Triggers fired under a serving lock only mark the dump pending
(``note_dump``); the actual file write happens at the next ``poll()``
from a safe (unlocked) point — submit exit, a completer's group
boundary, or the watchdog tick — so forensics never add file I/O to a
critical section. Repeat dumps for one reason are rate-limited
(``MIN_DUMP_INTERVAL_S``); ``debug_dump`` bypasses the limit.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("keystone_tpu")

#: One process-wide monotonic request-id sequence: ids minted at
#: ``PipelineService.submit`` and ``CompiledPipeline.call_async`` share
#: it, so an id is unique across every engine/service in the process and
#: orders submissions.
_req_seq = itertools.count(1)


def next_request_id() -> int:
    """Mint the next process-wide monotonic request id."""
    return next(_req_seq)


def derive_health(stats: Dict[str, Any]) -> Tuple[bool, Dict[str, Any]]:
    """(healthy, /healthz body) from a health-source stats dict — THE
    one health rule, applied identically by the serving daemon's own
    /healthz (workflow/daemon.py) and a ``tools/metrics_server.py``
    pointed at ``daemon.health_stats``, so the two surfaces can never
    disagree about the same service. Unhealthy when the worker died,
    the service closed, OR a hot-swap is mid-drain (``draining: true``
    tells load balancers to stop sending traffic early). Generation
    identity fields surface at the top level. Lives here, next to the
    journey machinery, because health derivation is pure dict logic
    that both the daemon (workflow/daemon.py) and the metrics sidecar
    (tools/metrics_server.py) must share — one source, no drift."""
    healthy = (
        bool(stats.get("worker_alive", True))
        and not bool(stats.get("closed", False))
        and not bool(stats.get("draining", False))
    )
    doc: Dict[str, Any] = {"healthy": healthy}
    for key in ("generation", "artifact_fingerprint", "draining"):
        if key in stats:
            doc[key] = stats[key]
    doc["stats"] = stats
    return healthy, doc


class FlightRecord:
    """One request's journey: phase stamps appended in flight, serialized
    whole at dump time. Single-writer by ownership handoff (see module
    docstring) — no lock of its own.

    ``first_phase`` names the journey's opening stamp: ``submitted`` for
    in-process service requests (the default), ``accepted`` for daemon
    ingress journeys whose network leg starts at the socket. ``meta``
    (via :meth:`note`) carries transport attributes — tenant, SLA tier,
    generation, HTTP status — without widening the stamp schema."""

    __slots__ = ("rid", "rows", "bucket", "replicas", "phases", "outcome",
                 "meta")

    def __init__(self, rid: int, rows: int, first_phase: str = "submitted"):
        self.rid = rid
        self.rows = rows
        self.bucket: Optional[int] = None
        self.replicas: List[int] = []
        self.phases: List[Tuple[str, int]] = [
            (first_phase, time.perf_counter_ns())
        ]
        self.outcome: Optional[str] = None
        self.meta: Optional[Dict[str, Any]] = None

    def stamp(self, phase: str) -> None:
        """Append a (phase, perf_counter_ns) stamp. Phases repeat when a
        journey loops (a re-dispatched request is flushed twice)."""
        self.phases.append((phase, time.perf_counter_ns()))

    def dispatched(self, replica: int, bucket: Optional[int]) -> None:
        """Stamp the launch onto a replica; re-dispatches append, so the
        record names EVERY replica that ever held this request."""
        self.replicas.append(int(replica))
        if bucket is not None:
            self.bucket = int(bucket)
        self.stamp("dispatched")

    def finish(self, outcome: str) -> None:
        self.outcome = outcome
        self.stamp("resolved")

    def note(self, **attrs: Any) -> None:
        """Attach transport metadata (tenant, tier, generation, status)
        to the journey; repeat calls merge. Copy-on-write: a concurrent
        ``snapshot()``/``dump()`` copies ``meta``, and inserting a key
        into the dict it is iterating would raise RuntimeError mid-dump
        — the lock-light torn-read contract covers append-only lists,
        so the dict must be swapped atomically instead of mutated."""
        merged = dict(self.meta) if self.meta else {}
        merged.update(attrs)
        self.meta = merged

    def as_dict(self) -> Dict[str, Any]:
        d = {
            "id": self.rid,
            "rows": self.rows,
            "bucket": self.bucket,
            "replicas": list(self.replicas),
            "phases": [
                {"phase": p, "t_ns": t} for p, t in list(self.phases)
            ],
            "outcome": self.outcome,
        }
        meta = self.meta  # one read: note() swaps the reference
        if meta:
            d["meta"] = dict(meta)
        return d


class FlightRecorder:
    """The bounded journey ring + error-event ring + dump machinery for
    one service instance."""

    #: Floor between two auto-dumps for the SAME reason: a storm must
    #: leave one artifact, not a thousand.
    MIN_DUMP_INTERVAL_S = 5.0

    #: Last-N error events kept alongside the journey ring.
    ERROR_CAPACITY = 256

    #: Most recent dump paths remembered (the rings are bounded; the
    #: dump history must be too — a service degraded for days would
    #: otherwise grow this into every stats()/healthz payload).
    DUMP_HISTORY = 64

    def __init__(
        self,
        name: str,
        capacity: Optional[int] = None,
        directory: Optional[str] = None,
        context: Optional[Callable[[], dict]] = None,
    ):
        from keystone_tpu.config import config

        self.name = name
        self.capacity = int(
            config.flight_records if capacity is None else capacity
        )
        if self.capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {self.capacity}")
        # capacity 0 = the journey ring is off (the repo-wide 0=disabled
        # env convention for KEYSTONE_FLIGHT_RECORDS): deque(maxlen=0)
        # makes every append a no-op while error events and dumps keep
        # working.
        self.directory = (
            directory if directory is not None
            else (config.flight_dir or tempfile.gettempdir())
        )
        self._context = context
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=self.capacity)
        self._errors: deque = deque(maxlen=self.ERROR_CAPACITY)
        self._pending_reason: Optional[str] = None
        self._last_dump: Dict[str, float] = {}
        self._dump_seq = itertools.count()
        self._dumps: deque = deque(maxlen=self.DUMP_HISTORY)
        self.dumps_total = 0
        self.records_started = 0
        self.evictions = 0

    @property
    def dumps(self) -> List[str]:
        """The most recent ``DUMP_HISTORY`` dump paths, oldest first."""
        with self._lock:
            return list(self._dumps)

    # -- recording (the hot path) ------------------------------------------

    def start(self, rid: int, rows: int,
              first_phase: str = "submitted") -> FlightRecord:
        """Open one request's journey record and enter it in the ring.
        The record is mutated in place as the request progresses; the
        ring holds the reference, so in-flight requests are visible to a
        dump exactly as far as they got. ``first_phase`` names the
        opening stamp (daemon ingress journeys start at ``accepted``)."""
        rec = FlightRecord(rid, rows, first_phase=first_phase)
        self.add(rec)
        return rec

    def add(self, rec) -> None:
        """Enter an externally-built journey record (anything with an
        ``as_dict()``) in the ring — solver journeys (``SolveRecord``)
        ride the same ring/dump machinery as serving requests."""
        with self._lock:
            # A full ring evicts its oldest journey on append: counted
            # per recorder AND process-wide (telemetry family) so the
            # loss is scrape-able — telemetry that silently loses data
            # is worse than none. capacity 0 (the ring knowingly off)
            # is not an eviction.
            evicted = (
                self.capacity > 0 and len(self._records) == self.capacity
            )
            if evicted:
                self.evictions += 1
            self._records.append(rec)
            self.records_started += 1
        if evicted:
            from keystone_tpu.utils.metrics import telemetry_counters

            telemetry_counters.bump("journeys_evicted")

    def error(self, kind: str, message: str,
              rid: Optional[int] = None) -> None:
        """Append one error event to the last-N ring."""
        with self._lock:
            self._errors.append({
                "kind": kind,
                "message": str(message)[:500],
                "req_id": rid,
                "t_ns": time.perf_counter_ns(),
            })

    # -- dumping -----------------------------------------------------------

    def note_dump(self, reason: str) -> None:
        """Mark a dump pending. Safe under any serving lock — the file
        write happens at the next ``poll()`` from an unlocked point.
        First reason wins until it is flushed."""
        with self._lock:
            if self._pending_reason is None:
                self._pending_reason = reason

    def poll(self) -> Optional[str]:
        """Flush a pending dump, if any (call from UNLOCKED points only:
        submit exit, completer group boundary, watchdog tick). Returns
        the path written, or None."""
        # Lock-free fast path: poll sits on the client-facing submit
        # path, and a pending dump is vanishingly rare. The racy read is
        # benign — a flag set concurrently is caught by the next poll
        # point (the watchdog tick guarantees one).
        if self._pending_reason is None:
            return None
        with self._lock:
            reason = self._pending_reason
            self._pending_reason = None
        if reason is None:
            return None
        return self.dump(reason)

    def snapshot(self) -> Dict[str, Any]:
        """The rings as plain data (journeys serialized as far as they
        got — see the module docstring on torn reads)."""
        with self._lock:
            records = list(self._records)
            errors = list(self._errors)
        return {
            "service": self.name,
            "capacity": self.capacity,
            "records_started": self.records_started,
            "records": [r.as_dict() for r in records],
            "errors": errors,
        }

    def dump(self, reason: str, path: Optional[str] = None,
             force: bool = False) -> Optional[str]:
        """Write the black box to JSON. Rate-limited per reason unless
        ``force``; returns the path written (None when rate-limited).
        Never raises: a forensics path that throws during the incident it
        exists to record would destroy the evidence AND the service."""
        now = time.perf_counter()
        with self._lock:
            if not force:
                last = self._last_dump.get(reason)
                if last is not None and now - last < self.MIN_DUMP_INTERVAL_S:
                    return None
            seq = next(self._dump_seq)
        doc = self.snapshot()
        doc["reason"] = reason
        # lint: ok(KL005) forensic artifact carries a real wall-clock timestamp
        doc["unix_time"] = time.time()
        try:
            if self._context is not None:
                doc["stats"] = self._context()
        except Exception as e:  # lint: broad-ok a half-closed service's stats must not kill the dump
            doc["stats_error"] = str(e)[:200]
        if path is None:
            fname = (
                f"keystone_flight_{self.name}_{reason}_"
                f"{os.getpid()}_{seq}.json"
            )
            path = os.path.join(self.directory, fname)
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, path)
        except OSError as e:
            # The rate-limit slot is NOT consumed on a failed write: a
            # transient disk error must not suppress the retry that would
            # have captured the incident.
            logger.warning("flight recorder dump to %s failed: %s", path, e)
            return None
        with self._lock:
            self._last_dump[reason] = now
            self._dumps.append(path)
            self.dumps_total += 1
        logger.warning(
            "flight recorder %s: dumped %d record(s) / %d error event(s) "
            "to %s (reason=%s)",
            self.name, len(doc["records"]), len(doc["errors"]), path, reason,
        )
        return path

    def stats(self) -> Dict[str, Any]:
        """Small health-surface summary (NOT the rings themselves)."""
        with self._lock:
            return {
                "records_held": len(self._records),
                "records_started": self.records_started,
                "records_evicted": self.evictions,
                "errors_held": len(self._errors),
                "dumps": list(self._dumps),
                "dumps_total": self.dumps_total,
                "pending_dump": self._pending_reason,
            }


# ---------------------------------------------------------------------------
# Solver progress: per-solve journeys, health surface, stall watchdog
# ---------------------------------------------------------------------------


class SolveRecord:
    """One streaming solve's journey: unit (chunk/block) progress, rates,
    checkpoint age, and a bounded ring of structured progress events.
    Mutated only by its owning ``ProgressReporter`` (under the reporter's
    lock); serialized whole at dump time — a record mid-solve serializes
    exactly as far as the solve got, per the module's torn-read contract,
    which is what makes a mid-fit death dump name the last completed
    unit."""

    __slots__ = ("rid", "kind", "total_units", "units_done", "rows_done",
                 "started_ns", "last_progress_ns", "oom_downshifts",
                 "checkpoint_unit", "checkpoint_ns", "residual", "outcome",
                 "stalls", "events", "fingerprint")

    #: Most recent structured progress events kept per solve.
    EVENT_CAPACITY = 128

    def __init__(self, rid: int, kind: str,
                 total_units: Optional[int] = None,
                 fingerprint: Optional[dict] = None):
        now = time.perf_counter_ns()
        self.rid = rid
        self.kind = kind
        self.total_units = total_units
        self.units_done = 0
        self.rows_done = 0
        self.started_ns = now
        self.last_progress_ns = now
        self.oom_downshifts = 0
        self.checkpoint_unit: Optional[int] = None
        self.checkpoint_ns: Optional[int] = None
        self.residual: Optional[float] = None
        self.outcome: Optional[str] = None
        self.stalls = 0
        self.events: deque = deque(maxlen=self.EVENT_CAPACITY)
        self.fingerprint = dict(fingerprint or {})

    def progress(self) -> Dict[str, Any]:
        """Derived progress numbers (rates, ETA, ages). Caller holds the
        reporter's lock when consistency matters."""
        now = time.perf_counter_ns()
        elapsed = max(1e-9, (now - self.started_ns) / 1e9)
        units_per_s = self.units_done / elapsed
        eta = None
        if self.total_units and self.units_done:
            eta = (self.total_units - self.units_done) / max(
                units_per_s, 1e-9
            )
        return {
            "units_done": self.units_done,
            "total_units": self.total_units,
            "rows_done": self.rows_done,
            "rows_per_s": round(self.rows_done / elapsed, 3),
            "eta_s": round(eta, 3) if eta is not None else None,
            "elapsed_s": round(elapsed, 6),
            "last_progress_age_s": round(
                (now - self.last_progress_ns) / 1e9, 6
            ),
            "oom_downshifts": self.oom_downshifts,
            "checkpoint_unit": self.checkpoint_unit,
            "checkpoint_age_s": (
                round((now - self.checkpoint_ns) / 1e9, 6)
                if self.checkpoint_ns is not None else None
            ),
            "residual": self.residual,
            "stalls": self.stalls,
        }

    def as_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "id": self.rid,
            "kind": self.kind,
            "outcome": self.outcome,
            "fingerprint": dict(self.fingerprint),
        }
        d.update(self.progress())
        d["events"] = list(self.events)
        return d


_solves_lock = threading.Lock()
_active_solves: Dict[int, "ProgressReporter"] = {}
_solver_recorder: Optional[FlightRecorder] = None


def solver_recorder() -> FlightRecorder:
    """The process-wide flight recorder for streaming solves (one ring
    shared by every solve, dump context = ``solver_stats``), built
    lazily so config (flight dir/capacity) is read at first solve."""
    global _solver_recorder
    with _solves_lock:
        if _solver_recorder is None:
            _solver_recorder = FlightRecorder("solver", context=solver_stats)
        return _solver_recorder


def reset_solver_recorder() -> None:
    """Drop the solver recorder so the next solve builds a fresh one
    under the current config (tests point KEYSTONE_FLIGHT_DIR / a
    config.flight_dir override at a tmpdir)."""
    global _solver_recorder
    with _solves_lock:
        _solver_recorder = None


def solver_stats() -> Dict[str, Any]:
    """The ``stats()``-style health surface for streaming solves: every
    in-flight solve's progress (units/rows done, rates, ETA, checkpoint
    age, stall count) plus the solver recorder's ring/dump summary —
    what ``tools/metrics_server.py`` serves at ``/solves``."""
    with _solves_lock:
        active = list(_active_solves.values())
        rec = _solver_recorder
    return {
        "active_solves": len(active),
        "solves": [r.stats() for r in active],
        "recorder": rec.stats() if rec is not None else None,
    }


class ProgressReporter:
    """Structured progress + stall forensics for ONE streaming solve.

    Always-on, like the serving flight recorder: the solver calls
    ``unit_done`` once per chunk/block — one locked counter update plus a
    bounded-ring event append every ``KEYSTONE_SOLVE_PROGRESS_EVERY``
    units — and the journey (a ``SolveRecord``) lives in the process-wide
    solver ``FlightRecorder`` ring, so an hour-scale fit is observable
    (``solver_stats()`` / the ``/solves`` endpoint: units, rows/s, ETA,
    oom_downshifts, checkpoint age) and a solve that dies mid-fit
    force-dumps a post-mortem naming the last completed unit, exactly
    like a dead serving worker.

    A per-solve watchdog thread (``KEYSTONE_SOLVE_WATCHDOG_MS``, 0 = off)
    fires when no unit completes inside the window — a dead producer or a
    wedged device queue becomes a ``solve_stalls`` counter bump plus an
    auto-dump instead of a silent hang; each tick is also an unlocked
    flush point for pending recorder dumps.

    Use as a context manager around the solve loop: clean exit stamps
    outcome ``ok``; an exception stamps ``error:<type>`` and dumps."""

    def __init__(self, kind: str, total_units: Optional[int] = None,
                 recorder: Optional[FlightRecorder] = None,
                 watchdog_ms: Optional[float] = None,
                 progress_every: Optional[int] = None):
        from keystone_tpu.config import config
        from keystone_tpu.utils.metrics import (
            metrics_registry,
            reliability_counters,
            runtime_fingerprint,
        )

        self.kind = kind
        self.recorder = solver_recorder() if recorder is None else recorder
        self._watchdog_s = (
            config.solve_watchdog_ms if watchdog_ms is None else watchdog_ms
        ) / 1e3
        self._every = max(1, int(
            config.solve_progress_every if progress_every is None
            else progress_every
        ))
        self.rid = next_request_id()
        self.record = SolveRecord(
            self.rid, kind, total_units, fingerprint=runtime_fingerprint()
        )
        self._lock = threading.Lock()
        self._done = False
        self._stop = threading.Event()
        # Re-arm stamp for the stall watchdog, SEPARATE from the
        # record's last_progress_ns: rate-limiting stall dumps must not
        # falsify the journey's real last-progress age on /solves.
        self._last_stall_ns = self.record.started_ns
        # oom_downshifts attribution is the process counter's delta since
        # solve start (concurrent downshifting solves share attribution —
        # the honest cheap reading).
        self._reliability = reliability_counters
        self._oom0 = reliability_counters.get("oom_downshifts")
        self._events_counter = metrics_registry.counters("solver.events")
        self._units_gauge = metrics_registry.gauge(
            f"solve.units_done[{kind}]"
        )
        self.recorder.add(self.record)
        with _solves_lock:
            _active_solves[self.rid] = self
        self._watchdog: Optional[threading.Thread] = None
        if self._watchdog_s > 0:
            self._watchdog = threading.Thread(
                target=self._solve_watch_loop,
                name=f"keystone-solve-watchdog-{self.rid}", daemon=True,
            )
            self._watchdog.start()

    # -- the solve loop's side ---------------------------------------------

    def unit_done(self, rows: int = 0, residual: Optional[float] = None,
                  **attrs) -> None:
        """Record one completed chunk/block (and the rows it consumed).
        ``residual`` is optional — passed only where the solver already
        has it cheaply (never synced for reporting). Extra ``attrs``
        (epoch, block, chunk) ride on the structured event."""
        now = time.perf_counter_ns()
        with self._lock:
            rec = self.record
            rec.units_done += 1
            rec.rows_done += int(rows)
            rec.last_progress_ns = now
            if residual is not None:
                rec.residual = float(residual)
            rec.oom_downshifts = (
                self._reliability.get("oom_downshifts") - self._oom0
            )
            units = rec.units_done
            if units % self._every == 0:
                ev: Dict[str, Any] = {"unit": units, "t_ns": now}
                ev.update(attrs)
                p = rec.progress()
                ev["rows_per_s"] = p["rows_per_s"]
                ev["eta_s"] = p["eta_s"]
                if residual is not None:
                    ev["residual"] = float(residual)
                rec.events.append(ev)
        self._units_gauge.set(units)
        self._events_counter.bump(f"{self.kind}_units")

    def checkpoint(self, unit: Optional[int] = None) -> None:
        """Stamp a written checkpoint (``unit`` defaults to the current
        unit count) — feeds the health surface's checkpoint age."""
        now = time.perf_counter_ns()
        with self._lock:
            self.record.checkpoint_unit = (
                self.record.units_done if unit is None else int(unit)
            )
            self.record.checkpoint_ns = now

    def finish(self, outcome: str = "ok") -> None:
        """Close the journey (idempotent) and stop the watchdog."""
        with self._lock:
            if self._done:
                return
            self._done = True
            self.record.outcome = outcome
        self._stop.set()
        with _solves_lock:
            _active_solves.pop(self.rid, None)
        self._events_counter.bump(f"{self.kind}_solves")
        # Unlocked point: flush any dump the watchdog marked pending.
        self.recorder.poll()

    def fail(self, exc: BaseException) -> None:
        """A solve died mid-fit: stamp the failure and force-dump the
        solver recorder — the journey names the last completed unit."""
        with self._lock:
            if self._done:
                return
            self._done = True
            self.record.outcome = f"error:{type(exc).__name__}"
            done = self.record.units_done
        self._stop.set()
        with _solves_lock:
            _active_solves.pop(self.rid, None)
        self._events_counter.bump(f"{self.kind}_failures")
        self.recorder.error(
            "solve_death",
            f"{self.kind} solve {self.rid} died after unit {done}: {exc}",
            rid=self.rid,
        )
        logger.warning(
            "%s solve %d died after unit %d (%s); dumping solver "
            "flight recorder", self.kind, self.rid, done, exc,
        )
        self.recorder.dump("solve_death", force=True)

    def stats(self) -> Dict[str, Any]:
        """This solve's live progress (the per-solve health surface)."""
        with self._lock:
            d: Dict[str, Any] = {
                "id": self.rid,
                "kind": self.kind,
                "outcome": self.record.outcome,
            }
            d.update(self.record.progress())
        return d

    def __enter__(self) -> "ProgressReporter":
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None:
            self.fail(exc)
        else:
            self.finish("ok")
        return False

    # -- the watchdog's side -----------------------------------------------

    def _solve_watch_loop(self) -> None:
        """Per-solve stall watchdog (registered thread root — see
        tools/keystone_lint.py KNOWN_THREAD_TARGETS): no unit completed
        inside the window → counter bump + recorder dump, re-armed so one
        stall yields one dump per window, not one per tick."""
        from keystone_tpu.utils.metrics import metrics_registry

        interval = max(self._watchdog_s / 4.0, 0.05)
        while not self._stop.wait(interval):
            self.recorder.poll()
            now = time.perf_counter_ns()
            with self._lock:
                if self._done:
                    return
                age_s = (now - self.record.last_progress_ns) / 1e9
                since_fire_s = (now - self._last_stall_ns) / 1e9
                if age_s < self._watchdog_s or since_fire_s < self._watchdog_s:
                    continue
                # Re-arm the FIRE stamp before dumping (one stall = one
                # dump per window); the record keeps the true
                # last-progress time so /solves reports the real age.
                self._last_stall_ns = now
                self.record.stalls += 1
                done = self.record.units_done
            metrics_registry.counters("solver.events").bump(
                f"{self.kind}_stalls"
            )
            self._reliability.bump("solve_stalls")
            self.recorder.error(
                "stall",
                f"{self.kind} solve {self.rid}: no progress for "
                f"{age_s * 1e3:.0f}ms after unit {done}",
                rid=self.rid,
            )
            logger.warning(
                "%s solve %d: watchdog stall — no unit completed for "
                "%.0fms (last unit %d); dumping solver flight recorder",
                self.kind, self.rid, age_s * 1e3, done,
            )
            self.recorder.dump("solve_stall")
