"""Black-box flight recorder: always-on per-request journey forensics.

The PR-4 tracer answers "where does time go" — but it must be armed
before the incident, and its spans are anonymous aggregates once a
request has been coalesced into a flush group. This module is the other
half of production observability: an ALWAYS-ON, lock-light bounded ring
of per-request journey records (id, rows, bucket, replica(s), per-phase
timestamps, final outcome) plus a last-N ring of error events, cheap
enough to leave running under full traffic and dumped to JSON when
something goes wrong — so the first deadline storm or replica death on a
box nobody was watching still leaves a post-mortem artifact behind.

Concurrency model (the "lock-light" part): the recorder's lock guards
only ring membership and dump bookkeeping. ``FlightRecord`` fields are
written WITHOUT the recorder lock by whichever thread currently owns the
request — ownership hands off through the serving locks (submit ->
dispatcher -> completer), which gives the stamps happens-before ordering;
a dump reads records without quiescing writers, so a record mid-flight
serializes exactly as far as its journey has progressed. That is a
feature: the dump taken at the moment of a stall shows WHERE each
request was stuck.

Dump triggers (``PipelineService`` wires these):

- ``worker_death`` / ``replica_death`` — the reliability events;
- ``deadline_storm`` — >= ``config.serve_storm_expired`` requests expired
  within one second;
- ``stall`` — the service's watchdog thread saw a non-empty pending
  queue make no dispatch progress for ``KEYSTONE_WATCHDOG_MS``;
- ``debug`` — an explicit ``PipelineService.debug_dump()``.

Triggers fired under a serving lock only mark the dump pending
(``note_dump``); the actual file write happens at the next ``poll()``
from a safe (unlocked) point — submit exit, a completer's group
boundary, or the watchdog tick — so forensics never add file I/O to a
critical section. Repeat dumps for one reason are rate-limited
(``MIN_DUMP_INTERVAL_S``); ``debug_dump`` bypasses the limit.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("keystone_tpu")

#: One process-wide monotonic request-id sequence: ids minted at
#: ``PipelineService.submit`` and ``CompiledPipeline.call_async`` share
#: it, so an id is unique across every engine/service in the process and
#: orders submissions.
_req_seq = itertools.count(1)


def next_request_id() -> int:
    """Mint the next process-wide monotonic request id."""
    return next(_req_seq)


class FlightRecord:
    """One request's journey: phase stamps appended in flight, serialized
    whole at dump time. Single-writer by ownership handoff (see module
    docstring) — no lock of its own."""

    __slots__ = ("rid", "rows", "bucket", "replicas", "phases", "outcome")

    def __init__(self, rid: int, rows: int):
        self.rid = rid
        self.rows = rows
        self.bucket: Optional[int] = None
        self.replicas: List[int] = []
        self.phases: List[Tuple[str, int]] = [
            ("submitted", time.perf_counter_ns())
        ]
        self.outcome: Optional[str] = None

    def stamp(self, phase: str) -> None:
        """Append a (phase, perf_counter_ns) stamp. Phases repeat when a
        journey loops (a re-dispatched request is flushed twice)."""
        self.phases.append((phase, time.perf_counter_ns()))

    def dispatched(self, replica: int, bucket: Optional[int]) -> None:
        """Stamp the launch onto a replica; re-dispatches append, so the
        record names EVERY replica that ever held this request."""
        self.replicas.append(int(replica))
        if bucket is not None:
            self.bucket = int(bucket)
        self.stamp("dispatched")

    def finish(self, outcome: str) -> None:
        self.outcome = outcome
        self.stamp("resolved")

    def as_dict(self) -> Dict[str, Any]:
        return {
            "id": self.rid,
            "rows": self.rows,
            "bucket": self.bucket,
            "replicas": list(self.replicas),
            "phases": [
                {"phase": p, "t_ns": t} for p, t in list(self.phases)
            ],
            "outcome": self.outcome,
        }


class FlightRecorder:
    """The bounded journey ring + error-event ring + dump machinery for
    one service instance."""

    #: Floor between two auto-dumps for the SAME reason: a storm must
    #: leave one artifact, not a thousand.
    MIN_DUMP_INTERVAL_S = 5.0

    #: Last-N error events kept alongside the journey ring.
    ERROR_CAPACITY = 256

    #: Most recent dump paths remembered (the rings are bounded; the
    #: dump history must be too — a service degraded for days would
    #: otherwise grow this into every stats()/healthz payload).
    DUMP_HISTORY = 64

    def __init__(
        self,
        name: str,
        capacity: Optional[int] = None,
        directory: Optional[str] = None,
        context: Optional[Callable[[], dict]] = None,
    ):
        from keystone_tpu.config import config

        self.name = name
        self.capacity = int(
            config.flight_records if capacity is None else capacity
        )
        if self.capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {self.capacity}")
        # capacity 0 = the journey ring is off (the repo-wide 0=disabled
        # env convention for KEYSTONE_FLIGHT_RECORDS): deque(maxlen=0)
        # makes every append a no-op while error events and dumps keep
        # working.
        self.directory = (
            directory if directory is not None
            else (config.flight_dir or tempfile.gettempdir())
        )
        self._context = context
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=self.capacity)
        self._errors: deque = deque(maxlen=self.ERROR_CAPACITY)
        self._pending_reason: Optional[str] = None
        self._last_dump: Dict[str, float] = {}
        self._dump_seq = itertools.count()
        self._dumps: deque = deque(maxlen=self.DUMP_HISTORY)
        self.dumps_total = 0
        self.records_started = 0

    @property
    def dumps(self) -> List[str]:
        """The most recent ``DUMP_HISTORY`` dump paths, oldest first."""
        with self._lock:
            return list(self._dumps)

    # -- recording (the hot path) ------------------------------------------

    def start(self, rid: int, rows: int) -> FlightRecord:
        """Open one request's journey record and enter it in the ring.
        The record is mutated in place as the request progresses; the
        ring holds the reference, so in-flight requests are visible to a
        dump exactly as far as they got."""
        rec = FlightRecord(rid, rows)
        with self._lock:
            self._records.append(rec)
            self.records_started += 1
        return rec

    def error(self, kind: str, message: str,
              rid: Optional[int] = None) -> None:
        """Append one error event to the last-N ring."""
        with self._lock:
            self._errors.append({
                "kind": kind,
                "message": str(message)[:500],
                "req_id": rid,
                "t_ns": time.perf_counter_ns(),
            })

    # -- dumping -----------------------------------------------------------

    def note_dump(self, reason: str) -> None:
        """Mark a dump pending. Safe under any serving lock — the file
        write happens at the next ``poll()`` from an unlocked point.
        First reason wins until it is flushed."""
        with self._lock:
            if self._pending_reason is None:
                self._pending_reason = reason

    def poll(self) -> Optional[str]:
        """Flush a pending dump, if any (call from UNLOCKED points only:
        submit exit, completer group boundary, watchdog tick). Returns
        the path written, or None."""
        # Lock-free fast path: poll sits on the client-facing submit
        # path, and a pending dump is vanishingly rare. The racy read is
        # benign — a flag set concurrently is caught by the next poll
        # point (the watchdog tick guarantees one).
        if self._pending_reason is None:
            return None
        with self._lock:
            reason = self._pending_reason
            self._pending_reason = None
        if reason is None:
            return None
        return self.dump(reason)

    def snapshot(self) -> Dict[str, Any]:
        """The rings as plain data (journeys serialized as far as they
        got — see the module docstring on torn reads)."""
        with self._lock:
            records = list(self._records)
            errors = list(self._errors)
        return {
            "service": self.name,
            "capacity": self.capacity,
            "records_started": self.records_started,
            "records": [r.as_dict() for r in records],
            "errors": errors,
        }

    def dump(self, reason: str, path: Optional[str] = None,
             force: bool = False) -> Optional[str]:
        """Write the black box to JSON. Rate-limited per reason unless
        ``force``; returns the path written (None when rate-limited).
        Never raises: a forensics path that throws during the incident it
        exists to record would destroy the evidence AND the service."""
        now = time.perf_counter()
        with self._lock:
            if not force:
                last = self._last_dump.get(reason)
                if last is not None and now - last < self.MIN_DUMP_INTERVAL_S:
                    return None
            seq = next(self._dump_seq)
        doc = self.snapshot()
        doc["reason"] = reason
        # lint: ok(KL005) forensic artifact carries a real wall-clock timestamp
        doc["unix_time"] = time.time()
        try:
            if self._context is not None:
                doc["stats"] = self._context()
        except Exception as e:  # lint: broad-ok a half-closed service's stats must not kill the dump
            doc["stats_error"] = str(e)[:200]
        if path is None:
            fname = (
                f"keystone_flight_{self.name}_{reason}_"
                f"{os.getpid()}_{seq}.json"
            )
            path = os.path.join(self.directory, fname)
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, path)
        except OSError as e:
            # The rate-limit slot is NOT consumed on a failed write: a
            # transient disk error must not suppress the retry that would
            # have captured the incident.
            logger.warning("flight recorder dump to %s failed: %s", path, e)
            return None
        with self._lock:
            self._last_dump[reason] = now
            self._dumps.append(path)
            self.dumps_total += 1
        logger.warning(
            "flight recorder %s: dumped %d record(s) / %d error event(s) "
            "to %s (reason=%s)",
            self.name, len(doc["records"]), len(doc["errors"]), path, reason,
        )
        return path

    def stats(self) -> Dict[str, Any]:
        """Small health-surface summary (NOT the rings themselves)."""
        with self._lock:
            return {
                "records_held": len(self._records),
                "records_started": self.records_started,
                "errors_held": len(self._errors),
                "dumps": list(self._dumps),
                "dumps_total": self.dumps_total,
                "pending_dump": self._pending_reason,
            }
