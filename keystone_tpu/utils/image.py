"""Image conventions and utilities.

Ref: src/main/scala/utils/Image.scala — the reference carries a zero-copy
multi-layout image container (ChannelMajor/ColumnMajor/RowMajor vectorized
images + ImageMetadata) because JVM featurization code is layout-sensitive
(SURVEY.md §2.12) [unverified].

TPU rebuild: batches of images are plain **NHWC float arrays** — XLA owns
physical layout assignment, so the multi-layout machinery collapses to one
logical convention plus `ImageMetadata` for shape bookkeeping. Utilities
mirror `utils/ImageUtils.scala` (grayscale, crop, flip, mapPixels).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

# ITU-R BT.601 luma weights, the standard grayscale conversion.
_LUMA = (0.299, 0.587, 0.114)


@dataclass(frozen=True)
class ImageMetadata:
    height: int
    width: int
    channels: int

    @property
    def num_pixels(self) -> int:
        return self.height * self.width * self.channels


def metadata_of(batch) -> ImageMetadata:
    _, h, w, c = batch.shape
    return ImageMetadata(h, w, c)


def grayscale(batch):
    """NHWC → NHW1 luminance."""
    if batch.shape[-1] == 1:
        return batch
    w = jnp.asarray(_LUMA, dtype=batch.dtype)
    return jnp.tensordot(batch, w, axes=[[-1], [0]])[..., None]


def crop(batch, top: int, left: int, height: int, width: int):
    return batch[:, top : top + height, left : left + width, :]


def flip_horizontal(batch):
    return batch[:, :, ::-1, :]


def map_pixels(batch, fn):
    return fn(batch)


def vectorize(batch):
    """NHWC → (N, H·W·C) row vectors."""
    return batch.reshape(batch.shape[0], -1)
