"""Image conventions and utilities.

Ref: src/main/scala/utils/Image.scala — the reference carries a zero-copy
multi-layout image container (ChannelMajor/ColumnMajor/RowMajor vectorized
images + ImageMetadata) because JVM featurization code is layout-sensitive
(SURVEY.md §2.12) [unverified].

TPU rebuild: batches of images are plain **NHWC float arrays** — XLA owns
physical layout assignment, so the multi-layout machinery collapses to one
logical convention plus `ImageMetadata` for shape bookkeeping. Utilities
mirror `utils/ImageUtils.scala` (grayscale, crop, flip, mapPixels).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

# ITU-R BT.601 luma weights, the standard grayscale conversion.
_LUMA = (0.299, 0.587, 0.114)


@dataclass(frozen=True)
class ImageMetadata:
    height: int
    width: int
    channels: int

    @property
    def num_pixels(self) -> int:
        return self.height * self.width * self.channels


def metadata_of(batch) -> ImageMetadata:
    _, h, w, c = batch.shape
    return ImageMetadata(h, w, c)


def grayscale(batch):
    """NHWC → NHW1 luminance."""
    if batch.shape[-1] == 1:
        return batch
    w = jnp.asarray(_LUMA, dtype=batch.dtype)
    return jnp.tensordot(batch, w, axes=[[-1], [0]])[..., None]


def crop(batch, top: int, left: int, height: int, width: int):
    return batch[:, top : top + height, left : left + width, :]


def flip_horizontal(batch):
    return batch[:, :, ::-1, :]


def map_pixels(batch, fn):
    return fn(batch)


def vectorize(batch):
    """NHWC → (N, H·W·C) row vectors."""
    return batch.reshape(batch.shape[0], -1)


def from_pil(img, size: int | None = None):
    """PIL image → HWC float32 in [0, 1] (ImageConversions analog,
    Ref: utils/ImageConversions.scala BufferedImage↔Image [unverified])."""
    import numpy as np

    # Convert before resizing: palette/bilevel modes force NEAREST resampling.
    img = img.convert("RGB")
    if size is not None:
        img = img.resize((size, size))
    return np.asarray(img, dtype=np.float32) / 255.0


def to_pil(array):
    """HWC float array in [0, 1] → PIL image."""
    import numpy as np
    from PIL import Image as PILImage

    arr = np.asarray(array)
    if arr.ndim == 3 and arr.shape[-1] == 1:
        arr = arr[..., 0]
    return PILImage.fromarray(
        np.rint(np.clip(arr, 0.0, 1.0) * 255.0).astype(np.uint8)
    )


def clamped_gradients(g):
    """Central differences with edge-clamped borders for (n, h, w) images —
    no wrap-around mixing opposite edges into border gradients."""
    gp = jnp.pad(g, ((0, 0), (1, 1), (1, 1)), mode="edge")
    gx = 0.5 * (gp[:, 1:-1, 2:] - gp[:, 1:-1, :-2])
    gy = 0.5 * (gp[:, 2:, 1:-1] - gp[:, :-2, 1:-1])
    return gx, gy


def orientation_maps(g, num_bins: int, signed: bool):
    """Soft-binned gradient-orientation channel maps for (n, h, w) images.

    Returns (n, h, w, num_bins): per pixel, the gradient magnitude split
    linearly between the two orientation bins bracketing its angle —
    unsigned ([0, π), HOG-style) or signed ([0, 2π), DAISY/SIFT-style).
    Shared by the HOG and DAISY extractors.
    """
    gx, gy = clamped_gradients(g)
    mag = jnp.sqrt(gx * gx + gy * gy)
    period = 2 * jnp.pi if signed else jnp.pi
    theta = jnp.mod(jnp.arctan2(gy, gx), period)
    fbin = theta * num_bins / period
    b0 = jnp.floor(fbin).astype(jnp.int32) % num_bins
    w1 = fbin - jnp.floor(fbin)
    bins = jnp.arange(num_bins)
    return (b0[..., None] == bins) * (mag * (1.0 - w1))[..., None] + (
        ((b0 + 1) % num_bins)[..., None] == bins
    ) * (mag * w1)[..., None]
