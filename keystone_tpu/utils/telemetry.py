"""Durable request telemetry: wire trace ids, the JSONL export
pipeline, and per-tenant SLO accounting.

The flight recorder (PR 8) and tracer (PR 4) answer "what happened"
only while the process lives and only inside one process: journeys sit
in bounded in-memory rings that vanish on churn, spans carry process-
local request ids no client ever sees, and the two are unjoinable with
the wire responses. This module is the correlation + durability layer
the ROADMAP serving items stand on:

- **Trace ids** — a client-visible correlation token accepted at
  ingress (``X-Trace-Id`` header / ``trace_id`` frame field) or minted
  at admission, echoed on EVERY response including rejections, threaded
  through ``FlightRecord.meta`` and tracer span attrs, so one id
  stitches a request across router → daemon → replica → offline logs.
- :class:`TelemetryLog` — an append-only JSONL export of resolved
  journeys plus span trees, written by a dedicated writer thread
  (``_writer_loop``, a registered thread root — see
  tools/keystone_lint.py KNOWN_THREAD_TARGETS) so the serving hot path
  never does file I/O: producers enqueue through a BOUNDED queue and a
  full queue drops the record and counts it
  (``telemetry.records_dropped``) — export never blocks admission (the
  off-lock checkpoint-writer discipline of ``OnlineTrainer.submit``).
  Segments rotate by size and retention is bounded
  (``KEYSTONE_TELEMETRY_KEEP``, the ``keep_artifacts`` precedent).
  Default-off: ``KEYSTONE_TELEMETRY_DIR`` unset/empty means
  :func:`active_telemetry` returns None and every call site pays one
  None check (the ``active_tracer()`` discipline).
- :class:`SloAccounting` — per-(tenant, tier) rolling-window
  deadline-hit rate and error-budget burn, fed by the daemon's
  ``finish_request`` and surfaced on ``/stats`` (tenant-redacted for
  anonymous callers) and as per-tier gauges on ``/metrics``.

Clock note: journey stamps and span endpoints are ``perf_counter_ns``
— monotonic, per-process, meaningless across processes. Every segment
therefore opens with a ``meta`` record carrying an anchor pair
(``unix_time``, ``perf_ns`` captured together), which is what lets
``tools/trace_report.py`` place multiple processes' journeys on one
wall-clock timeline.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import re
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("keystone_tpu")

#: Sentinel that tells the writer thread to drain and exit.
_CLOSE = object()

#: What an inbound trace id may look like. Anything else (too long,
#: exotic bytes, header-injection attempts) is REPLACED with a freshly
#: minted id rather than refused — correlation is best-effort, the
#: request itself must not fail over a malformed optional header.
TRACE_ID_RE = re.compile(r"^[A-Za-z0-9._:\-]{1,64}$")


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace id (uuid4-derived: unique across
    processes and hosts without coordination)."""
    return uuid.uuid4().hex[:16]


def accept_trace_id(raw: Optional[str]) -> str:
    """The trace id a request enters the system with: the client's when
    it is well-formed, a freshly minted one otherwise (absent, empty,
    or malformed — malformed inputs must not propagate into logs and
    response headers verbatim)."""
    if raw and TRACE_ID_RE.match(raw):
        return raw
    return mint_trace_id()


def _telemetry_counters():
    from keystone_tpu.utils.metrics import telemetry_counters

    return telemetry_counters


class TelemetryLog:
    """Append-only JSONL telemetry segments for ONE process, written by
    a dedicated writer thread.

    Record kinds (one JSON object per line, ``kind`` discriminates):

    - ``meta`` — opens every segment: pid, service name, schema
      version, and the wall/perf anchor pair that maps this process's
      ``perf_counter_ns`` stamps onto wall time.
    - ``journey`` — one resolved ``FlightRecord`` (``as_dict()``
      payload under ``journey``) plus its trace id.
    - ``spans`` — tracer span trees (ring + tail-retained store) in the
      tracer's native ns schema; written at export points (daemon
      close), not per request.

    Thread-safety: ``journey``/``spans``/``emit`` are safe from any
    thread and never block — a full queue drops and counts. The writer
    thread owns the file handle exclusively.
    """

    #: Bumped when the line schema changes shape incompatibly.
    SCHEMA = 1

    def __init__(self, directory: str, name: str = "telemetry",
                 rotate_mb: Optional[float] = None,
                 keep: Optional[int] = None,
                 queue_cap: Optional[int] = None):
        from keystone_tpu.config import config

        self.directory = directory
        self.name = str(name)
        self.pid = os.getpid()
        self._rotate_bytes = int(
            (config.telemetry_rotate_mb if rotate_mb is None
             else float(rotate_mb)) * 1e6
        )
        self._keep = max(1, int(
            config.telemetry_keep if keep is None else keep
        ))
        cap = int(config.telemetry_queue if queue_cap is None else queue_cap)
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, cap))
        # The anchor pair: captured back-to-back so the wall/perf skew
        # is one function call's worth. This is the ONE place telemetry
        # reads the wall clock — every stamp stays monotonic.
        # lint: ok(KL005) durable telemetry needs a wall anchor to merge processes offline
        self._anchor_unix = time.time()
        self._anchor_perf_ns = time.perf_counter_ns()
        self._lock = threading.Lock()  # guards counters + closed flag
        self._closed = False
        self.enqueued = 0
        self.dropped = 0
        self.written = 0
        self.rotations = 0
        self._seq = 0
        self._path: Optional[str] = None
        os.makedirs(directory, exist_ok=True)
        self._thread = threading.Thread(
            target=self._writer_loop,
            name=f"keystone-telemetry-{self.name}", daemon=True,
        )
        self._thread.start()

    # -- producer side (hot path adjacent: never blocks) -------------------

    def emit(self, record: Dict[str, Any]) -> bool:
        """Enqueue one raw record for the writer. Returns False (and
        counts the drop) when the queue is full or the log is closed —
        NEVER blocks, never raises into the request path."""
        with self._lock:
            if self._closed:
                self.dropped += 1
                _telemetry_counters().bump("records_dropped")
                return False
        try:
            self._queue.put_nowait(record)
        except queue.Full:
            with self._lock:
                self.dropped += 1
            _telemetry_counters().bump("records_dropped")
            return False
        with self._lock:
            self.enqueued += 1
        _telemetry_counters().bump("records_enqueued")
        return True

    def journey(self, service: str, rec: Any,
                trace_id: Optional[str] = None) -> bool:
        """Export one resolved journey record (anything with
        ``as_dict()``). The trace id defaults to the record's own
        ``meta.trace_id`` note."""
        doc = rec.as_dict()
        if trace_id is None:
            trace_id = (doc.get("meta") or {}).get("trace_id")
        return self.emit({
            "kind": "journey",
            "service": service,
            "pid": self.pid,
            "trace_id": trace_id,
            "journey": doc,
        })

    def spans(self, tracer: Any, only_traced: bool = True) -> bool:
        """Export the tracer's current ring + tail-retained span trees
        (native ns schema; the segment meta's anchor maps them to wall
        time). ``only_traced`` keeps just spans that carry request
        correlation (``trace_id``/``req_id``/``req_ids`` attrs) so an
        export at daemon close doesn't ship unrelated solver spans."""

        def keep(s: Dict[str, Any]) -> bool:
            if not only_traced:
                return True
            args = s.get("args") or {}
            return ("trace_id" in args or "req_id" in args
                    or "req_ids" in args)

        events = [s for s in tracer.spans() if keep(s)]
        seen = {(s["name"], s["start_ns"]) for s in events}
        for spans in tracer.retained().values():
            events.extend(
                s for s in spans
                if keep(s) and (s["name"], s["start_ns"]) not in seen
            )
        if not events:
            return False
        return self.emit({
            "kind": "spans",
            "pid": self.pid,
            "events": events,
        })

    # -- the writer thread -------------------------------------------------

    def _meta_record(self) -> Dict[str, Any]:
        return {
            "kind": "meta",
            "schema": self.SCHEMA,
            "service": self.name,
            "pid": self.pid,
            "anchor": {
                "unix_time": self._anchor_unix,
                "perf_ns": self._anchor_perf_ns,
            },
            "segment": self._seq,
        }

    def _segment_path(self, seq: int) -> str:
        return os.path.join(
            self.directory,
            f"keystone_telemetry_{self.name}_{self.pid}_{seq:06d}.jsonl",
        )

    def _open_segment(self, f) -> Tuple[Any, int]:
        """Close ``f`` (if any), open the next segment, write its meta
        line, prune retention. Returns (handle, bytes_written)."""
        if f is not None:
            f.close()
            self.rotations += 1
            _telemetry_counters().bump("segments_rotated")
        self._seq += 1
        self._path = self._segment_path(self._seq)
        f = open(self._path, "w")
        line = json.dumps(self._meta_record()) + "\n"
        f.write(line)
        self._prune_segments()
        return f, len(line)

    def _prune_segments(self) -> None:
        """Bounded retention (the ``keep_artifacts`` precedent): keep
        the newest ``keep`` segments THIS process wrote, delete the
        rest. Best-effort — retention failing must not kill the
        writer."""
        floor = self._seq - self._keep + 1
        if floor <= 1:
            return
        import glob

        prefix = f"keystone_telemetry_{self.name}_{self.pid}_"
        pattern = os.path.join(self.directory, prefix + "[0-9]*.jsonl")
        for old in glob.glob(pattern):
            stem = os.path.basename(old)[len(prefix):-len(".jsonl")]
            try:
                seq = int(stem)
            except ValueError:
                continue  # not ours
            if seq < floor:
                try:
                    os.unlink(old)
                    _telemetry_counters().bump("segments_pruned")
                except OSError:
                    pass  # retention is best-effort

    def _writer_loop(self) -> None:
        """The dedicated writer (registered thread root — see
        tools/keystone_lint.py KNOWN_THREAD_TARGETS): drains the
        bounded queue to the current JSONL segment, rotating by size.
        A write error drops the record (counted) and keeps draining —
        a full disk must degrade telemetry, never the queue's
        producers."""
        f = None
        size = 0
        try:
            f, size = self._open_segment(None)
        except OSError as e:
            logger.warning("telemetry %s: cannot open segment: %s",
                           self.name, e)
        while True:
            rec = self._queue.get()
            if rec is _CLOSE:
                break
            try:
                if f is None:
                    f, size = self._open_segment(None)
                line = json.dumps(rec) + "\n"
                f.write(line)
                f.flush()
                size += len(line)
                with self._lock:
                    self.written += 1
                _telemetry_counters().bump("records_written")
                if size >= self._rotate_bytes:
                    f, size = self._open_segment(f)
            except (OSError, TypeError, ValueError) as e:
                with self._lock:
                    self.dropped += 1
                _telemetry_counters().bump("records_dropped")
                logger.warning(
                    "telemetry %s: record dropped on write error: %s",
                    self.name, e,
                )
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    # -- lifecycle / introspection -----------------------------------------

    def drain(self, timeout: float = 5.0) -> bool:
        """Wait (bounded) until everything enqueued so far is on disk —
        the daemon-close epilogue, and what tests poll instead of
        sleeping. True = drained; False = the writer is behind (or
        wedged) past the timeout. Never raises."""
        deadline = time.monotonic() + max(0.0, timeout)
        while True:
            with self._lock:
                settled = self.written + self.dropped >= self.enqueued
            if settled and self._queue.empty():
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting records, drain the queue, join the writer.
        Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        # The close sentinel BLOCKS if the queue is full: the producers
        # are already refused above, so the writer drains it promptly.
        self._queue.put(_CLOSE)
        self._thread.join(timeout=timeout)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "directory": self.directory,
                "segment": self._path,
                "enqueued": self.enqueued,
                "written": self.written,
                "dropped": self.dropped,
                "rotations": self.rotations,
                "closed": self._closed,
            }


_telemetry_lock = threading.Lock()
_telemetry: Optional[TelemetryLog] = None
_telemetry_key: Optional[tuple] = None


def active_telemetry() -> Optional[TelemetryLog]:
    """The process-wide TelemetryLog, or None when export is off
    (``KEYSTONE_TELEMETRY_DIR`` unset/empty). Resolved ONCE per
    daemon/service — the ``active_tracer()`` discipline — and rebuilt
    when the directory knob changes, so tests flip the knob without a
    reload."""
    global _telemetry, _telemetry_key
    from keystone_tpu.config import resolved_telemetry_dir

    directory = resolved_telemetry_dir()
    if not directory:
        return None
    key = (directory,)
    with _telemetry_lock:
        if key != _telemetry_key or _telemetry is None:
            if _telemetry is not None:
                _telemetry.close()
            _telemetry = TelemetryLog(directory)
            _telemetry_key = key
        return _telemetry


def reset_telemetry() -> None:
    """Close and drop the cached log (a fresh one on next resolve)."""
    global _telemetry, _telemetry_key
    with _telemetry_lock:
        if _telemetry is not None:
            _telemetry.close()
        _telemetry = None
        _telemetry_key = None


# ---------------------------------------------------------------------------
# Per-tenant SLO accounting
# ---------------------------------------------------------------------------


#: HTTP statuses that consume error budget: server-side failures. The
#: client's own errors (400/403) and admission fast-fails (429 — the
#: daemon REFUSED work, it did not fail it) are excluded from the SLO
#: denominator; a deadline miss (504) and a dropped connection do burn.
SLO_BAD_STATUSES = frozenset((500, 503, 504))
SLO_EXCLUDED_STATUSES = frozenset((400, 403, 429))


class SloAccounting:
    """Rolling-window deadline-hit rate and error-budget burn per
    (tenant, tier).

    ``observe()`` is one lock + deque append on the response path;
    windows prune lazily. Memory is bounded twice over: per-key deques
    cap at ``MAX_EVENTS`` (a flood hotter than the window can hold
    degrades to the newest events — hit rates stay correct over what is
    retained), and the key space is the admission table's tenant×tier.

    Burn rate is the SRE error-budget reading: ``miss_rate / (1 -
    target)``. 1.0 = failing at exactly the sustainable rate; 10 =
    burning a month of budget in ~3 days."""

    MAX_EVENTS = 65536

    def __init__(self, window_s: Optional[float] = None,
                 target: Optional[float] = None):
        from keystone_tpu.config import config

        self.window_s = float(
            config.slo_window_s if window_s is None else window_s
        )
        self.target = float(
            config.slo_target if target is None else target
        )
        self._lock = threading.Lock()
        # (tenant, tier) -> deque[(t_monotonic, good: bool)]
        self._events: Dict[Tuple[str, str], deque] = {}

    def observe(self, tenant: str, tier: str, status: int) -> None:
        """Record one resolved response. Excluded statuses (client
        errors, admission refusals) don't enter the window."""
        if status in SLO_EXCLUDED_STATUSES:
            return
        good = status not in SLO_BAD_STATUSES
        now = time.monotonic()
        with self._lock:
            dq = self._events.get((tenant, tier))
            if dq is None:
                dq = self._events[(tenant, tier)] = deque(
                    maxlen=self.MAX_EVENTS
                )
            dq.append((now, good))

    def _prune_locked(self, dq: deque, now: float) -> None:
        horizon = now - self.window_s
        while dq and dq[0][0] < horizon:
            dq.popleft()

    def snapshot(self, redact_tenants: bool = False) -> Dict[str, Any]:
        """The live SLO surface: per tenant/tier window totals, hit
        rate, and burn. With ``redact_tenants`` the per-tenant keys
        collapse to per-tier aggregates (the /stats anonymous-caller
        rule — tier names are not secrets, tenant names are)."""
        now = time.monotonic()
        with self._lock:
            items = [
                (key, list(dq)) for key, dq in self._events.items()
                if (self._prune_locked(dq, now) or dq)
            ]
        agg: Dict[Tuple[str, str], List[int]] = {}
        for (tenant, tier), events in items:
            key = ("*", tier) if redact_tenants else (tenant, tier)
            tot = agg.setdefault(key, [0, 0])
            for _, good in events:
                tot[0] += 1
                tot[1] += int(good)
        out: Dict[str, Any] = {
            "window_s": self.window_s,
            "target": self.target,
            "tenants": {},
        }
        budget = max(1e-9, 1.0 - self.target)
        for (tenant, tier), (total, good) in sorted(agg.items()):
            hit = good / total if total else None
            entry = {
                "total": total,
                "good": good,
                "hit_rate": round(hit, 6) if hit is not None else None,
                "burn": (
                    round((1.0 - hit) / budget, 4)
                    if hit is not None else None
                ),
            }
            out["tenants"].setdefault(tenant, {})[tier] = entry
        return out

    def tier_rates(self) -> Dict[str, Dict[str, float]]:
        """Per-tier aggregate hit-rate/burn — the tenant-free numbers
        the daemon exports as /metrics gauges."""
        snap = self.snapshot(redact_tenants=True)
        out: Dict[str, Dict[str, float]] = {}
        for tiers in snap["tenants"].values():
            for tier, entry in tiers.items():
                if entry["hit_rate"] is not None:
                    out[tier] = {
                        "hit_rate": entry["hit_rate"],
                        "burn": entry["burn"],
                    }
        return out
