"""JAX cross-version shims.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the top-level
``jax`` namespace, and its replication-check kwarg was renamed
``check_rep`` → ``check_vma`` along the way. The solver stack imports it
from here and always passes ``check_vma=``; the shim resolves the import
location and translates the kwarg for whichever jax the image bakes in, so
the same source runs against both API generations.
"""

from __future__ import annotations

import inspect

try:  # newer jax: top-level export (check_vma kwarg)
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax: experimental namespace (check_rep kwarg)
    from jax.experimental.shard_map import shard_map as _shard_map

_ACCEPTS_CHECK_VMA = "check_vma" in inspect.signature(_shard_map).parameters


def shard_map(f=None, **kwargs):
    """``jax.shard_map`` with the check kwarg translated per version.

    Works both called directly (``shard_map(fn, mesh=..., ...)``) and as a
    keyword-configured decorator via ``partial(shard_map, mesh=..., ...)``.
    """
    if not _ACCEPTS_CHECK_VMA and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif _ACCEPTS_CHECK_VMA and "check_rep" in kwargs:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    if f is None:
        return lambda fn: _shard_map(fn, **kwargs)
    return _shard_map(f, **kwargs)
