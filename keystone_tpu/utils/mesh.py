"""Device-mesh helpers and the data-parallel sharding convention.

The Spark execution substrate of the reference (RDD partitions over executors,
Ref: workflow over org.apache.spark.rdd.RDD [unverified]) maps here to a
``jax.sharding.Mesh`` over TPU chips: the ``data`` axis plays the role of RDD
row partitioning, and collectives over ICI replace ``treeAggregate``/shuffle.

``SpecLayout`` is the one sharding convention the workflow layer threads
through fused featurize chains (arXiv:2112.09017's spec-threading for
gram-accumulation-as-all-reduce designs): activations row-sharded on
``config.data_axis``, params and small outputs replicated. A fused chain
lowers ONCE under ``jax.jit`` with these explicit ``in_shardings`` /
``out_shardings`` instead of inheriting whatever placement its input
happened to carry — input placement can no longer silently degrade a
chain to single-device.

Everything in keystone_tpu is written to be mesh-shape agnostic: the same code
runs on 1 chip, on N fake CPU devices (tests), and on a pod slice.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.config import config

_default_mesh: Optional[Mesh] = None


class MeshMismatchError(RuntimeError):
    """Persisted solver/checkpoint state was recorded under a different
    mesh width (device count / data axis) than the one resuming it.

    Raised — never silently resumed and never silently restarted — by the
    streaming solvers' checkpoint binding: per-shard state folded under
    one mesh must not continue under another, because the operator would
    read a 'resumed' solve whose provenance (and any per-shard manifest)
    lies about the mesh it ran on. Re-run on the recording mesh width, or
    delete the checkpoint to start fresh deliberately."""


def default_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D mesh over all local devices on the ``data`` axis.

    Replaces the SparkContext/executor topology of the reference. Multi-host
    meshes are created the same way after ``jax.distributed.initialize`` —
    ``jax.devices()`` then spans hosts and the collectives ride ICI/DCN.
    """
    global _default_mesh
    if devices is None:
        if _default_mesh is None:
            _default_mesh = Mesh(
                np.asarray(jax.devices()), axis_names=(config.data_axis,)
            )
        return _default_mesh
    # An explicit device list is a one-off mesh; never install it as default.
    return Mesh(np.asarray(devices), axis_names=(config.data_axis,))


def set_default_mesh(mesh: Mesh) -> None:
    global _default_mesh
    _default_mesh = mesh


def reset_default_mesh() -> None:
    """Drop the memoized default mesh (the ``reset_memory_probe``
    convention): tests that fake device counts or install one-off meshes
    via ``set_default_mesh`` call this so a memoized narrow mesh can never
    leak into a later test expecting the full device set."""
    global _default_mesh
    _default_mesh = None


def data_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Rows sharded over the data axis — the RDD-partitioning analog."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P(config.data_axis))


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Fully replicated — the Spark ``broadcast`` analog."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P())


def num_data_shards(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or default_mesh()
    return mesh.shape[config.data_axis]


def pad_rows(x: np.ndarray | jax.Array, multiple: int):
    """Pad the leading axis to a multiple, returning (padded, n_real).

    Zero rows are harmless for gram/normal-equation reductions and are masked
    out by consumers that care (e.g. evaluators).
    """
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_widths = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    if isinstance(x, np.ndarray):
        return np.pad(x, pad_widths), n
    import jax.numpy as jnp

    return jnp.pad(x, pad_widths), n


@dataclass(frozen=True)
class SpecLayout:
    """THE data-parallel sharding convention for fused featurize chains:
    row-sharded activations on ``axis`` (``config.data_axis``), replicated
    params/outputs — the SpecLayout-style spec threading of SNIPPETS [2].

    Hashable (frozen, Mesh is hashable), so transformers key their
    sharded-jit caches on the layout itself: one compiled executable per
    (chain, mesh) pair, lowered once with explicit shardings.
    """

    mesh: Mesh
    axis: str

    @classmethod
    def for_mesh(cls, mesh: Optional[Mesh] = None) -> "SpecLayout":
        return cls(mesh or default_mesh(), config.data_axis)

    @property
    def num_shards(self) -> int:
        return self.mesh.shape[self.axis]

    def data(self) -> NamedSharding:
        """Row-sharded: batches/activations flowing through the chain."""
        return NamedSharding(self.mesh, P(self.axis))

    def replicated(self) -> NamedSharding:
        """Replicated: fitted params, grams, solved weights."""
        return NamedSharding(self.mesh, P())

    def jit(self, fn, donate_argnums=(), **jit_kwargs):
        """Lower ``fn`` (batch -> batch, row-independent) ONCE with the
        convention's explicit shardings: rows sharded in, rows sharded
        out. The explicit specs — not input inheritance — are what make
        the chain's placement a contract instead of an accident.

        ``donate_argnums`` is honored only under ``config.donate_buffers``
        (KEYSTONE_DONATE_BUFFERS=0 pins it off) and is the caller's claim
        that those buffers are dead after the call — donate ONLY staging
        copies the caller itself created, never arrays it was handed:
        a donated buffer is deleted, and any later read raises jax's
        deleted-buffer RuntimeError. Unlike the solver loops'
        ``row_matrix.donate_argnums``, this does not refuse CPU meshes:
        the current runtime honors donation there too, which is what lets
        the fake-device tests pin deletion and aliasing for real."""
        if donate_argnums and config.donate_buffers:
            jit_kwargs["donate_argnums"] = donate_argnums
        return jax.jit(
            fn, in_shardings=self.data(), out_shardings=self.data(),
            **jit_kwargs,
        )

    def put(self, x) -> jax.Array:
        """Row-shard a (divisible) batch over the mesh."""
        return jax.device_put(x, self.data())

    def pad_put(self, x):
        """Mask-pad a batch's rows to the shard multiple and shard it;
        returns (sharded_padded, n_real). Pad rows are zeros — inert for
        the row-independent chains this layout lowers, and trimmed back to
        ``n_real`` by the caller after the chain runs."""
        padded, n = pad_rows(x, self.num_shards)
        return self.put(padded), n


def layout_of_array(x) -> Optional[SpecLayout]:
    """The SpecLayout an array already carries: a ``jax.Array`` whose
    sharding is a NamedSharding row-partitioned on a >1-shard data axis
    (the placement ``DatasetOperator`` gives divisible batches). None for
    host arrays, replicated/single-device arrays, and foreign layouts."""
    if not isinstance(x, jax.Array):
        return None
    sharding = getattr(x, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return None
    mesh = sharding.mesh
    axis = config.data_axis
    if axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return None
    spec = sharding.spec
    if not spec or spec[0] != axis:
        return None
    return SpecLayout(mesh, axis)


def host_batch_shard_class(data, shards: Optional[int] = None) -> str:
    """THE shardability classifier for a host batch entering the graph —
    one definition shared by the runtime placement (DatasetOperator), the
    fused-chain lowering decision (``batch_layout``), and the static lint
    (KG103), so the three can never drift apart:

    - ``"inert"`` — not a numeric host array, or a 1-share mesh: nothing
      to decide;
    - ``"small"`` — below ``config.shard_min_rows``: the single-device
      fallback class (counted, never silent);
    - ``"pad"`` — rows never divide the mesh: the mask-pad class;
    - ``"shard"`` — rows divide the mesh: direct row-sharded placement.
    """
    if (
        not isinstance(data, np.ndarray)
        or data.ndim < 1
        or data.dtype.kind not in "biufc"
    ):
        return "inert"
    if shards is None:
        try:
            shards = num_data_shards()
        except RuntimeError:  # deviceless backend: no mesh to shard over
            return "inert"
    if shards <= 1:
        return "inert"
    if data.shape[0] < config.shard_min_rows:
        return "small"
    return "pad" if data.shape[0] % shards else "shard"


#: Fingerprint keys that name the MESH a solve ran on, not the problem.
MESH_FP_KEYS = ("device_count", "data_axis")


def refuse_mesh_mismatch(
    saved_fp,
    expected_fp,
    where: str,
    extra_mesh_keys: tuple = (),
    same_problem=None,
) -> None:
    """Raise the typed ``MeshMismatchError`` when a persisted fingerprint
    names the SAME problem as ``expected_fp`` under a DIFFERENT mesh —
    the one refusal rule shared by every checkpointing solver, so the
    contract can never fork per solver.

    ``extra_mesh_keys`` names additional keys that legitimately follow
    the mesh (e.g. padded row counts); ``same_problem`` overrides the
    problem-identity comparison (default: dict equality) for solvers with
    tolerant float matching. Pre-manifest fingerprints (mesh keys absent
    or None) never refuse — they have no mesh claim to contradict — and
    any OTHER disagreement is the caller's warn-and-start-fresh path.
    """
    if not isinstance(saved_fp, dict):
        return
    saved_mesh = {k: saved_fp.get(k) for k in MESH_FP_KEYS}
    if None in saved_mesh.values():
        return
    expected_mesh = {k: expected_fp.get(k) for k in MESH_FP_KEYS}
    if saved_mesh == expected_mesh:
        return
    excluded = set(MESH_FP_KEYS) | set(extra_mesh_keys)
    if same_problem is None:
        same_problem = lambda a, b: a == b  # noqa: E731
    if same_problem(
        {k: v for k, v in saved_fp.items() if k not in excluded},
        {k: v for k, v in expected_fp.items() if k not in excluded},
    ):
        raise MeshMismatchError(
            f"{where}: checkpoint was written under mesh {saved_mesh}, "
            f"but this solve runs under {expected_mesh}; resuming solver "
            "state across a mesh-width change is refused. Re-run on the "
            "recording mesh width, or delete the checkpoint to start "
            "fresh."
        )


def mesh_fp_compat(saved_fp, expected_fp):
    """Backfill ABSENT mesh-manifest keys in a pre-manifest fingerprint
    from the expected one (wildcards), so a legacy checkpoint of the same
    problem on the same mesh still RESUMES after the manifest upgrade
    instead of silently restarting. Keys that are present always keep
    their saved values — a real mismatch still mismatches."""
    if not isinstance(saved_fp, dict):
        return saved_fp
    out = dict(saved_fp)
    for k in MESH_FP_KEYS:
        if k not in out and k in expected_fp:
            out[k] = expected_fp[k]
    return out


def value_data_shards(value) -> Optional[int]:
    """How many data shards a node output spans: the layout's width for
    row-sharded device arrays, 1 for any other placed ``jax.Array``
    (replicated/single-device), None for host values — the profile row's
    mesh-width provenance, a dict read, never a device sync."""
    layout = layout_of_array(value)
    if layout is not None:
        return layout.num_shards
    return 1 if isinstance(value, jax.Array) else None


def batch_layout(x) -> Optional[SpecLayout]:
    """The layout a fused chain should lower with for input ``x``, or None
    for the plain (propagation) path.

    - An already row-sharded device array (the DatasetOperator placement)
      returns its own layout: the chain re-lowers with those explicit
      specs instead of trusting propagation.
    - A host numeric batch at or above ``config.shard_min_rows`` rows
      returns the default layout: the chain call STAGES it onto the mesh
      itself (``put`` for the divisible "shard" class, ``pad_put`` +
      trim for the "pad" class — the old silent single-device cliff) and
      owns the staging copy, which is what makes it donatable into the
      lowered chain (``config.donate_buffers``). Host arrivals are the
      streamed-fit common case: the jittable tail of a mixed chain takes
      its input from the host stage before it.
    - Everything else (sub-minimum batches, non-numeric data, 1-share
      meshes) returns None.
    """
    layout = layout_of_array(x)
    if layout is not None:
        return layout
    if isinstance(x, jax.Array):  # placed already (replicated/one device)
        return None
    if host_batch_shard_class(x) not in ("pad", "shard"):
        # Small / non-numeric batches have nothing to stage.
        return None
    return SpecLayout.for_mesh()
