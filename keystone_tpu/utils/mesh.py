"""Device-mesh helpers and the data-parallel sharding convention.

The Spark execution substrate of the reference (RDD partitions over executors,
Ref: workflow over org.apache.spark.rdd.RDD [unverified]) maps here to a
``jax.sharding.Mesh`` over TPU chips: the ``data`` axis plays the role of RDD
row partitioning, and collectives over ICI replace ``treeAggregate``/shuffle.

``SpecLayout`` is the one sharding convention the workflow layer threads
through fused featurize chains (arXiv:2112.09017's spec-threading for
gram-accumulation-as-all-reduce designs): activations row-sharded on
``config.data_axis``, params and small outputs replicated. A fused chain
lowers ONCE under ``jax.jit`` with these explicit ``in_shardings`` /
``out_shardings`` instead of inheriting whatever placement its input
happened to carry — input placement can no longer silently degrade a
chain to single-device.

Everything in keystone_tpu is written to be mesh-shape agnostic: the same code
runs on 1 chip, on N fake CPU devices (tests), and on a pod slice.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.config import config

_default_mesh: Optional[Mesh] = None


class MeshMismatchError(RuntimeError):
    """Persisted solver/checkpoint state was recorded under a different
    mesh width (device count / data axis) than the one resuming it, and
    could not be migrated.

    Raised — never silently resumed and never silently restarted — by the
    streaming solvers' checkpoint binding when elastic migration is
    pinned off (``KEYSTONE_ELASTIC_MESH=0``) or the state is genuinely
    non-migratable (a torn/partial per-shard payload): continuing
    differently-folded state unexamined would hand the operator a
    'resumed' solve whose provenance lies about the mesh it ran on.
    Recovery: ``utils.mesh.reshard_state`` migrates the state onto the
    current width (the default-on ``KEYSTONE_ELASTIC_MESH`` path does
    this automatically at resume, counted in the "elastic" metrics
    family), or re-run on the recording mesh width. The work in the
    checkpoint is recoverable — deleting it is a last resort, not the
    advice."""


def default_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D mesh over all local devices on the ``data`` axis.

    Replaces the SparkContext/executor topology of the reference. Multi-host
    meshes are created the same way after ``jax.distributed.initialize`` —
    ``jax.devices()`` then spans hosts and the collectives ride ICI/DCN.
    """
    global _default_mesh
    if devices is None:
        if _default_mesh is None:
            _default_mesh = Mesh(
                np.asarray(jax.devices()), axis_names=(config.data_axis,)
            )
        return _default_mesh
    # An explicit device list is a one-off mesh; never install it as default.
    return Mesh(np.asarray(devices), axis_names=(config.data_axis,))


def set_default_mesh(mesh: Mesh) -> None:
    global _default_mesh
    _default_mesh = mesh


def reset_default_mesh() -> None:
    """Drop the memoized default mesh (the ``reset_memory_probe``
    convention): tests that fake device counts or install one-off meshes
    via ``set_default_mesh`` call this so a memoized narrow mesh can never
    leak into a later test expecting the full device set."""
    global _default_mesh
    _default_mesh = None


def data_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Rows sharded over the data axis — the RDD-partitioning analog."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P(config.data_axis))


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Fully replicated — the Spark ``broadcast`` analog."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P())


def num_data_shards(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or default_mesh()
    return mesh.shape[config.data_axis]


def fold_blocks(width: int) -> int:
    """Canonical block count for the width-independent solver row fold,
    or 0 when this mesh width must fall back to the plain psum fold.

    Row reductions folded over ``config.gram_fold_blocks`` fixed row
    blocks in a balanced-tree order produce the SAME bits on any mesh
    width that divides the block count — the property that lets a solve
    checkpointed on one width resume on another bit-identically (the
    elastic mesh contract). Active only when both the block count and
    the width are powers of two with ``width <= blocks``."""
    blocks = int(config.gram_fold_blocks or 0)
    if blocks <= 0 or blocks & (blocks - 1):
        return 0
    width = int(width)
    if width <= 0 or width & (width - 1) or blocks % width:
        return 0
    return blocks


def pad_multiple(width: int) -> int:
    """The row-padding multiple for solver operands on a ``width``-shard
    mesh: the canonical fold block count when the deterministic fold is
    active (every width's rows then pad identically, which is what keeps
    the fold's block boundaries — and therefore its bits —
    width-independent), else the mesh width."""
    return fold_blocks(width) or int(width)


def pad_rows(x: np.ndarray | jax.Array, multiple: int):
    """Pad the leading axis to a multiple, returning (padded, n_real).

    Zero rows are harmless for gram/normal-equation reductions and are masked
    out by consumers that care (e.g. evaluators).
    """
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_widths = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    if isinstance(x, np.ndarray):
        return np.pad(x, pad_widths), n
    import jax.numpy as jnp

    return jnp.pad(x, pad_widths), n


@dataclass(frozen=True)
class SpecLayout:
    """THE data-parallel sharding convention for fused featurize chains:
    row-sharded activations on ``axis`` (``config.data_axis``), replicated
    params/outputs — the SpecLayout-style spec threading of SNIPPETS [2].

    Hashable (frozen, Mesh is hashable), so transformers key their
    sharded-jit caches on the layout itself: one compiled executable per
    (chain, mesh) pair, lowered once with explicit shardings.
    """

    mesh: Mesh
    axis: str

    @classmethod
    def for_mesh(cls, mesh: Optional[Mesh] = None) -> "SpecLayout":
        return cls(mesh or default_mesh(), config.data_axis)

    @property
    def num_shards(self) -> int:
        return self.mesh.shape[self.axis]

    def data(self) -> NamedSharding:
        """Row-sharded: batches/activations flowing through the chain."""
        return NamedSharding(self.mesh, P(self.axis))

    def replicated(self) -> NamedSharding:
        """Replicated: fitted params, grams, solved weights."""
        return NamedSharding(self.mesh, P())

    def jit(self, fn, donate_argnums=(), **jit_kwargs):
        """Lower ``fn`` (batch -> batch, row-independent) ONCE with the
        convention's explicit shardings: rows sharded in, rows sharded
        out. The explicit specs — not input inheritance — are what make
        the chain's placement a contract instead of an accident.

        ``donate_argnums`` is honored only under ``config.donate_buffers``
        (KEYSTONE_DONATE_BUFFERS=0 pins it off) and is the caller's claim
        that those buffers are dead after the call — donate ONLY staging
        copies the caller itself created, never arrays it was handed:
        a donated buffer is deleted, and any later read raises jax's
        deleted-buffer RuntimeError. Unlike the solver loops'
        ``row_matrix.donate_argnums``, this does not refuse CPU meshes:
        the current runtime honors donation there too, which is what lets
        the fake-device tests pin deletion and aliasing for real."""
        if donate_argnums and config.donate_buffers:
            jit_kwargs["donate_argnums"] = donate_argnums
        return jax.jit(
            fn, in_shardings=self.data(), out_shardings=self.data(),
            **jit_kwargs,
        )

    def put(self, x) -> jax.Array:
        """Row-shard a (divisible) batch over the mesh."""
        return jax.device_put(x, self.data())

    def pad_put(self, x):
        """Mask-pad a batch's rows to the shard multiple and shard it;
        returns (sharded_padded, n_real). Pad rows are zeros — inert for
        the row-independent chains this layout lowers, and trimmed back to
        ``n_real`` by the caller after the chain runs."""
        padded, n = pad_rows(x, self.num_shards)
        return self.put(padded), n


def layout_of_array(x) -> Optional[SpecLayout]:
    """The SpecLayout an array already carries: a ``jax.Array`` whose
    sharding is a NamedSharding row-partitioned on a >1-shard data axis
    (the placement ``DatasetOperator`` gives divisible batches). None for
    host arrays, replicated/single-device arrays, and foreign layouts."""
    if not isinstance(x, jax.Array):
        return None
    sharding = getattr(x, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return None
    mesh = sharding.mesh
    axis = config.data_axis
    if axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return None
    spec = sharding.spec
    if not spec or spec[0] != axis:
        return None
    return SpecLayout(mesh, axis)


def host_batch_shard_class(data, shards: Optional[int] = None) -> str:
    """THE shardability classifier for a host batch entering the graph —
    one definition shared by the runtime placement (DatasetOperator), the
    fused-chain lowering decision (``batch_layout``), and the static lint
    (KG103), so the three can never drift apart:

    - ``"inert"`` — not a numeric host array, or a 1-share mesh: nothing
      to decide;
    - ``"small"`` — below ``config.shard_min_rows``: the single-device
      fallback class (counted, never silent);
    - ``"pad"`` — rows never divide the mesh: the mask-pad class;
    - ``"shard"`` — rows divide the mesh: direct row-sharded placement.
    """
    if (
        not isinstance(data, np.ndarray)
        or data.ndim < 1
        or data.dtype.kind not in "biufc"
    ):
        return "inert"
    if shards is None:
        try:
            shards = num_data_shards()
        except RuntimeError:  # deviceless backend: no mesh to shard over
            return "inert"
    if shards <= 1:
        return "inert"
    if data.shape[0] < config.shard_min_rows:
        return "small"
    return "pad" if data.shape[0] % shards else "shard"


#: Fingerprint keys that name the MESH a solve ran on, not the problem.
MESH_FP_KEYS = ("device_count", "data_axis")


def refuse_mesh_mismatch(
    saved_fp,
    expected_fp,
    where: str,
    extra_mesh_keys: tuple = (),
    same_problem=None,
) -> bool:
    """The one mesh-width rule shared by every checkpointing solver, so
    the contract can never fork per solver: when a persisted fingerprint
    names the SAME problem as ``expected_fp`` under a DIFFERENT mesh,
    either signal the elastic migration path (``config.elastic_mesh``,
    default on — returns True, the caller migrates via ``reshard_state``)
    or raise the typed ``MeshMismatchError`` (elastic pinned off).
    Returns False when there is no same-problem mesh conflict.

    ``extra_mesh_keys`` names additional keys that legitimately follow
    the mesh (e.g. padded row counts); ``same_problem`` overrides the
    problem-identity comparison (default: dict equality) for solvers with
    tolerant float matching. Pre-manifest fingerprints (mesh keys absent
    or None) never refuse — they have no mesh claim to contradict — and
    any OTHER disagreement is the caller's warn-and-start-fresh path.
    """
    if not isinstance(saved_fp, dict):
        return False
    saved_mesh = {k: saved_fp.get(k) for k in MESH_FP_KEYS}
    if None in saved_mesh.values():
        return False
    expected_mesh = {k: expected_fp.get(k) for k in MESH_FP_KEYS}
    if saved_mesh == expected_mesh:
        return False
    excluded = set(MESH_FP_KEYS) | set(extra_mesh_keys)
    if same_problem is None:
        same_problem = lambda a, b: a == b  # noqa: E731
    if not same_problem(
        {k: v for k, v in saved_fp.items() if k not in excluded},
        {k: v for k, v in expected_fp.items() if k not in excluded},
    ):
        return False
    if config.elastic_mesh:
        return True
    raise MeshMismatchError(
        f"{where}: checkpoint was written under mesh {saved_mesh}, "
        f"but this solve runs under {expected_mesh}; elastic migration "
        "is pinned off (KEYSTONE_ELASTIC_MESH=0), so resuming solver "
        "state across the width change is refused. Recover with "
        "utils.mesh.reshard_state (or unpin KEYSTONE_ELASTIC_MESH to "
        "migrate automatically at resume), or re-run on the recording "
        "mesh width — the checkpointed work is recoverable."
    )


def mesh_resume_decision(
    saved_fp,
    expected_fp,
    where: str,
    extra_mesh_keys: tuple = (),
    same_problem=None,
):
    """THE checkpoint-resume triage every durable-state family routes
    through (stream solve, BCD, ``OnlineState``) — legacy-wildcard
    backfill, problem-identity comparison, and the mesh-width rule in one
    place, so the three can never drift apart.

    Returns ``(decision, saved_fp)`` where ``saved_fp`` has absent
    pre-manifest mesh keys backfilled (``mesh_fp_compat``) and
    ``decision`` is one of:

    - ``"resume"`` — same problem, same mesh: continue the state as-is;
    - ``"migrate"`` — same problem under a different mesh width with
      ``config.elastic_mesh`` on: the caller migrates the payload via
      ``reshard_state`` and then resumes;
    - ``"fresh"`` — a different problem (or no usable fingerprint): the
      caller's warn-and-start-fresh path.

    Raises ``MeshMismatchError`` for the same-problem/different-mesh
    case when elastic migration is pinned off.
    """
    saved_fp = mesh_fp_compat(saved_fp, expected_fp)
    if not isinstance(saved_fp, dict):
        return "fresh", saved_fp
    matches = same_problem if same_problem is not None else (
        lambda a, b: a == b
    )
    if matches(saved_fp, expected_fp):
        return "resume", saved_fp
    if refuse_mesh_mismatch(
        saved_fp, expected_fp, where,
        extra_mesh_keys=extra_mesh_keys, same_problem=same_problem,
    ):
        return "migrate", saved_fp
    return "fresh", saved_fp


def mesh_fp_compat(saved_fp, expected_fp):
    """Backfill ABSENT mesh-manifest keys in a pre-manifest fingerprint
    from the expected one (wildcards), so a legacy checkpoint of the same
    problem on the same mesh still RESUMES after the manifest upgrade
    instead of silently restarting. Keys that are present always keep
    their saved values — a real mismatch still mismatches."""
    if not isinstance(saved_fp, dict):
        return saved_fp
    out = dict(saved_fp)
    for k in MESH_FP_KEYS:
        if k not in out and k in expected_fp:
            out[k] = expected_fp[k]
    return out


#: family name -> adapter(state, layout) -> migrated state. Families
#: register at import; ``reshard_state`` imports them lazily so the
#: registry is always populated by first use (no import cycles: this
#: module never imports the solvers at top level).
_RESHARD_ADAPTERS: dict = {}


def register_reshard_adapter(family: str, adapter) -> None:
    """Register one durable-state family's migration adapter. The
    adapter takes ``(state, layout)`` — the persisted payload dict and
    the target ``SpecLayout`` — and returns a NEW payload whose
    accumulators are bit-identical and whose mesh manifest names the
    target layout; it raises ``MeshMismatchError`` for payloads it can
    prove torn/partial (those must keep the typed refusal)."""
    _RESHARD_ADAPTERS[family] = adapter


def _infer_reshard_family(state) -> Optional[str]:
    """Which durable-state family a payload dict belongs to, from its
    key shape (each family's snapshot schema is disjoint)."""
    if not isinstance(state, dict):
        return None
    keys = set(state)
    if {"pipeline_digest", "digests", "rows"} <= keys:
        return "profile"
    if {"fingerprint", "gram", "atb"} <= keys:
        if {"x_sum", "y_sum"} <= keys:
            return "online_state"
        if "chunks_done" in keys:
            return "stream_solve"
    if {"fingerprint", "epoch", "W", "R"} <= keys:
        return "bcd_stream" if "block" in keys else "bcd_epoch"
    return None


def reshard_state(state, new_layout: Optional[SpecLayout] = None,
                  family: Optional[str] = None):
    """Migrate one durable-state payload onto ``new_layout``'s mesh
    width — the elastic-mesh recovery every checkpointing family shares.

    The retained f64 accumulators are placement-free by construction
    (gram/AᵀB/col_sums are psum'd sums whose grouping invariance PR 14
    pinned), so migration is a manifest rewrite, not a recompute: the
    per-family adapter re-folds/re-pads anything mesh-shaped (e.g. the
    BCD residual's padded rows), rewrites the fingerprint's mesh keys
    (``MESH_FP_KEYS``) onto the new layout, and returns a NEW payload
    bit-identical in every accumulator byte. A migrated resume therefore
    matches an uninterrupted fresh fit at the target width bit-for-bit.

    ``family`` names the adapter explicitly; None infers it from the
    payload's key shape. Every migration is counted in the "elastic"
    metrics registry family and logged — never silent. Truly
    non-migratable state (unknown family, torn/partial per-shard
    payloads) raises the typed ``MeshMismatchError`` instead.
    """
    import logging

    # Importing the families registers their adapters (see
    # register_reshard_adapter); lazy so there is no import cycle.
    import keystone_tpu.linalg.bcd  # noqa: F401
    import keystone_tpu.linalg.normal_equations  # noqa: F401
    import keystone_tpu.workflow.online  # noqa: F401
    import keystone_tpu.workflow.profile_store  # noqa: F401
    from keystone_tpu.utils.metrics import elastic_counters

    if new_layout is None:
        new_layout = SpecLayout.for_mesh()
    if family is None:
        family = _infer_reshard_family(state)
    adapter = _RESHARD_ADAPTERS.get(family)
    if adapter is None:
        elastic_counters.bump("migrations_refused")
        raise MeshMismatchError(
            f"reshard_state: no migration adapter for this state "
            f"(family={family!r}); it cannot be migrated across mesh "
            "widths — re-run on the recording mesh width"
        )
    migrated = adapter(state, new_layout)
    elastic_counters.bump("states_migrated")
    elastic_counters.bump(f"{family}_migrated")
    logging.getLogger("keystone_tpu").warning(
        "elastic mesh: migrated %s state onto %d-shard mesh "
        "(counted in metrics family 'elastic')",
        family, new_layout.num_shards,
    )
    return migrated


#: Filename of the JSON mesh sidecar every checkpoint writer drops next
#: to its payloads — the static lint's (KG107) no-execution window into
#: what mesh a directory's state was folded under.
MESH_MANIFEST_NAME = "mesh_manifest.json"


def write_mesh_manifest(ckpt_dir: str, fingerprint) -> None:
    """Atomic JSON sidecar naming the mesh a checkpoint directory's state
    was folded under (the fingerprint is JSON-safe scalars by
    construction), so the static lint (KG107) can flag a width drift with
    one dict read — no unpickling, no orbax restore, no execution.
    Best-effort: a read-only store keeps its payloads authoritative."""
    import json
    import os

    path = os.path.join(os.path.abspath(ckpt_dir), MESH_MANIFEST_NAME)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(dict(fingerprint), f)
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError):
        try:
            os.unlink(tmp)
        except OSError:
            pass


def read_mesh_manifest(ckpt_dir) -> Optional[dict]:
    """The sidecar's fingerprint dict, or None when absent/unreadable —
    the advisory read; payload fingerprints stay authoritative at
    resume."""
    import json
    import os

    if not ckpt_dir:
        return None
    path = os.path.join(os.path.abspath(str(ckpt_dir)), MESH_MANIFEST_NAME)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None
    return doc if isinstance(doc, dict) else None


def reshard_refused(where: str, reason: str) -> MeshMismatchError:
    """The non-migratable refusal adapters raise: counted (never silent)
    and worded like every other mesh refusal, naming the recovery."""
    from keystone_tpu.utils.metrics import elastic_counters

    elastic_counters.bump("migrations_refused")
    return MeshMismatchError(
        f"{where}: state cannot be migrated across mesh widths "
        f"({reason}); reshard_state refuses rather than resume a "
        "corrupted payload — re-run on the recording mesh width or "
        "delete the checkpoint after inspecting it"
    )


def value_data_shards(value) -> Optional[int]:
    """How many data shards a node output spans: the layout's width for
    row-sharded device arrays, 1 for any other placed ``jax.Array``
    (replicated/single-device), None for host values — the profile row's
    mesh-width provenance, a dict read, never a device sync."""
    layout = layout_of_array(value)
    if layout is not None:
        return layout.num_shards
    return 1 if isinstance(value, jax.Array) else None


def batch_layout(x) -> Optional[SpecLayout]:
    """The layout a fused chain should lower with for input ``x``, or None
    for the plain (propagation) path.

    - An already row-sharded device array (the DatasetOperator placement)
      returns its own layout: the chain re-lowers with those explicit
      specs instead of trusting propagation.
    - A host numeric batch at or above ``config.shard_min_rows`` rows
      returns the default layout: the chain call STAGES it onto the mesh
      itself (``put`` for the divisible "shard" class, ``pad_put`` +
      trim for the "pad" class — the old silent single-device cliff) and
      owns the staging copy, which is what makes it donatable into the
      lowered chain (``config.donate_buffers``). Host arrivals are the
      streamed-fit common case: the jittable tail of a mixed chain takes
      its input from the host stage before it.
    - Everything else (sub-minimum batches, non-numeric data, 1-share
      meshes) returns None.
    """
    layout = layout_of_array(x)
    if layout is not None:
        return layout
    if isinstance(x, jax.Array):  # placed already (replicated/one device)
        return None
    if host_batch_shard_class(x) not in ("pad", "shard"):
        # Small / non-numeric batches have nothing to stage.
        return None
    return SpecLayout.for_mesh()
