"""Device-mesh helpers.

The Spark execution substrate of the reference (RDD partitions over executors,
Ref: workflow over org.apache.spark.rdd.RDD [unverified]) maps here to a
``jax.sharding.Mesh`` over TPU chips: the ``data`` axis plays the role of RDD
row partitioning, and collectives over ICI replace ``treeAggregate``/shuffle.

Everything in keystone_tpu is written to be mesh-shape agnostic: the same code
runs on 1 chip, on N fake CPU devices (tests), and on a pod slice.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.config import config

_default_mesh: Optional[Mesh] = None


def default_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D mesh over all local devices on the ``data`` axis.

    Replaces the SparkContext/executor topology of the reference. Multi-host
    meshes are created the same way after ``jax.distributed.initialize`` —
    ``jax.devices()`` then spans hosts and the collectives ride ICI/DCN.
    """
    global _default_mesh
    if devices is None:
        if _default_mesh is None:
            _default_mesh = Mesh(
                np.asarray(jax.devices()), axis_names=(config.data_axis,)
            )
        return _default_mesh
    # An explicit device list is a one-off mesh; never install it as default.
    return Mesh(np.asarray(devices), axis_names=(config.data_axis,))


def set_default_mesh(mesh: Mesh) -> None:
    global _default_mesh
    _default_mesh = mesh


def data_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Rows sharded over the data axis — the RDD-partitioning analog."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P(config.data_axis))


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Fully replicated — the Spark ``broadcast`` analog."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P())


def num_data_shards(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or default_mesh()
    return mesh.shape[config.data_axis]


def pad_rows(x: np.ndarray | jax.Array, multiple: int):
    """Pad the leading axis to a multiple, returning (padded, n_real).

    Zero rows are harmless for gram/normal-equation reductions and are masked
    out by consumers that care (e.g. evaluators).
    """
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_widths = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    if isinstance(x, np.ndarray):
        return np.pad(x, pad_widths), n
    import jax.numpy as jnp

    return jnp.pad(x, pad_widths), n
