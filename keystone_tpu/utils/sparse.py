"""Host-side CSR batch — sparse features without (n × vocab) dense arrays.

Ref: the reference's text path emits Spark `SparseVector`s from
CommonSparseFeatures onward (SURVEY.md §2.7/§2.8) [unverified]. The TPU has
no sparse MXU path, so the rebuild keeps sparsity on the HOST — where the
memory problem lives — and densifies per column block right before device
work: the solver streams dense (n, block) slices to the chip (the same
double-buffered seam the out-of-HBM dense path uses), and classifier
inference accumulates block gemms. Vocab ≫ 10k therefore never materializes
an (n, vocab) dense array anywhere.

Indices are unique within each row (the vectorizers build from dicts);
``densify`` relies on that.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import numpy as np


class SparseBatch:
    """CSR: ``values[indptr[i]:indptr[i+1]]`` at ``indices[...]`` is row i."""

    __slots__ = ("indptr", "indices", "values", "dim", "_rows", "_csc")

    def __init__(self, indptr, indices, values, dim: int):
        self.indptr = np.asarray(indptr, dtype=np.int64)
        self.indices = np.asarray(indices, dtype=np.int32)
        self.values = np.asarray(values, dtype=np.float32)
        self.dim = int(dim)
        self._rows: Optional[np.ndarray] = None
        self._csc: Optional[tuple] = None

    # -- construction ------------------------------------------------------

    @classmethod
    def from_term_maps(
        cls, docs: Sequence[Mapping[str, float]], index: Mapping[str, int], dim: int
    ) -> "SparseBatch":
        indptr = [0]
        indices: list = []
        values: list = []
        for doc in docs:
            for term, weight in doc.items():
                j = index.get(term)
                if j is not None:
                    indices.append(j)
                    values.append(weight)
            indptr.append(len(indices))
        return cls(indptr, indices, values, dim)

    @classmethod
    def from_counts(
        cls, docs: Sequence[Sequence[str]], index: Mapping[str, int], dim: int
    ) -> "SparseBatch":
        from collections import Counter

        indptr = [0]
        indices: list = []
        values: list = []
        for tokens in docs:
            counts = Counter(tokens)
            for term, c in counts.items():
                j = index.get(term)
                if j is not None:
                    indices.append(j)
                    values.append(float(c))
            indptr.append(len(indices))
        return cls(indptr, indices, values, dim)

    @classmethod
    def from_dense(cls, X) -> "SparseBatch":
        X = np.asarray(X)
        indptr = [0]
        indices: list = []
        values: list = []
        for row in X:
            nz = np.nonzero(row)[0]
            indices.extend(nz.tolist())
            values.extend(row[nz].tolist())
            indptr.append(len(indices))
        return cls(indptr, indices, values, X.shape[1])

    # -- properties --------------------------------------------------------

    @property
    def shape(self):
        return (len(self.indptr) - 1, self.dim)

    @property
    def nnz(self) -> int:
        return int(self.indices.shape[0])

    @property
    def nbytes(self) -> int:
        return self.indptr.nbytes + self.indices.nbytes + self.values.nbytes

    def __len__(self) -> int:
        return self.shape[0]

    def _row_ids(self) -> np.ndarray:
        if self._rows is None:
            self._rows = np.repeat(
                np.arange(len(self), dtype=np.int64), np.diff(self.indptr)
            )
        return self._rows

    def _col_sorted(self) -> tuple:
        """(rows, cols, vals) sorted by column — one O(nnz log nnz) sort,
        after which every column-block densify is O(nnz_block) via
        searchsorted bounds instead of an O(nnz) mask scan per block."""
        if self._csc is None:
            order = np.argsort(self.indices, kind="stable")
            self._csc = (
                self._row_ids()[order],
                self.indices[order],
                self.values[order],
            )
        return self._csc

    # -- dense views -------------------------------------------------------

    def densify(
        self, start: int = 0, stop: Optional[int] = None, dtype=np.float32
    ) -> np.ndarray:
        """Dense (n, stop-start) slice of columns [start, stop) — the
        per-block view the streamed solver consumes."""
        stop = self.dim if stop is None else stop
        out = np.zeros((len(self), stop - start), dtype=dtype)
        rows, cols, vals = self._col_sorted()
        lo, hi = np.searchsorted(cols, (start, stop))
        out[rows[lo:hi], cols[lo:hi] - start] = vals[lo:hi]
        return out

    def toarray(self, dtype=np.float32) -> np.ndarray:
        return self.densify(0, self.dim, dtype)

    def matmul(self, M, block: int = 8192) -> np.ndarray:
        """self @ M for a dense (dim, k) M, densifying one column block at a
        time — peak extra memory is (n, block), never (n, dim)."""
        M = np.asarray(M)
        out = np.zeros((len(self), M.shape[1]), dtype=np.float32)
        for s in range(0, self.dim, block):
            e = min(s + block, self.dim)
            out += self.densify(s, e) @ M[s:e]
        return out

    # -- reductions --------------------------------------------------------

    def column_sums(self) -> np.ndarray:
        return np.bincount(
            self.indices, weights=self.values, minlength=self.dim
        ).astype(np.float32)

    def grouped_column_sums(self, groups, num_groups: int) -> np.ndarray:
        """(num_groups, dim) per-group column sums — one bincount over
        group-offset keys (the naive-Bayes per-class count reduction)."""
        groups = np.asarray(groups, dtype=np.int64).ravel()
        rows = self._row_ids()
        keys = groups[rows] * self.dim + self.indices
        flat = np.bincount(
            keys, weights=self.values, minlength=num_groups * self.dim
        )
        return flat.reshape(num_groups, self.dim).astype(np.float32)

    def row_sum(self, i: int) -> float:
        s, e = int(self.indptr[i]), int(self.indptr[i + 1])
        return float(self.values[s:e].sum())

    def to_bcoo(self, dtype=None):
        """This batch as a ``jax.experimental.sparse.BCOO`` on the default
        device — the device-sparse view for models that iterate over X
        inside jit (e.g. logistic regression's LBFGS loop). COO coords come
        straight from the CSR structure; nothing densifies.
        ``unique_indices=True`` is safe by the class invariant (indices are
        unique per row) and unlocks the cheaper scatter lowerings."""
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse

        values = self.values if dtype is None else self.values.astype(dtype)
        coords = np.stack(
            [self._row_ids().astype(np.int32), self.indices], axis=1
        )
        return jsparse.BCOO(
            (jnp.asarray(values), jnp.asarray(coords)),
            shape=self.shape,
            unique_indices=True,
        )

    # -- structure edits ---------------------------------------------------

    def append_ones(self) -> "SparseBatch":
        """A copy with one extra all-ones column at index ``dim`` — the
        intercept column for solvers that learn b as a model weight."""
        n = len(self)
        indptr = self.indptr + np.arange(n + 1, dtype=np.int64)
        # Insert one (dim, 1.0) entry at each original row end — three
        # vectorized ops, no per-row Python loop.
        at = np.asarray(self.indptr[1:])
        indices = np.insert(self.indices, at, np.int32(self.dim))
        values = np.insert(self.values, at, np.float32(1.0))
        return SparseBatch(indptr, indices, values, self.dim + 1)

    def __repr__(self) -> str:
        n, d = self.shape
        return f"SparseBatch({n}x{d}, nnz={self.nnz})"
