"""Multi-host bring-up over DCN.

Ref: the reference inherits its control plane from Spark (driver/executor
over Netty RPC; SURVEY.md §5 distributed-backend row). The TPU equivalent
is single-controller-per-host JAX: each host process calls
``jax.distributed.initialize`` (rendezvous over DCN), after which
``jax.devices()`` spans every chip in the slice and the same
mesh/collective code used on one host runs pod-wide — `psum`/`all_gather`
ride ICI within a slice and DCN across slices, replacing treeAggregate 1:1.

Single-host (or this sandbox's 1-chip / fake-CPU-mesh) callers skip
initialization entirely; nothing else in the framework changes.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host rendezvous. Arguments default from the standard
    env vars (KEYSTONE_COORDINATOR, KEYSTONE_NUM_PROCESSES,
    KEYSTONE_PROCESS_ID) so `bin/run-pipeline.sh` can drive pod launches
    with env knobs alone."""
    coordinator_address = coordinator_address or os.environ.get(
        "KEYSTONE_COORDINATOR"
    )
    if coordinator_address is None:
        return  # single-host: nothing to do
    # `is None`, not `or`: process_id 0 (the coordinator) is falsy.
    if num_processes is None:
        num_processes = int(os.environ["KEYSTONE_NUM_PROCESSES"])
    if process_id is None:
        process_id = int(os.environ["KEYSTONE_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh():
    """Mesh over every device in the (possibly multi-host) job."""
    from keystone_tpu.utils.mesh import default_mesh

    return default_mesh()
