from keystone_tpu.utils.stats import about_eq
from keystone_tpu.utils.mesh import (
    MeshMismatchError,
    SpecLayout,
    data_sharding,
    default_mesh,
    replicated_sharding,
    reset_default_mesh,
)

__all__ = [
    "about_eq",
    "default_mesh",
    "data_sharding",
    "replicated_sharding",
    "reset_default_mesh",
    "MeshMismatchError",
    "SpecLayout",
]
