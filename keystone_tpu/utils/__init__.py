from keystone_tpu.utils.stats import about_eq
from keystone_tpu.utils.mesh import default_mesh, data_sharding, replicated_sharding

__all__ = ["about_eq", "default_mesh", "data_sharding", "replicated_sharding"]
