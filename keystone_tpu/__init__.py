"""keystone_tpu — a TPU-native framework with the capabilities of KeystoneML.

A type-safe Pipeline DAG of Transformer/Estimator nodes whose optimizer lowers
fused operator chains to single XLA computations; a distributed linear-algebra
layer built on ``jax.sharding`` with XLA collectives over ICI/DCN in place of
Spark ``treeAggregate``/shuffle; operator libraries for image featurization,
NLP, statistics, and large-scale linear learning; and the canonical end-to-end
pipelines (MNIST, Newsgroups, CIFAR, TIMIT, ImageNet).

Reference: amplab/keystone (KeystoneML, Scala/Spark). See SURVEY.md for the
structural analysis this rebuild follows. Reference paths cited in docstrings
are ``[unverified]`` (the reference mount was empty; see SURVEY.md provenance).
"""

from keystone_tpu.workflow import (
    Estimator,
    LabelEstimator,
    Pipeline,
    PipelineDataset,
    Transformer,
)

__version__ = "0.1.0"

__all__ = [
    "Transformer",
    "Estimator",
    "LabelEstimator",
    "Pipeline",
    "PipelineDataset",
]
