"""MNIST loader: IDX or CSV files when available, synthetic fallback.

Ref: the reference's MNIST pipeline reads the Bosen-format CSV dumps via
`CsvDataLoader` (SURVEY.md §2.11) [unverified]. This environment has no
network, so `synthetic(...)` generates a deterministic MNIST-like dataset
(per-class prototype digits + noise) for tests and smoke runs; quality
numbers on real MNIST require pointing `--train/--test` at real files.
"""

from __future__ import annotations

import os
import struct
from typing import Optional, Tuple

import numpy as np

from keystone_tpu.config import config
from keystone_tpu.loaders.csv_loader import CsvDataLoader
from keystone_tpu.loaders.labeled_data import LabeledData


def _read_idx(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        magic = struct.unpack(">HBB", f.read(4))
        _, dtype_code, ndim = magic
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(dims)


class MnistLoader:
    @staticmethod
    def load(path: str) -> LabeledData:
        """Load from a CSV (label first) or an IDX image/label file pair
        (``path`` without extension + '-images-idx3-ubyte'/'-labels-idx1-ubyte')."""
        if path.endswith(".csv"):
            return CsvDataLoader.load_labeled(path)
        imgs = _read_idx(path + "-images-idx3-ubyte")
        labels = _read_idx(path + "-labels-idx1-ubyte")
        X = imgs.reshape(imgs.shape[0], -1).astype(config.default_dtype) / 255.0
        return LabeledData(X, labels.astype(np.int32))

    @staticmethod
    def synthetic(
        n: int = 4096, num_classes: int = 10, dim: int = 784, seed: int = 0
    ) -> Tuple[LabeledData, LabeledData]:
        """Deterministic MNIST-like data: smooth per-class prototypes + noise.

        Returns (train, test). Linearly separable enough that the canonical
        RandomFFT pipeline reaches its MNIST-level accuracy bar, small enough
        to run in CI.
        """
        rng = np.random.default_rng(seed)
        # Smooth prototypes: low-frequency random images per class.
        freq = rng.normal(size=(num_classes, 8, 8))
        protos = np.zeros((num_classes, 28, 28), dtype=np.float64)
        for c in range(num_classes):
            f = np.zeros((28, 28))
            f[:8, :8] = freq[c]
            protos[c] = np.abs(np.fft.ifft2(f).real)
        protos = protos.reshape(num_classes, -1)
        protos /= protos.max(axis=1, keepdims=True)

        def make(count, seed_off):
            r = np.random.default_rng(seed + seed_off)
            y = r.integers(0, num_classes, size=count)
            X = protos[y][:, :dim] if dim <= 784 else np.pad(
                protos[y], ((0, 0), (0, dim - 784))
            )
            X = X + 0.35 * r.normal(size=X.shape)
            from keystone_tpu.loaders.synthetic import with_label_noise

            y = with_label_noise(y, num_classes, r)
            return LabeledData(
                X.astype(config.default_dtype), y.astype(np.int32)
            )

        return make(n, 1), make(max(n // 4, 256), 2)
