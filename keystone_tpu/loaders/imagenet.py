"""ImageNet loader: per-synset tars/dirs of JPEGs + label map.

Ref: src/main/scala/loaders/ImageNetLoader.scala — reads JPEGs from tar
archives (S3-friendly) with a synset→label map (SURVEY.md §2.9)
[unverified]. Decode is a host thread pool feeding fixed-size NHWC
batches; `synthetic` generates class-textured images for the no-network
environment.
"""

from __future__ import annotations

import io
import os
import tarfile
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from keystone_tpu.config import config
from keystone_tpu.loaders.labeled_data import LabeledData


from keystone_tpu.loaders.labeled_data import decode_pool_workers as _pool_workers


def _decode(buf: bytes, size: int) -> np.ndarray:
    from PIL import Image

    with Image.open(io.BytesIO(buf)) as im:
        im = im.convert("RGB").resize((size, size))
        return np.asarray(im, dtype=np.float32) / 255.0


def _decode_batch(bufs, size: int, pool) -> np.ndarray:
    """Decode a batch of jpeg buffers to (n, size, size, 3) float32.

    ``KEYSTONE_JPEG_BACKEND`` = native | pil | auto (default). auto uses the
    C++ libjpeg pool (OpenMP, no GIL — see native/src/jpeg_pool.cpp) when
    the library builds, falling back to the PIL thread pool per batch (also
    on any native decode error, e.g. a CMYK jpeg libjpeg won't convert).
    """
    backend = os.environ.get("KEYSTONE_JPEG_BACKEND", "auto")
    if backend in ("auto", "native"):
        from keystone_tpu import native

        if native.jpeg_available():
            try:
                return native.decode_jpeg_batch(list(bufs), size)
            except ValueError:
                if backend == "native":
                    raise
        elif backend == "native":
            raise RuntimeError(
                f"native jpeg pool unavailable: {native.build_error()}"
            )
    images = list(pool.map(lambda b: _decode(b, size), bufs))
    return np.stack(images).astype(np.float32)


class ImageNetLoader:
    @staticmethod
    def load_label_map(path: str) -> Dict[str, int]:
        """Lines of `<synset> <int label>`."""
        out: Dict[str, int] = {}
        with open(path) as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 2:
                    out[parts[0]] = int(parts[1])
        return out

    @staticmethod
    def iter_jobs(
        data_path: str,
        label_map: Dict[str, int],
        limit: Optional[int] = None,
        shard: Optional[Tuple[int, int]] = None,
    ):
        """Lazily yield (jpeg_bytes, label) in deterministic walk order —
        the streaming source both `load` and `stream_batches` consume.

        ``shard=(h, H)`` is the multi-host ingest seam (SURVEY.md §7 hard
        part 4): host h of H walks only entries h, h+H, h+2H, ... of the
        sorted synset list, so H hosts decode disjoint slices whose union
        is the full dataset — the per-host analog of the reference reading
        one S3 tar shard per Spark executor. Pair with
        ``utils.distributed`` (process_index/process_count) on real pods.
        """
        count = 0
        entries = sorted(os.listdir(data_path))
        if shard is not None:
            h, num_hosts = shard
            if not 0 <= h < num_hosts:
                raise ValueError(f"shard index {h} not in [0, {num_hosts})")
            entries = entries[h::num_hosts]
        for entry in entries:
            synset = entry[:-4] if entry.endswith(".tar") else entry
            label = label_map.get(synset)
            if label is None:
                continue
            full = os.path.join(data_path, entry)
            if entry.endswith(".tar"):
                with tarfile.open(full) as tf:
                    # Iterate the TarFile directly: members stream as the
                    # archive is read, so limit/prefetch consumers never
                    # wait on a full getmembers() scan of a multi-GB tar.
                    for member in tf:
                        if member.isfile():
                            f = tf.extractfile(member)
                            if f is not None:
                                yield f.read(), label
                                count += 1
                                if limit is not None and count >= limit:
                                    return
            elif os.path.isdir(full):
                for fname in sorted(os.listdir(full)):
                    with open(os.path.join(full, fname), "rb") as f:
                        yield f.read(), label
                    count += 1
                    if limit is not None and count >= limit:
                        return

    @staticmethod
    def load(
        data_path: str,
        label_map: Dict[str, int],
        size: int = 256,
        workers: Optional[int] = None,
        limit: Optional[int] = None,
        shard: Optional[Tuple[int, int]] = None,
    ) -> LabeledData:
        """`data_path`: directory of `<synset>.tar` archives or of
        `<synset>/` subdirectories of JPEGs. ``shard=(h, H)``: load only
        host h's slice of the synset list (see iter_jobs)."""
        jobs: List[Tuple[bytes, int]] = list(
            ImageNetLoader.iter_jobs(data_path, label_map, limit, shard)
        )
        with ThreadPoolExecutor(max_workers=_pool_workers(workers)) as pool:
            images = _decode_batch([b for b, _l in jobs], size, pool)
        return LabeledData(
            images.astype(config.default_dtype, copy=False),
            np.asarray([label for _b, label in jobs], dtype=np.int32),
        )

    @staticmethod
    def load_balanced_sample(
        data_path: str,
        label_map: Dict[str, int],
        total: int,
        size: int = 256,
        workers: Optional[int] = None,
    ) -> np.ndarray:
        """~total images drawn a few per synset (decoded NHWC) — the
        class-balanced fitting sample for featurizer statistics (a prefix
        of the sorted walk would be a single class)."""
        entries = [
            e
            for e in sorted(os.listdir(data_path))
            if (e[:-4] if e.endswith(".tar") else e) in label_map
        ]
        if len(entries) > total > 0:
            # Fewer samples than synsets: stride across the whole alphabet
            # instead of stopping at a prefix of it (class-coverage bias).
            stride = len(entries) / total
            entries = [entries[int(i * stride)] for i in range(total)]
        per = max(1, -(-total // max(len(entries), 1)))  # ceil
        bufs: List[bytes] = []
        for entry in entries:
            synset = entry[:-4] if entry.endswith(".tar") else entry
            for buf, _label in ImageNetLoader.iter_jobs(
                data_path, {synset: label_map[synset]}, limit=per
            ):
                bufs.append(buf)
            if len(bufs) >= total:
                break
        with ThreadPoolExecutor(max_workers=_pool_workers(workers)) as pool:
            return _decode_batch(bufs[:total], size, pool)

    @staticmethod
    def stream_batches(
        data_path: str,
        label_map: Dict[str, int],
        batch_size: int = 256,
        size: int = 256,
        workers: Optional[int] = None,
        limit: Optional[int] = None,
        prefetch: int = 2,
        shard: Optional[Tuple[int, int]] = None,
    ):
        """Decode-ahead (X, y) batch stream — the ingest-featurization
        overlap path (SURVEY.md §7 hard part 4).

        A producer thread reads bytes and decodes batches on its own pool,
        running up to ``prefetch`` batches ahead through a bounded queue, so
        JPEG decode of batch b+1 overlaps the device work on batch b. The
        yielded (NHWC float batch, int labels) pairs plug straight into the
        ``BatchIterator``/chunked-solver seam (loaders/stream.py).
        """
        import queue
        import threading

        q: "queue.Queue" = queue.Queue(maxsize=max(prefetch, 1))
        DONE = object()
        stop = threading.Event()  # set when the consumer abandons early

        def put(item) -> bool:
            """Bounded put that gives up when the consumer is gone —
            otherwise an abandoned generator strands this thread (and its
            tar handle + decode pool) blocked on a full queue forever."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                with ThreadPoolExecutor(
                    max_workers=_pool_workers(workers),
                    thread_name_prefix="keystone-decode",
                ) as pool:
                    bufs: List[bytes] = []
                    labels: List[int] = []

                    def flush() -> bool:
                        X = _decode_batch(bufs, size, pool).astype(
                            config.default_dtype, copy=False
                        )
                        y = np.asarray(labels, dtype=np.int32)
                        bufs.clear()
                        labels.clear()
                        return put((X, y))

                    for buf, label in ImageNetLoader.iter_jobs(
                        data_path, label_map, limit, shard
                    ):
                        if stop.is_set():
                            return
                        bufs.append(buf)
                        labels.append(label)
                        if len(bufs) == batch_size and not flush():
                            return
                    if bufs:
                        flush()
            except BaseException as e:  # lint: broad-ok producer-thread error of any kind re-raises in the consumer
                put(e)
            finally:
                put(DONE)  # stop-aware: never blocks an abandoned stream

        thread = threading.Thread(
            target=produce, daemon=True, name="keystone-ingest-producer"
        )
        thread.start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            # Keep draining until the producer is DEAD: a one-shot drain
            # races its in-flight put (it can land right after we empty the
            # queue, leaving a blocking put + a stranded thread).
            while thread.is_alive():
                try:
                    q.get(timeout=0.1)
                except queue.Empty:
                    pass
                thread.join(timeout=0.1)

    @staticmethod
    def synthetic(
        n: int = 512, num_classes: int = 16, size: int = 64, seed: int = 0
    ) -> Tuple[LabeledData, LabeledData]:
        """Class-textured images (distinct grating frequency/orientation per
        class + noise)."""
        yy, xx = np.mgrid[0:size, 0:size]
        angles = np.linspace(0, np.pi, num_classes, endpoint=False)
        freqs = 2 + (np.arange(num_classes) % 8)
        textures = np.stack(
            [
                0.5
                + 0.5
                * np.sin(
                    2 * np.pi * freqs[c] / size * (xx * np.cos(angles[c]) + yy * np.sin(angles[c]))
                )
                for c in range(num_classes)
            ]
        )

        def make(count, off):
            r = np.random.default_rng(seed + off)
            y = r.integers(0, num_classes, size=count)
            base = textures[y][..., None]  # (count, size, size, 1)
            tint = 0.5 + 0.5 * r.uniform(size=(count, 1, 1, 3))
            X = base * tint + 0.15 * r.normal(size=(count, size, size, 3))
            from keystone_tpu.loaders.synthetic import with_label_noise

            y = with_label_noise(y, num_classes, r)
            return LabeledData(
                np.clip(X, 0, 1).astype(config.default_dtype),
                y.astype(np.int32),
            )

        return make(n, 1), make(max(n // 4, 128), 2)
