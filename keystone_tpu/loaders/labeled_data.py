"""(data, labels) pairing used by every supervised pipeline.

Ref: src/main/scala/loaders/LabeledData.scala [unverified].
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


@dataclass
class LabeledData:
    data: Any
    labels: Any

    def __iter__(self):
        yield self.data
        yield self.labels
