"""(data, labels) pairing used by every supervised pipeline.

Ref: src/main/scala/loaders/LabeledData.scala [unverified].
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class LabeledData:
    data: Any
    labels: Any

    def __iter__(self):
        yield self.data
        yield self.labels


def decode_pool_workers(requested: Optional[int]) -> int:
    """Decode-pool size, capped at the host's core count — shared by every
    image loader. Measured on a 1-core host (NOTES_r2 §8): PIL decode
    throughput was NON-monotone in worker count (343 img/s @4, 157 @8)
    because every worker beyond the core count only adds GIL/scheduler
    thrash — decode is CPU-bound, not IO-bound. Oversubscription is never
    useful here."""
    cores = os.cpu_count() or 1
    if requested is None:
        return min(16, cores)
    return max(1, min(requested, cores))
