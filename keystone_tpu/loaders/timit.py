"""TIMIT frame-features loader + synthetic fallback.

Ref: src/main/scala/loaders/TimitFeaturesDataLoader.scala — pre-extracted
MFCC frame features (the reference consumes dumps, not raw audio) with
per-frame phone labels (SURVEY.md §2.9) [unverified].

Formats: .npz with arrays `features` (n, d) and `labels` (n,), or a pair of
CSVs (features, labels). `synthetic` generates phone-class gaussian frames
with context splicing like the canonical 440-dim MFCC-context setup.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from keystone_tpu.config import config
from keystone_tpu.loaders.labeled_data import LabeledData


class TimitFeaturesDataLoader:
    NUM_PHONES = 147  # the reference's phone-state label count

    @staticmethod
    def load(features_path: str, labels_path: str | None = None) -> LabeledData:
        if features_path.endswith(".npz"):
            data = np.load(features_path)
            return LabeledData(
                data["features"].astype(config.default_dtype),
                data["labels"].astype(np.int32),
            )
        X = np.loadtxt(features_path, delimiter=",", dtype=config.default_dtype)
        if labels_path is None:
            raise ValueError("labels_path required for CSV features")
        y = np.loadtxt(labels_path, dtype=np.int64).astype(np.int32)
        return LabeledData(X, y)

    @staticmethod
    def synthetic(
        n: int = 4096,
        num_phones: int = 24,
        frame_dim: int = 40,
        context: int = 5,
        seed: int = 0,
    ) -> Tuple[LabeledData, LabeledData]:
        """Gaussian phone clusters with ±context frame splicing
        (dim = frame_dim · (2·context + 1), like the 440-dim MFCC setup)."""
        rng = np.random.default_rng(seed)
        protos = rng.normal(scale=1.0, size=(num_phones, frame_dim))
        dim = frame_dim * (2 * context + 1)

        def make(count, off):
            r = np.random.default_rng(seed + off)
            y = r.integers(0, num_phones, size=count)
            center = protos[y] + 0.6 * r.normal(size=(count, frame_dim))
            # Neighbor frames: same phone signal, more noise (coarticulation).
            frames = [center]
            for _k in range(2 * context):
                frames.append(
                    protos[y] + 1.2 * r.normal(size=(count, frame_dim))
                )
            X = np.concatenate(frames, axis=1)
            assert X.shape[1] == dim
            from keystone_tpu.loaders.synthetic import with_label_noise

            y = with_label_noise(y, num_phones, r)
            return LabeledData(
                X.astype(config.default_dtype), y.astype(np.int32)
            )

        return make(n, 1), make(max(n // 4, 256), 2)
