"""CIFAR-10 loader: binary format + synthetic fallback.

Ref: src/main/scala/loaders/CifarLoader.scala — parses the CIFAR-10 binary
format (1 label byte + 3072 channel-major pixel bytes per record)
(SURVEY.md §2.9) [unverified]. Output here is NHWC float32 in [0, 1].

`synthetic(...)` generates a deterministic CIFAR-like set (class-specific
color/texture statistics) for the no-network environment.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from keystone_tpu.config import config
from keystone_tpu.loaders.labeled_data import LabeledData

_REC = 1 + 3 * 32 * 32


class CifarLoader:
    @staticmethod
    def load(path: str) -> LabeledData:
        raw = np.fromfile(path, dtype=np.uint8)
        if raw.size % _REC != 0:
            raise ValueError(f"{path}: not CIFAR-10 binary (size {raw.size})")
        raw = raw.reshape(-1, _REC)
        labels = raw[:, 0].astype(np.int32)
        # channel-major (3, 32, 32) → NHWC
        imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        X = imgs.astype(config.default_dtype) / 255.0
        return LabeledData(X, labels)

    @staticmethod
    def synthetic(
        n: int = 2048, num_classes: int = 10, seed: int = 0
    ) -> Tuple[LabeledData, LabeledData]:
        """Class-distinct smooth color images + noise. Returns (train, test)."""
        rng = np.random.default_rng(seed)
        # Per-class low-frequency color pattern.
        freq = rng.normal(size=(num_classes, 3, 4, 4))
        protos = np.zeros((num_classes, 32, 32, 3))
        for c in range(num_classes):
            for ch in range(3):
                f = np.zeros((32, 32))
                f[:4, :4] = freq[c, ch]
                protos[c, :, :, ch] = np.fft.ifft2(f).real
        protos -= protos.min(axis=(1, 2, 3), keepdims=True)
        protos /= protos.max(axis=(1, 2, 3), keepdims=True)

        def make(count, off):
            r = np.random.default_rng(seed + off)
            y = r.integers(0, num_classes, size=count)
            X = protos[y] + 0.25 * r.normal(size=(count, 32, 32, 3))
            from keystone_tpu.loaders.synthetic import with_label_noise

            y = with_label_noise(y, num_classes, r)
            return LabeledData(
                np.clip(X, 0, 1).astype(config.default_dtype),
                y.astype(np.int32),
            )

        return make(n, 1), make(max(n // 4, 256), 2)
