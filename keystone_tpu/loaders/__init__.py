from keystone_tpu.loaders.labeled_data import LabeledData
from keystone_tpu.loaders.csv_loader import CsvDataLoader
from keystone_tpu.loaders.mnist import MnistLoader
from keystone_tpu.loaders.stream import (
    BatchIterator,
    PrefetchIterator,
    prefetch_batches,
)

__all__ = [
    "LabeledData",
    "CsvDataLoader",
    "MnistLoader",
    "BatchIterator",
    "PrefetchIterator",
    "prefetch_batches",
]
