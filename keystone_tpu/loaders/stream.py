"""Out-of-core row-batch ingestion — the data-feeder seam.

The reference's north star keeps Spark as the data loader in front of the
TPU compute (BASELINE.json). This module is that seam: any source that can
yield (features, labels) row batches — a CSV reader, a Spark/Beam job
writing a socket or files, a tf.data/grain pipeline — plugs in as a
``BatchIterator``, and the chunk-accumulating solvers (see
linalg.normal_equations.solve_least_squares_chunked) train on datasets
whose row count exceeds host memory.

Ref: loaders/* running on Spark RDD partitions (SURVEY.md §2.9, §5
distributed-backend row) [unverified].
"""

from __future__ import annotations

import logging
import queue
import threading
import types
from contextlib import contextmanager
from typing import Any, Callable, Iterable, Iterator, Optional, Tuple

import numpy as np

from keystone_tpu.config import config
from keystone_tpu.utils.reliability import (
    RecordCorruptError,
    RetryPolicy,
    active_plan,
)

logger = logging.getLogger("keystone_tpu")

Batch = Tuple[np.ndarray, Optional[np.ndarray]]


def resolved_prefetch_depth_value(depth: Optional[int] = None) -> int:
    """THE effective prefetch depth, one resolution order for every
    consumer: an explicit ``depth`` argument > a live-exported
    KEYSTONE_PREFETCH_DEPTH (presence wins, including an explicit 0 —
    the synchronous-ingest pin) > the session resource plan's clamp
    (``PlanResourcesRule`` caps depth × measured per-batch bytes against
    the HBM budget share; the plan only ever clamps the hand-picked
    value DOWN) > ``config.prefetch_depth``."""
    if depth is not None:
        return int(depth)
    from keystone_tpu.config import resolved_prefetch_depth

    env = resolved_prefetch_depth()
    if env is not None:
        return env
    from keystone_tpu.workflow.executor import PipelineEnv

    planned = PipelineEnv.get().resource_plan.get("prefetch_depth")
    if planned:
        return min(int(planned), int(config.prefetch_depth))
    return int(config.prefetch_depth)


class PrefetchIterator:
    """Runs an upstream batch producer on a background thread into a
    bounded queue — the ingest-overlap seam of the framework.

    The reference got this for free: Spark scheduled RDD partition reads
    concurrently with executor compute. Here the producer (CSV parse,
    JPEG decode, ``map_batches`` featurization) fills a
    ``depth``-bounded queue while the consumer (a chunked solver or the
    streamed pipeline apply) drains it, so host ingest overlaps device
    compute and peak host residency stays ≤ depth queued batches (plus
    the one in each thread's hands).

    Semantics the chunked solvers rely on:

    - order-preserving and value-preserving: the consumer sees exactly
      the producer's batches, bit-identical, in order;
    - a producer exception is re-raised in the consumer at the point of
      the failed ``next()`` (not swallowed on the thread);
    - ``close()`` (also ``with``-exit, generator abandonment via
      ``__del__``) stops the producer promptly even when it is blocked
      on a full queue.

    Reliability (utils/reliability.py): transient record-read failures
    (flaky I/O, the harness's ``io`` site) are retried with backoff on
    the producer thread — value-identical on success, so the consumer
    never notices. Irrecoverably corrupt records (``RecordCorruptError``,
    the ``corrupt`` site) are quarantined — skipped and counted in
    ``reliability_counters`` — instead of killing the stream. A producer
    thread that dies without posting its DONE/ERROR sentinel (a real
    crash or the ``producer_death`` site) is detected by the consumer's
    liveness poll and restarted on the same upstream iterator, whose
    position is intact, so the stream continues bit-identically.

    Single-use, like any iterator. For a re-iterable source, wrap each
    fresh iteration (``BatchIterator.prefetch`` does this).
    """

    _ITEM, _DONE, _ERROR = 0, 1, 2
    #: How long the consumer blocks per queue poll before re-checking
    #: producer liveness: the only cost of death detection is a wakeup
    #: while STARVING (queue empty), never on the fed path.
    _POLL_S = 0.1
    _MAX_RESTARTS = 5
    _JOIN_TIMEOUT_S = 5.0

    def __init__(
        self,
        source: Iterable,
        depth: Optional[int] = None,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        depth = resolved_prefetch_depth_value(depth)
        if depth < 1:
            raise ValueError(
                f"prefetch depth must be >= 1, got {depth} (use "
                "prefetch_batches for a depth-0 synchronous passthrough)"
            )
        self.depth = depth
        #: High-water mark of queued batches — residency evidence for the
        #: ingest bench (always ≤ depth by construction).
        self.max_queued = 0
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._exhausted = False
        # The upstream iterator is held on self (not closed over by the
        # thread) so a replacement producer can resume it after a death.
        self._it: Iterator = iter(source)
        self._plan = active_plan()  # resolved ONCE: None = zero overhead
        from keystone_tpu.utils.metrics import active_tracer, metrics_registry

        # Same discipline as the fault plan: the tracer is resolved once
        # per stream, so the untraced producer/consumer pay a None check.
        self._tracer = active_tracer()
        # Process-level gauge: concurrent streams share it (last writer
        # wins on value; max is the high-water across all of them).
        self._depth_gauge = metrics_registry.gauge("prefetch.queue_depth")
        self._produced = 0
        self._retry = retry_policy if retry_policy is not None else RetryPolicy()
        self._restarts = 0
        self._quarantined = 0
        self._join_warned = False
        self._thread = self._spawn_producer()

    def _spawn_producer(self) -> threading.Thread:
        t = threading.Thread(
            target=self._produce, name="keystone-prefetch", daemon=True
        )
        t.start()
        return t

    # -- producer thread ---------------------------------------------------

    def _put(self, msg) -> bool:
        """Blocking put that stays responsive to close(); False = closed.
        When tracing, the message carries its enqueue timestamp so the
        consumer can record the cross-thread queue-residency span."""
        if self._tracer is not None:
            msg = msg + (self._tracer.now(),)
        while not self._stop.is_set():
            try:
                self._queue.put(msg, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _quarantine(self, exc: BaseException) -> None:
        from keystone_tpu.utils.metrics import reliability_counters

        reliability_counters.bump("records_quarantined")
        self._quarantined += 1
        log = logger.warning if self._quarantined <= 3 else logger.debug
        log("prefetch: quarantined corrupt record #%d (%s)",
            self._quarantined, exc)

    def _produce(self) -> None:
        it, plan, retry = self._it, self._plan, self._retry
        # A generator whose body raises is CLOSED by the raise, so only
        # non-generator iterators can meaningfully retry / survive
        # ``next()`` failures; harness faults fire at the post-fetch gate
        # and are recoverable for every source.
        durable_src = not isinstance(it, types.GeneratorType)
        tr = self._tracer
        try:
            while not self._stop.is_set():
                if plan is not None and plan.check("producer_death"):
                    # Exit with NO sentinel — exactly what a killed thread
                    # leaves behind; the consumer's liveness poll recovers.
                    return
                t0 = tr.now() if tr is not None else 0
                try:
                    if durable_src:
                        item = retry.call(
                            lambda: next(it),
                            site="record_read", counter="io_retries",
                        )
                    else:
                        item = next(it)
                    if plan is not None:
                        # The injected-io gate models a flaky read: a
                        # retry re-reads the SAME record, value-identical.
                        retry.call(
                            lambda: plan.maybe_raise("io"),
                            site="record_read", counter="io_retries",
                        )
                        plan.maybe_raise("corrupt")
                except StopIteration:
                    break
                except RecordCorruptError as exc:
                    self._quarantine(exc)
                    continue
                if tr is not None:
                    tr.record(
                        "prefetch.produce", "stream", t0, batch=self._produced
                    )
                self._produced += 1
                if not self._put((self._ITEM, item)):
                    return
                depth_now = self._queue.qsize()
                if depth_now > self.max_queued:
                    self.max_queued = depth_now
                self._depth_gauge.set(depth_now)
        except BaseException as exc:  # lint: broad-ok producer error of any kind re-raises in the consumer
            self._put((self._ERROR, exc))
        else:
            self._put((self._DONE, None))

    # -- consumer side -----------------------------------------------------

    def __iter__(self) -> "PrefetchIterator":
        return self

    def _restart_producer(self) -> None:
        """Replace a producer that died without a sentinel. The upstream
        iterator's position is intact (the fault fires between records),
        so the replacement continues the stream bit-identically."""
        from keystone_tpu.utils.metrics import reliability_counters

        self._restarts += 1
        reliability_counters.bump("producer_restarts")
        if self._restarts > self._MAX_RESTARTS:
            self._exhausted = True
            raise RuntimeError(
                f"prefetch producer died {self._restarts} times without "
                "reporting an error; giving up on the stream"
            )
        logger.warning(
            "prefetch producer died silently; restarting (%d/%d)",
            self._restarts, self._MAX_RESTARTS,
        )
        self._thread = self._spawn_producer()

    def __next__(self) -> Any:
        if self._exhausted:
            raise StopIteration
        tr = self._tracer
        t_wait = tr.now() if tr is not None else 0
        while True:
            try:
                msg = self._queue.get(timeout=self._POLL_S)
                break
            except queue.Empty:
                if self._stop.is_set() or self._thread.is_alive():
                    continue
                if not self._queue.empty():
                    continue  # died after a final put: drain it first
                self._restart_producer()
        kind, val = msg[0], msg[1]
        if tr is not None and kind == self._ITEM:
            now = tr.now()
            # How long the consumer stood starved at the queue...
            tr.record("prefetch.consumer_wait", "stream", t_wait, now)
            # ...and how long the batch sat queued (cross-thread span:
            # producer enqueue timestamp → this dequeue).
            if len(msg) > 2:
                tr.record("prefetch.queue_residency", "stream", msg[2], now)
        if self._stop.is_set():
            # close() ran while we waited: whatever we were handed (a
            # stale item the producer's in-flight put landed after the
            # drain, or the wake-up sentinel) is post-close and must not
            # surface as data.
            self._exhausted = True
            raise StopIteration
        if kind == self._ITEM:
            return val
        self._exhausted = True
        self._depth_gauge.set(0)  # the stream is over; depth reads current
        self._join_producer()
        if kind == self._ERROR:
            raise val
        raise StopIteration

    def _join_producer(self) -> None:
        """Join the producer with a bounded wait; a thread still alive
        after the timeout is LEAKED (most likely blocked in upstream I/O
        that honors no deadline) — warn once, visibly, instead of
        silently abandoning it. Daemonic, so it can't block exit."""
        self._thread.join(timeout=self._JOIN_TIMEOUT_S)
        if self._thread.is_alive() and not self._join_warned:
            self._join_warned = True
            from keystone_tpu.utils.metrics import reliability_counters

            reliability_counters.bump("producer_leaks")
            logger.warning(
                "prefetch producer thread %r still alive %.0fs after "
                "close/stop — likely blocked in upstream I/O; leaking it "
                "(daemon thread, will not block interpreter exit)",
                self._thread.name, self._JOIN_TIMEOUT_S,
            )

    def close(self) -> None:
        """Stop the producer and release the queue. Idempotent; called on
        ``with``-exit and garbage collection, so an abandoned consumer
        (early break, exception) can't leave the thread parked on a full
        queue holding file handles."""
        self._exhausted = True
        self._stop.set()
        self._depth_gauge.set(0)
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._join_producer()
        if not self._thread.is_alive():
            # Release the upstream promptly (run generator finalizers,
            # close file handles) — holding self._it for restartability
            # otherwise defers that to GC. Only once the producer is
            # truly gone: closing a generator another thread is executing
            # raises.
            close_upstream = getattr(self._it, "close", None)
            if close_upstream is not None:
                try:
                    close_upstream()
                except Exception:  # lint: broad-ok upstream close is courtesy cleanup; a failing finalizer must not mask the stream result
                    pass
        # Wake any consumer still parked in queue.get() (cross-thread
        # close): the sentinel turns its wait into StopIteration.
        try:
            self._queue.put_nowait((self._DONE, None))
        except queue.Full:
            pass

    def __enter__(self) -> "PrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # lint: broad-ok GC/teardown finalizer: anything may be half-torn-down
            pass


def prefetch_batches(batches: Iterable, depth: Optional[int] = None):
    """``PrefetchIterator`` behind the ``config.prefetch_depth`` knob.

    depth > 0 wraps ``batches`` in a background-thread prefetcher; depth 0
    returns ``batches`` itself — a true passthrough, so the synchronous
    path is byte-for-byte today's behavior, not a degenerate queue.
    Depth resolution (env pin > session plan clamp > config):
    ``resolved_prefetch_depth_value``."""
    depth = resolved_prefetch_depth_value(depth)
    if depth <= 0:
        return batches
    return PrefetchIterator(batches, depth)


@contextmanager
def prefetched(batches: Iterable, depth: Optional[int] = None):
    """``prefetch_batches`` as a context manager: the one shutdown idiom
    for every consumer — closes the prefetcher (stopping its thread) on
    exit, and is a no-op close for the depth-0 passthrough."""
    src = prefetch_batches(batches, depth)
    try:
        yield src
    finally:
        close = getattr(src, "close", None)
        if close is not None:
            close()


class BatchIterator:
    """Re-iterable source of (features, labels-or-None) row batches."""

    def __init__(self, factory: Callable[[], Iterable[Batch]]):
        self._factory = factory

    def __iter__(self) -> Iterator[Batch]:
        return iter(self._factory())

    @staticmethod
    def from_arrays(X, y=None, batch_rows: int = 4096) -> "BatchIterator":
        X = np.asarray(X)
        y_arr = None if y is None else np.asarray(y)

        def gen():
            for s in range(0, X.shape[0], batch_rows):
                e = min(s + batch_rows, X.shape[0])
                yield X[s:e], None if y_arr is None else y_arr[s:e]

        return BatchIterator(gen)

    @staticmethod
    def from_csv(
        path: str,
        label_col: Optional[int] = 0,
        batch_rows: int = 4096,
        label_dtype=np.int32,
    ) -> "BatchIterator":
        """Stream a CSV in row chunks without loading it whole.

        ``label_dtype`` defaults to int32 (class labels); pass a float
        dtype for regression targets — int truncation of real-valued
        targets would silently corrupt the solve.
        """

        def gen():
            rows, labels = [], []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    vals = [float(v) for v in line.split(",")]
                    if label_col is not None:
                        labels.append(vals.pop(label_col))
                    rows.append(vals)
                    if len(rows) == batch_rows:
                        yield _emit(rows, labels, label_col)
                        rows, labels = [], []
            if rows:
                yield _emit(rows, labels, label_col)

        def _emit(rows, labels, label_col):
            X = np.asarray(rows, dtype=config.default_dtype)
            y = (
                None
                if label_col is None
                else np.asarray(labels, dtype=label_dtype)
            )
            return X, y

        return BatchIterator(gen)

    def map_batches(self, fn: Callable[[np.ndarray], np.ndarray]) -> "BatchIterator":
        """Apply a featurization function to every feature batch (e.g. a
        fitted pipeline's transformer chain)."""

        def gen():
            for X, y in self:
                yield fn(X), y

        return BatchIterator(gen)

    def prefetch(self, depth: Optional[int] = None) -> "BatchIterator":
        """Re-iterable prefetching view: every fresh iteration runs the
        producer chain (including any ``map_batches`` upstream) on its own
        background thread, ``depth`` batches ahead (default
        ``config.prefetch_depth``; 0 = synchronous passthrough)."""

        return BatchIterator(lambda: prefetch_batches(iter(self), depth))
