"""Out-of-core row-batch ingestion — the data-feeder seam.

The reference's north star keeps Spark as the data loader in front of the
TPU compute (BASELINE.json). This module is that seam: any source that can
yield (features, labels) row batches — a CSV reader, a Spark/Beam job
writing a socket or files, a tf.data/grain pipeline — plugs in as a
``BatchIterator``, and the chunk-accumulating solvers (see
linalg.normal_equations.solve_least_squares_chunked) train on datasets
whose row count exceeds host memory.

Ref: loaders/* running on Spark RDD partitions (SURVEY.md §2.9, §5
distributed-backend row) [unverified].
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, Optional, Tuple

import numpy as np

from keystone_tpu.config import config

Batch = Tuple[np.ndarray, Optional[np.ndarray]]


class BatchIterator:
    """Re-iterable source of (features, labels-or-None) row batches."""

    def __init__(self, factory: Callable[[], Iterable[Batch]]):
        self._factory = factory

    def __iter__(self) -> Iterator[Batch]:
        return iter(self._factory())

    @staticmethod
    def from_arrays(X, y=None, batch_rows: int = 4096) -> "BatchIterator":
        X = np.asarray(X)
        y_arr = None if y is None else np.asarray(y)

        def gen():
            for s in range(0, X.shape[0], batch_rows):
                e = min(s + batch_rows, X.shape[0])
                yield X[s:e], None if y_arr is None else y_arr[s:e]

        return BatchIterator(gen)

    @staticmethod
    def from_csv(
        path: str,
        label_col: Optional[int] = 0,
        batch_rows: int = 4096,
        label_dtype=np.int32,
    ) -> "BatchIterator":
        """Stream a CSV in row chunks without loading it whole.

        ``label_dtype`` defaults to int32 (class labels); pass a float
        dtype for regression targets — int truncation of real-valued
        targets would silently corrupt the solve.
        """

        def gen():
            rows, labels = [], []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    vals = [float(v) for v in line.split(",")]
                    if label_col is not None:
                        labels.append(vals.pop(label_col))
                    rows.append(vals)
                    if len(rows) == batch_rows:
                        yield _emit(rows, labels, label_col)
                        rows, labels = [], []
            if rows:
                yield _emit(rows, labels, label_col)

        def _emit(rows, labels, label_col):
            X = np.asarray(rows, dtype=config.default_dtype)
            y = (
                None
                if label_col is None
                else np.asarray(labels, dtype=label_dtype)
            )
            return X, y

        return BatchIterator(gen)

    def map_batches(self, fn: Callable[[np.ndarray], np.ndarray]) -> "BatchIterator":
        """Apply a featurization function to every feature batch (e.g. a
        fitted pipeline's transformer chain)."""

        def gen():
            for X, y in self:
                yield fn(X), y

        return BatchIterator(gen)
