"""Out-of-core row-batch ingestion — the data-feeder seam.

The reference's north star keeps Spark as the data loader in front of the
TPU compute (BASELINE.json). This module is that seam: any source that can
yield (features, labels) row batches — a CSV reader, a Spark/Beam job
writing a socket or files, a tf.data/grain pipeline — plugs in as a
``BatchIterator``, and the chunk-accumulating solvers (see
linalg.normal_equations.solve_least_squares_chunked) train on datasets
whose row count exceeds host memory.

Ref: loaders/* running on Spark RDD partitions (SURVEY.md §2.9, §5
distributed-backend row) [unverified].
"""

from __future__ import annotations

import queue
import threading
from contextlib import contextmanager
from typing import Any, Callable, Iterable, Iterator, Optional, Tuple

import numpy as np

from keystone_tpu.config import config

Batch = Tuple[np.ndarray, Optional[np.ndarray]]


class PrefetchIterator:
    """Runs an upstream batch producer on a background thread into a
    bounded queue — the ingest-overlap seam of the framework.

    The reference got this for free: Spark scheduled RDD partition reads
    concurrently with executor compute. Here the producer (CSV parse,
    JPEG decode, ``map_batches`` featurization) fills a
    ``depth``-bounded queue while the consumer (a chunked solver or the
    streamed pipeline apply) drains it, so host ingest overlaps device
    compute and peak host residency stays ≤ depth queued batches (plus
    the one in each thread's hands).

    Semantics the chunked solvers rely on:

    - order-preserving and value-preserving: the consumer sees exactly
      the producer's batches, bit-identical, in order;
    - a producer exception is re-raised in the consumer at the point of
      the failed ``next()`` (not swallowed on the thread);
    - ``close()`` (also ``with``-exit, generator abandonment via
      ``__del__``) stops the producer promptly even when it is blocked
      on a full queue.

    Single-use, like any iterator. For a re-iterable source, wrap each
    fresh iteration (``BatchIterator.prefetch`` does this).
    """

    _ITEM, _DONE, _ERROR = 0, 1, 2

    def __init__(self, source: Iterable, depth: Optional[int] = None):
        if depth is None:
            depth = config.prefetch_depth
        depth = int(depth)
        if depth < 1:
            raise ValueError(
                f"prefetch depth must be >= 1, got {depth} (use "
                "prefetch_batches for a depth-0 synchronous passthrough)"
            )
        self.depth = depth
        #: High-water mark of queued batches — residency evidence for the
        #: ingest bench (always ≤ depth by construction).
        self.max_queued = 0
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._exhausted = False
        self._thread = threading.Thread(
            target=self._produce,
            args=(iter(source),),
            name="keystone-prefetch",
            daemon=True,
        )
        self._thread.start()

    # -- producer thread ---------------------------------------------------

    def _put(self, msg) -> bool:
        """Blocking put that stays responsive to close(); False = closed."""
        while not self._stop.is_set():
            try:
                self._queue.put(msg, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self, it: Iterator) -> None:
        try:
            for item in it:
                if not self._put((self._ITEM, item)):
                    return
                depth_now = self._queue.qsize()
                if depth_now > self.max_queued:
                    self.max_queued = depth_now
        except BaseException as exc:  # surfaced in the consumer
            self._put((self._ERROR, exc))
        else:
            self._put((self._DONE, None))

    # -- consumer side -----------------------------------------------------

    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self) -> Any:
        if self._exhausted:
            raise StopIteration
        kind, val = self._queue.get()
        if self._stop.is_set():
            # close() ran while we waited: whatever we were handed (a
            # stale item the producer's in-flight put landed after the
            # drain, or the wake-up sentinel) is post-close and must not
            # surface as data.
            self._exhausted = True
            raise StopIteration
        if kind == self._ITEM:
            return val
        self._exhausted = True
        self._thread.join(timeout=5.0)
        if kind == self._ERROR:
            raise val
        raise StopIteration

    def close(self) -> None:
        """Stop the producer and release the queue. Idempotent; called on
        ``with``-exit and garbage collection, so an abandoned consumer
        (early break, exception) can't leave the thread parked on a full
        queue holding file handles."""
        self._exhausted = True
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
        # Wake any consumer still parked in queue.get() (cross-thread
        # close): the sentinel turns its wait into StopIteration.
        try:
            self._queue.put_nowait((self._DONE, None))
        except queue.Full:
            pass

    def __enter__(self) -> "PrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def prefetch_batches(batches: Iterable, depth: Optional[int] = None):
    """``PrefetchIterator`` behind the ``config.prefetch_depth`` knob.

    depth > 0 wraps ``batches`` in a background-thread prefetcher; depth 0
    returns ``batches`` itself — a true passthrough, so the synchronous
    path is byte-for-byte today's behavior, not a degenerate queue."""
    depth = config.prefetch_depth if depth is None else int(depth)
    if depth <= 0:
        return batches
    return PrefetchIterator(batches, depth)


@contextmanager
def prefetched(batches: Iterable, depth: Optional[int] = None):
    """``prefetch_batches`` as a context manager: the one shutdown idiom
    for every consumer — closes the prefetcher (stopping its thread) on
    exit, and is a no-op close for the depth-0 passthrough."""
    src = prefetch_batches(batches, depth)
    try:
        yield src
    finally:
        close = getattr(src, "close", None)
        if close is not None:
            close()


class BatchIterator:
    """Re-iterable source of (features, labels-or-None) row batches."""

    def __init__(self, factory: Callable[[], Iterable[Batch]]):
        self._factory = factory

    def __iter__(self) -> Iterator[Batch]:
        return iter(self._factory())

    @staticmethod
    def from_arrays(X, y=None, batch_rows: int = 4096) -> "BatchIterator":
        X = np.asarray(X)
        y_arr = None if y is None else np.asarray(y)

        def gen():
            for s in range(0, X.shape[0], batch_rows):
                e = min(s + batch_rows, X.shape[0])
                yield X[s:e], None if y_arr is None else y_arr[s:e]

        return BatchIterator(gen)

    @staticmethod
    def from_csv(
        path: str,
        label_col: Optional[int] = 0,
        batch_rows: int = 4096,
        label_dtype=np.int32,
    ) -> "BatchIterator":
        """Stream a CSV in row chunks without loading it whole.

        ``label_dtype`` defaults to int32 (class labels); pass a float
        dtype for regression targets — int truncation of real-valued
        targets would silently corrupt the solve.
        """

        def gen():
            rows, labels = [], []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    vals = [float(v) for v in line.split(",")]
                    if label_col is not None:
                        labels.append(vals.pop(label_col))
                    rows.append(vals)
                    if len(rows) == batch_rows:
                        yield _emit(rows, labels, label_col)
                        rows, labels = [], []
            if rows:
                yield _emit(rows, labels, label_col)

        def _emit(rows, labels, label_col):
            X = np.asarray(rows, dtype=config.default_dtype)
            y = (
                None
                if label_col is None
                else np.asarray(labels, dtype=label_dtype)
            )
            return X, y

        return BatchIterator(gen)

    def map_batches(self, fn: Callable[[np.ndarray], np.ndarray]) -> "BatchIterator":
        """Apply a featurization function to every feature batch (e.g. a
        fitted pipeline's transformer chain)."""

        def gen():
            for X, y in self:
                yield fn(X), y

        return BatchIterator(gen)

    def prefetch(self, depth: Optional[int] = None) -> "BatchIterator":
        """Re-iterable prefetching view: every fresh iteration runs the
        producer chain (including any ``map_batches`` upstream) on its own
        background thread, ``depth`` batches ahead (default
        ``config.prefetch_depth``; 0 = synchronous passthrough)."""

        return BatchIterator(lambda: prefetch_batches(iter(self), depth))
