"""Amazon reviews loader: JSON-lines/CSV reviews + synthetic fallback.

Ref: src/main/scala/loaders/AmazonReviewsDataLoader.scala — star rating →
binary label (> 3.5 positive) (SURVEY.md §2.9) [unverified].
"""

from __future__ import annotations

import csv
import json
from typing import Tuple

import numpy as np

from keystone_tpu.loaders.labeled_data import LabeledData

_POS = ["great", "excellent", "love", "perfect", "best", "amazing", "works"]
_NEG = ["terrible", "broke", "waste", "refund", "awful", "disappointed", "poor"]
_FILLER = ["the", "product", "i", "it", "this", "was", "and", "my", "to", "use"]


class AmazonReviewsDataLoader:
    THRESHOLD = 3.5

    @staticmethod
    def load(path: str) -> LabeledData:
        """JSON-lines ({"reviewText", "overall"}) or CSV (text, stars)."""
        texts, labels = [], []
        with open(path, errors="replace") as f:
            if path.endswith(".json") or path.endswith(".jsonl"):
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    texts.append(rec["reviewText"])
                    labels.append(
                        1 if float(rec["overall"]) > AmazonReviewsDataLoader.THRESHOLD else 0
                    )
            else:
                for row in csv.reader(f):
                    if len(row) < 2:
                        continue
                    texts.append(row[0])
                    labels.append(
                        1 if float(row[1]) > AmazonReviewsDataLoader.THRESHOLD else 0
                    )
        return LabeledData(texts, np.asarray(labels, dtype=np.int32))

    @staticmethod
    def synthetic(
        n: int = 1000, seed: int = 0
    ) -> Tuple[LabeledData, LabeledData]:
        def make(count, off):
            r = np.random.default_rng(seed + off)
            texts, labels = [], []
            for _ in range(count):
                pos = bool(r.integers(0, 2))
                vocab = _POS if pos else _NEG
                words = list(r.choice(vocab, size=r.integers(3, 8))) + list(
                    r.choice(_FILLER, size=r.integers(8, 16))
                )
                # A little label noise via cross-polarity words.
                if r.uniform() < 0.3:
                    words += list(r.choice(_NEG if pos else _POS, size=1))
                r.shuffle(words)
                texts.append(" ".join(words))
                labels.append(1 if pos else 0)
            from keystone_tpu.loaders.synthetic import with_label_noise

            labels = with_label_noise(
                np.asarray(labels, dtype=np.int32), 2, r
            )
            return LabeledData(texts, labels)

        return make(n, 1), make(max(n // 4, 100), 2)
