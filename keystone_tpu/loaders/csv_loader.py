"""Generic CSV → array loader.

Ref: src/main/scala/loaders/CsvDataLoader.scala — parse each line into a
dense vector [unverified]. Host-side NumPy parse; arrays then flow to the
device through the pipeline.
"""

from __future__ import annotations

import numpy as np

from keystone_tpu.config import config
from keystone_tpu.loaders.labeled_data import LabeledData


class CsvDataLoader:
    @staticmethod
    def load(path: str, dtype=None) -> np.ndarray:
        return np.loadtxt(path, delimiter=",", dtype=dtype or config.default_dtype)

    @staticmethod
    def load_labeled(path: str, label_col: int = 0) -> LabeledData:
        """CSV with a label column (first by default, MNIST-CSV style)."""
        raw = np.loadtxt(path, delimiter=",", dtype=np.float64)
        labels = raw[:, label_col].astype(np.int32)
        data = np.delete(raw, label_col, axis=1).astype(config.default_dtype)
        return LabeledData(data, labels)
