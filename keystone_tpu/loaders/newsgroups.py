"""20 Newsgroups loader: directory-per-class text + synthetic fallback.

Ref: src/main/scala/loaders/NewsgroupsDataLoader.scala (SURVEY.md §2.9)
[unverified].
"""

from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np

from keystone_tpu.loaders.labeled_data import LabeledData

# Class-specific vocabulary for the synthetic corpus generator.
_TOPICS = [
    ["space", "orbit", "rocket", "nasa", "launch", "moon", "satellite"],
    ["hockey", "goal", "puck", "team", "season", "playoff", "skate"],
    ["windows", "driver", "file", "disk", "program", "install", "boot"],
    ["car", "engine", "dealer", "mileage", "brake", "tire", "drive"],
    ["god", "faith", "church", "belief", "scripture", "moral", "prayer"],
]
_COMMON = ["the", "a", "of", "to", "and", "in", "is", "that", "it", "for"]


class NewsgroupsDataLoader:
    @staticmethod
    def load(
        path: str, classes: List[str] | None = None
    ) -> Tuple[LabeledData, List[str]]:
        """Directory-per-class layout: path/<group>/<doc files>.

        Pass the training split's `classes` when loading a test split so the
        label indices align; unknown subdirectories then raise instead of
        silently shifting every label.

        Returns (LabeledData(texts, int labels), class names).
        """
        found = sorted(
            d
            for d in os.listdir(path)
            if os.path.isdir(os.path.join(path, d))
        )
        if classes is None:
            classes = found
        else:
            unknown = set(found) - set(classes)
            if unknown:
                raise ValueError(
                    f"{path} has classes {sorted(unknown)} not present in the "
                    f"training class list {classes}"
                )
        index = {c: i for i, c in enumerate(classes)}
        texts: List[str] = []
        labels: List[int] = []
        for cls in found:
            ci = index[cls]
            cdir = os.path.join(path, cls)
            for fname in sorted(os.listdir(cdir)):
                fpath = os.path.join(cdir, fname)
                if os.path.isfile(fpath):
                    with open(fpath, errors="replace") as f:
                        texts.append(f.read())
                    labels.append(ci)
        return (
            LabeledData(texts, np.asarray(labels, dtype=np.int32)),
            list(classes),
        )

    @staticmethod
    def synthetic(
        n: int = 1000, num_classes: int = 5, seed: int = 0
    ) -> Tuple[LabeledData, LabeledData, List[str]]:
        """Deterministic topic-mixture corpus. Returns (train, test, names)."""
        num_classes = min(num_classes, len(_TOPICS))

        def make(count, off):
            r = np.random.default_rng(seed + off)
            texts, labels = [], []
            for _ in range(count):
                c = int(r.integers(0, num_classes))
                words = list(
                    r.choice(_TOPICS[c], size=r.integers(8, 20))
                ) + list(r.choice(_COMMON, size=r.integers(10, 25)))
                r.shuffle(words)
                texts.append(" ".join(words))
                labels.append(c)
            from keystone_tpu.loaders.synthetic import with_label_noise

            labels = with_label_noise(
                np.asarray(labels, dtype=np.int32), num_classes, r
            )
            return LabeledData(texts, labels)

        names = [t[0] for t in _TOPICS[:num_classes]]
        return make(n, 1), make(max(n // 4, 100), 2), names
