"""Pascal VOC loader: JPEG images + multi-label annotations.

Ref: src/main/scala/loaders/VOCLoader.scala — VOC2007 images with
20-class multi-label annotations (SURVEY.md §2.9) [unverified]. JPEG
decode via PIL on a host thread pool (the javax.imageio analog);
`synthetic` generates class-colored shape images for the no-network
environment.
"""

from __future__ import annotations

import os
import xml.etree.ElementTree as ET
from concurrent.futures import ThreadPoolExecutor
from typing import List, Sequence, Tuple

import numpy as np

from keystone_tpu.config import config
from keystone_tpu.loaders.labeled_data import LabeledData

VOC_CLASSES = [
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
]


def _decode_resize(path: str, size: int) -> np.ndarray:
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB").resize((size, size))
        return np.asarray(im, dtype=np.float32) / 255.0


class VOCLoader:
    @staticmethod
    def load(
        image_dir: str,
        annotation_dir: str,
        size: int = 128,
        workers: int = 16,
        classes: Sequence[str] = tuple(VOC_CLASSES),
    ) -> LabeledData:
        """Returns LabeledData(NHWC images, (n, C) binary multilabels)."""
        from keystone_tpu.loaders.labeled_data import decode_pool_workers

        workers = decode_pool_workers(workers)
        index = {c: i for i, c in enumerate(classes)}
        names = sorted(
            f[:-4] for f in os.listdir(annotation_dir) if f.endswith(".xml")
        )
        labels = np.zeros((len(names), len(classes)), dtype=np.int32)
        paths: List[str] = []
        for i, name in enumerate(names):
            tree = ET.parse(os.path.join(annotation_dir, name + ".xml"))
            for obj in tree.findall(".//object/name"):
                ci = index.get(obj.text or "")
                if ci is not None:
                    labels[i, ci] = 1
            paths.append(os.path.join(image_dir, name + ".jpg"))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            images = list(pool.map(lambda p: _decode_resize(p, size), paths))
        return LabeledData(
            np.stack(images).astype(config.default_dtype), labels
        )

    # Expected present classes per synthetic image: `synthetic` draws
    # r.integers(1, 3) — 1 or 2 present classes, uniformly — so E = 1.5.
    # Exported so the acceptance harness's mAP noise band derives its
    # prevalence from the same sampling rule it bounds (ADVICE r5).
    SYNTH_PRESENT_CLASSES_MEAN = 1.5

    @staticmethod
    def synthetic(
        n: int = 256, num_classes: int = 6, size: int = 64, seed: int = 0
    ) -> Tuple[LabeledData, LabeledData]:
        """Multi-label images: each present class adds its own textured
        rectangle; labels are the class-presence vector."""
        rng = np.random.default_rng(seed)
        # Per-class texture: oriented gratings at distinct frequencies.
        yy, xx = np.mgrid[0:size, 0:size]
        textures = [
            0.5 + 0.5 * np.sin(2 * np.pi * ((c + 2) / 16.0) * (xx * np.cos(a) + yy * np.sin(a)))
            for c, a in zip(range(num_classes), np.linspace(0, np.pi, num_classes, endpoint=False))
        ]

        def make(count, off):
            r = np.random.default_rng(seed + off)
            X = 0.1 * r.uniform(size=(count, size, size, 3))
            Y = np.zeros((count, num_classes), dtype=np.int32)
            for i in range(count):
                present = r.choice(
                    num_classes, size=r.integers(1, 3), replace=False
                )
                for c in present:
                    Y[i, c] = 1
                    s = size // 2
                    top = int(r.integers(0, size - s))
                    left = int(r.integers(0, size - s))
                    patch = textures[c][top : top + s, left : left + s]
                    ch = c % 3
                    X[i, top : top + s, left : left + s, ch] += patch
            from keystone_tpu.loaders.synthetic import with_label_noise

            Y = with_label_noise(Y, num_classes, r)
            return LabeledData(
                np.clip(X, 0, 1).astype(config.default_dtype), Y
            )

        return make(n, 1), make(max(n // 4, 64), 2)
