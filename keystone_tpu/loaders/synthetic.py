"""Controlled class overlap for the synthetic datasets.

The acceptance harness (tools/acceptance.py --synthetic) must validate
QUALITY, not just plumbing: perfectly-separable generators score 1.0
against any floor, so a solver regression costing ten points would still
pass (VERDICT r3 weak #4). Flipping a known fraction of labels to a random
other class injects a KNOWN Bayes floor — with flip rate p and C classes,
even a perfect model scores ≈ (1-p) + p/C on the (also noisy) test labels
— so every metric must land strictly inside (floor, ceiling) and the
acceptance table binds in both directions.

The knob is the KEYSTONE_SYNTH_LABEL_NOISE env var (a fraction, default
off) so the generators stay deterministic and noise-free for the unit
suite; only the acceptance harness turns it on.
"""

from __future__ import annotations

import os

import numpy as np


def label_noise_rate() -> float:
    try:
        return float(os.environ.get("KEYSTONE_SYNTH_LABEL_NOISE", "") or 0.0)
    except ValueError:
        return 0.0


def with_label_noise(y: np.ndarray, num_classes: int, rng) -> np.ndarray:
    """Flip a KEYSTONE_SYNTH_LABEL_NOISE fraction of labels.

    Integer label vectors move to a uniformly random OTHER class (the
    classic symmetric-noise model with its closed-form Bayes accuracy).
    Multi-label indicator matrices (2-d, e.g. VOC presence vectors) flip
    each entry independently with the same probability. ``rng`` is the
    generator's own per-split Generator, so train/test noise stays
    deterministic per seed."""
    p = label_noise_rate()
    if p <= 0.0:
        return y
    y = np.array(y, copy=True)
    if y.ndim == 2:
        flip = rng.uniform(size=y.shape) < p
        y[flip] = 1 - y[flip]
        return y
    flip = rng.uniform(size=y.shape[0]) < p
    shift = rng.integers(1, max(num_classes, 2), size=y.shape[0])
    y[flip] = (y[flip] + shift[flip]) % max(num_classes, 2)
    return y
