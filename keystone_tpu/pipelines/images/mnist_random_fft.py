"""MnistRandomFFT — the canonical MNIST pipeline.

Ref: src/main/scala/pipelines/images/mnist/MnistRandomFFT.scala
(BASELINE.json config: "random-Fourier features + LinearMapEstimator"):
for each of `num_ffts` blocks, RandomSignNode → PaddedFFT → LinearRectifier;
blocks merged with Pipeline.gather; LinearMapEstimator on the gathered
features; MaxClassifier [unverified].

TPU notes: the whole featurization (sign flips, batched FFTs, rectifier,
concat) fuses into one XLA computation by the chain-fusion rule + gather
node; the solve is the psum-reduced distributed ridge solver.
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass
from typing import Optional

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders import MnistLoader
from keystone_tpu.nodes.learning import LinearMapEstimator
from keystone_tpu.nodes.stats import LinearRectifier, PaddedFFT, RandomSignNode
from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier
from keystone_tpu.workflow import Pipeline


@dataclass
class MnistRandomFFTConfig:
    train_path: Optional[str] = None
    test_path: Optional[str] = None
    num_ffts: int = 4
    lam: float = 0.1
    seed: int = 0
    num_classes: int = 10
    synthetic_n: int = 4096  # used when no data paths are given


def build_pipeline(conf: MnistRandomFFTConfig, train, train_labels) -> Pipeline:
    dim = train.shape[1]
    branches = [
        RandomSignNode.create(dim, seed=conf.seed + i)
        .and_then(PaddedFFT())
        .and_then(LinearRectifier())
        for i in range(conf.num_ffts)
    ]
    features = Pipeline.gather(branches)
    targets = ClassLabelIndicators(conf.num_classes)(train_labels)
    return features.and_then(
        LinearMapEstimator(lam=conf.lam), train, targets
    ).and_then(MaxClassifier())


def run(conf: MnistRandomFFTConfig) -> dict:
    t0 = time.perf_counter()
    if conf.train_path:
        if not conf.test_path:
            raise ValueError(
                "--test is required when --train is given (evaluating on the "
                "training set would report memorization as test accuracy)"
            )
        train = MnistLoader.load(conf.train_path)
        test = MnistLoader.load(conf.test_path)
    else:
        train, test = MnistLoader.synthetic(n=conf.synthetic_n, seed=conf.seed)
    t_load = time.perf_counter() - t0

    t0 = time.perf_counter()
    pipeline = build_pipeline(conf, train.data, train.labels)
    predictions = pipeline(test.data).get()  # fits lazily, then predicts
    t_fit = time.perf_counter() - t0

    metrics = MulticlassClassifierEvaluator(conf.num_classes).evaluate(
        predictions, test.labels
    )
    train_pred = pipeline(train.data).get()
    train_metrics = MulticlassClassifierEvaluator(conf.num_classes).evaluate(
        train_pred, train.labels
    )
    return {
        "test_accuracy": metrics.total_accuracy,
        "train_accuracy": train_metrics.total_accuracy,
        "macro_f1": metrics.macro_f1,
        "load_seconds": t_load,
        "fit_predict_seconds": t_fit,
        "summary": metrics.summary(),
    }


def main(argv=None):
    from keystone_tpu.utils.platform import setup_platform

    setup_platform()
    p = argparse.ArgumentParser(description="MnistRandomFFT pipeline")
    p.add_argument("--train", dest="train_path")
    p.add_argument("--test", dest="test_path")
    p.add_argument("--num-ffts", type=int, default=4)
    p.add_argument("--lam", type=float, default=0.1)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--synthetic-n", type=int, default=4096)
    a = p.parse_args(argv)
    conf = MnistRandomFFTConfig(
        train_path=a.train_path,
        test_path=a.test_path,
        num_ffts=a.num_ffts,
        lam=a.lam,
        seed=a.seed,
        synthetic_n=a.synthetic_n,
    )
    out = run(conf)
    print(out["summary"])
    print(
        f"train acc {out['train_accuracy']:.4f} | "
        f"load {out['load_seconds']:.2f}s | fit+predict {out['fit_predict_seconds']:.2f}s"
    )
    return out


if __name__ == "__main__":
    main()
