"""RandomPatchCifar — the canonical CIFAR pipeline.

Ref: src/main/scala/pipelines/images/cifar/RandomPatchCifar.scala
(BASELINE.json config: "Convolver + ZCAWhitener + BlockLeastSquaresEstimator"):
random patches → ZCA whitening → convolution with whitened random-patch
filters → symmetric rectification → spatial sum pooling →
BlockLeastSquaresEstimator → MaxClassifier (SURVEY.md §2.11, §3.1)
[unverified].

TPU notes: filter prep (patch sampling + ZCA fit) is a small fit on the
device; the conv + rectify + pool featurization fuses into one XLA program
(MXU conv, vector-unit rectify, reduce_window pool); the solve is the
psum-reduced block coordinate descent.
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
import numpy as np

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.cifar import CifarLoader
from keystone_tpu.nodes.images import (
    Convolver,
    ImageVectorizer,
    Pooler,
    RandomPatcher,
    SymmetricRectifier,
)
from keystone_tpu.nodes.learning import (
    BlockLeastSquaresEstimator,
    ZCAWhitenerEstimator,
)
from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier
from keystone_tpu.workflow import Pipeline


@dataclass
class RandomPatchCifarConfig:
    train_path: Optional[str] = None
    test_path: Optional[str] = None
    num_filters: int = 256
    patch_size: int = 6
    patch_sample: int = 10000
    pool_size: int = 13
    pool_stride: int = 13
    alpha: float = 0.25
    lam: float = 10.0
    block_size: int = 4096
    num_iters: int = 3
    zca_eps: float = 0.1
    num_classes: int = 10
    seed: int = 0
    synthetic_n: int = 2048
    # "bfloat16" runs the conv featurization on the MXU's bf16/f32-accum
    # path (features and the solve stay f32 unless KEYSTONE_SOLVER_DTYPE).
    feature_dtype: Optional[str] = None


def build_featurizer(conf: RandomPatchCifarConfig, train_images) -> Pipeline:
    """Fit filters (random whitened patches) and build the conv featurizer."""
    patches = RandomPatcher(
        num_patches=conf.patch_sample,
        patch_size=conf.patch_size,
        seed=conf.seed,
    )(train_images)
    flat = jnp.asarray(patches).reshape(patches.shape[0], -1)
    whitener = ZCAWhitenerEstimator(eps=conf.zca_eps).fit(flat)
    # Sample num_filters whitened patches as filters, unit-normalized.
    rng = np.random.default_rng(conf.seed + 1)
    idx = rng.choice(flat.shape[0], size=conf.num_filters, replace=False)
    filt_flat = np.asarray(whitener(flat[idx]))
    norms = np.linalg.norm(filt_flat, axis=1, keepdims=True)
    filt_flat = filt_flat / np.maximum(norms, 1e-8)
    c = train_images.shape[-1]
    filters = filt_flat.reshape(
        conf.num_filters, conf.patch_size, conf.patch_size, c
    )
    return (
        Convolver(filters, whitener=whitener, compute_dtype=conf.feature_dtype)
        .and_then(SymmetricRectifier(alpha=conf.alpha))
        .and_then(Pooler(conf.pool_stride, conf.pool_size, mode="sum"))
        .and_then(ImageVectorizer())
    )


def run(conf: RandomPatchCifarConfig) -> dict:
    if conf.train_path:
        if not conf.test_path:
            raise ValueError("--test is required when --train is given")
        train = CifarLoader.load(conf.train_path)
        test = CifarLoader.load(conf.test_path)
    else:
        train, test = CifarLoader.synthetic(n=conf.synthetic_n)

    t0 = time.perf_counter()
    featurizer = build_featurizer(conf, train.data)
    targets = ClassLabelIndicators(conf.num_classes)(train.labels)
    pipeline = featurizer.and_then(
        BlockLeastSquaresEstimator(
            block_size=conf.block_size,
            num_iters=conf.num_iters,
            lam=conf.lam,
        ),
        train.data,
        targets,
    ).and_then(MaxClassifier())
    predictions = pipeline(test.data).get()
    elapsed = time.perf_counter() - t0

    metrics = MulticlassClassifierEvaluator(conf.num_classes).evaluate(
        predictions, test.labels
    )
    return {
        "test_accuracy": metrics.total_accuracy,
        "macro_f1": metrics.macro_f1,
        "seconds": elapsed,
        "summary": metrics.summary(),
    }


def main(argv=None):
    from keystone_tpu.utils.platform import setup_platform

    setup_platform()
    p = argparse.ArgumentParser(description="RandomPatchCifar pipeline")
    p.add_argument("--train", dest="train_path")
    p.add_argument("--test", dest="test_path")
    p.add_argument("--num-filters", type=int, default=256)
    p.add_argument("--patch-size", type=int, default=6)
    p.add_argument("--lam", type=float, default=10.0)
    p.add_argument("--num-iters", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--synthetic-n", type=int, default=2048)
    p.add_argument(
        "--feature-dtype", choices=["float32", "bfloat16"], default=None
    )
    a = p.parse_args(argv)
    conf = RandomPatchCifarConfig(
        train_path=a.train_path,
        test_path=a.test_path,
        num_filters=a.num_filters,
        patch_size=a.patch_size,
        lam=a.lam,
        num_iters=a.num_iters,
        seed=a.seed,
        synthetic_n=a.synthetic_n,
        feature_dtype=a.feature_dtype,  # Convolver normalizes "float32"→off
    )
    out = run(conf)
    print(out["summary"])
    print(f"total {out['seconds']:.2f}s")
    return out


if __name__ == "__main__":
    main()
