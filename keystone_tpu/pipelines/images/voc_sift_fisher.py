"""VOCSIFTFisher — SIFT + Fisher-vector VOC multi-label pipeline.

Ref: src/main/scala/pipelines/images/voc/VOCSIFTFisher.scala
(SURVEY.md §2.11, §3.4) [unverified]: grayscale → native dense SIFT →
PCA (fit on a descriptor sample) → GMM (native EM) → FisherVector →
SignedHellingerMapper → L2 normalize → block least squares → mAP.
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from keystone_tpu.evaluation.mean_average_precision import (
    MeanAveragePrecisionEvaluator,
)
from keystone_tpu.loaders.voc import VOCLoader
from keystone_tpu.nodes.images import GrayScaler
from keystone_tpu.nodes.images.external import SIFTExtractor
from keystone_tpu.nodes.images.external.fisher_vector import (
    fit_fisher_featurizer,
)
from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
from keystone_tpu.workflow import Pipeline


@dataclass
class VOCSIFTFisherConfig:
    image_dir: Optional[str] = None
    annotation_dir: Optional[str] = None
    test_image_dir: Optional[str] = None
    test_annotation_dir: Optional[str] = None
    sift_step: int = 4
    sift_bin: int = 4
    sift_backend: str = "native"
    pca_dims: int = 64
    gmm_k: int = 16
    gmm_iters: int = 20
    descriptor_sample: int = 100_000
    lam: float = 1e-3
    block_size: int = 4096
    num_iters: int = 2
    fv_backend: str = "tpu"
    seed: int = 0
    synthetic_n: int = 192
    synthetic_classes: int = 6


def build_featurizer(conf: VOCSIFTFisherConfig, train_images) -> Pipeline:
    """Fit PCA + GMM on training descriptors; return the full featurizer."""
    front = GrayScaler().and_then(
        SIFTExtractor(step=conf.sift_step, bin_size=conf.sift_bin,
                      backend=conf.sift_backend)
    )
    return fit_fisher_featurizer(
        front,
        train_images,
        pca_dims=conf.pca_dims,
        gmm_k=conf.gmm_k,
        em_iters=conf.gmm_iters,
        sample_size=conf.descriptor_sample,
        backend=conf.fv_backend,
        seed=conf.seed,
    )


def run(conf: VOCSIFTFisherConfig) -> dict:
    if conf.image_dir:
        if not (
            conf.annotation_dir
            and conf.test_image_dir
            and conf.test_annotation_dir
        ):
            raise ValueError(
                "real data requires train+test image and annotation dirs"
            )
        train = VOCLoader.load(conf.image_dir, conf.annotation_dir)
        test = VOCLoader.load(conf.test_image_dir, conf.test_annotation_dir)
        num_classes = train.labels.shape[1]
    else:
        train, test = VOCLoader.synthetic(
            n=conf.synthetic_n, num_classes=conf.synthetic_classes
        )
        num_classes = conf.synthetic_classes

    t0 = time.perf_counter()
    featurizer = build_featurizer(conf, train.data)
    targets = (2.0 * train.labels - 1.0).astype(np.float32)
    pipeline = featurizer.and_then(
        BlockLeastSquaresEstimator(
            block_size=conf.block_size, num_iters=conf.num_iters, lam=conf.lam
        ),
        train.data,
        targets,
    )
    scores = np.asarray(pipeline(test.data).get())
    elapsed = time.perf_counter() - t0

    result = MeanAveragePrecisionEvaluator(num_classes).evaluate(
        scores, test.labels
    )
    return {
        "map": result["map"],
        "per_class_ap": result["per_class_ap"].tolist(),
        "seconds": elapsed,
        "summary": f"mAP: {result['map']:.4f}",
    }


def main(argv=None):
    from keystone_tpu.utils.platform import setup_platform

    setup_platform()
    p = argparse.ArgumentParser(description="VOC SIFT+FisherVector pipeline")
    p.add_argument("--images", dest="image_dir")
    p.add_argument("--annotations", dest="annotation_dir")
    p.add_argument("--test-images", dest="test_image_dir")
    p.add_argument("--test-annotations", dest="test_annotation_dir")
    p.add_argument("--pca-dims", type=int, default=64)
    p.add_argument("--gmm-k", type=int, default=16)
    p.add_argument("--lam", type=float, default=1e-3)
    p.add_argument("--fv-backend", choices=["tpu", "pallas", "native"], default="tpu")
    p.add_argument("--sift-backend", choices=["native", "xla"], default="native",
                   help="xla runs dense SIFT on the device (host keeps only decode)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--synthetic-n", type=int, default=192)
    a = p.parse_args(argv)
    out = run(
        VOCSIFTFisherConfig(
            image_dir=a.image_dir,
            annotation_dir=a.annotation_dir,
            test_image_dir=a.test_image_dir,
            test_annotation_dir=a.test_annotation_dir,
            pca_dims=a.pca_dims,
            gmm_k=a.gmm_k,
            lam=a.lam,
            fv_backend=a.fv_backend,
            sift_backend=a.sift_backend,
            seed=a.seed,
            synthetic_n=a.synthetic_n,
        )
    )
    print(out["summary"])
    print(f"total {out['seconds']:.2f}s")
    return out


if __name__ == "__main__":
    main()
