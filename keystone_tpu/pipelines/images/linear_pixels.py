"""LinearPixels — the CIFAR sanity pipeline: raw pixels → linear solve.

Ref: src/main/scala/pipelines/images/cifar/LinearPixels.scala
(SURVEY.md §2.11) [unverified].
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass
from typing import Optional

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.cifar import CifarLoader
from keystone_tpu.nodes.images import GrayScaler, ImageVectorizer
from keystone_tpu.nodes.learning import LinearMapEstimator
from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier


@dataclass
class LinearPixelsConfig:
    train_path: Optional[str] = None
    test_path: Optional[str] = None
    lam: float = 1.0
    num_classes: int = 10
    synthetic_n: int = 2048


def run(conf: LinearPixelsConfig) -> dict:
    if conf.train_path:
        if not conf.test_path:
            raise ValueError("--test is required when --train is given")
        train = CifarLoader.load(conf.train_path)
        test = CifarLoader.load(conf.test_path)
    else:
        train, test = CifarLoader.synthetic(n=conf.synthetic_n)

    t0 = time.perf_counter()
    featurizer = GrayScaler().and_then(ImageVectorizer())
    targets = ClassLabelIndicators(conf.num_classes)(train.labels)
    pipeline = featurizer.and_then(
        LinearMapEstimator(lam=conf.lam), train.data, targets
    ).and_then(MaxClassifier())
    predictions = pipeline(test.data).get()
    elapsed = time.perf_counter() - t0

    metrics = MulticlassClassifierEvaluator(conf.num_classes).evaluate(
        predictions, test.labels
    )
    return {
        "test_accuracy": metrics.total_accuracy,
        "seconds": elapsed,
        "summary": metrics.summary(),
    }


def main(argv=None):
    from keystone_tpu.utils.platform import setup_platform

    setup_platform()
    p = argparse.ArgumentParser(description="LinearPixels CIFAR pipeline")
    p.add_argument("--train", dest="train_path")
    p.add_argument("--test", dest="test_path")
    p.add_argument("--lam", type=float, default=1.0)
    p.add_argument("--synthetic-n", type=int, default=2048)
    a = p.parse_args(argv)
    out = run(
        LinearPixelsConfig(
            train_path=a.train_path,
            test_path=a.test_path,
            lam=a.lam,
            synthetic_n=a.synthetic_n,
        )
    )
    print(out["summary"])
    print(f"total {out['seconds']:.2f}s")
    return out


if __name__ == "__main__":
    main()
