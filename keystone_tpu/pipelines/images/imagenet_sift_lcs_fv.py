"""ImageNetSiftLcsFV — the north-star pipeline.

Ref: src/main/scala/pipelines/images/imagenet/ImageNetSiftLcsFV.scala
(BASELINE.json config: "SIFT/LCS + GMM FisherVector +
BlockWeightedLeastSquares (64k-dim)"; SURVEY.md §2.11, §3.4) [unverified]:
two descriptor branches — grayscale dense SIFT and local color statistics
— each PCA-reduced, Fisher-vector encoded against its own GMM, signed-sqrt
and L2 normalized; branches concatenated (Pipeline.gather); class-balanced
block weighted least squares; top-5 error via TopKClassifier.

TPU notes: each branch's PCA→FV→normalize tail fuses into one XLA
computation; the gathered 2·(2·k·pca_dims)-dim features feed the
psum-reduced weighted BCD solver.

Full-scale config (the REAL-DATA default via resolve_scale, matching
BASELINE.json "64k-dim"):

    pca_dims=64  gmm_k=256  → feature_dim = 2·(2·256·64) = 65,536
    solver: weighted BCD, block_size=auto (HBM-safe, 8192 cap), 3 epochs

Synthetic/CI runs default to gmm_k=16 (4,096-dim) so smoke tests stay
fast; pass --gmm-k 256 to force the headline scale anywhere.
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from keystone_tpu.loaders.imagenet import ImageNetLoader
from keystone_tpu.nodes.images import GrayScaler
from keystone_tpu.nodes.images.external import SIFTExtractor
from keystone_tpu.nodes.images.external.fisher_vector import (
    fit_fisher_featurizer,
)
from keystone_tpu.nodes.images.lcs import LCSExtractor
from keystone_tpu.nodes.learning import BlockWeightedLeastSquaresEstimator
from keystone_tpu.nodes.util import ClassLabelIndicators, TopKClassifier
from keystone_tpu.workflow import Pipeline


def _scoring_engine(model, stream_batch: int):
    """The classifier head as a replica-pool serving engine for the
    streamed scorer's data-parallel offline apply, or None when the model
    can't take the AOT path (not jittable / row-coupled) — the caller
    falls back to ``batch_call``. A single bucket at the stream batch
    size keeps warmup to one compile per device: the stream only ever
    yields full batches plus one trailing partial (padded up)."""
    from keystone_tpu.workflow.serving import (
        CompiledPipeline,
        RowDependenceError,
    )

    try:
        # Stable name = explicit aggregation key: repeated scoring passes
        # in one process reuse the same registry entries instead of
        # leaking a fresh serve.dispatch[cpN]/gauge set per pass.
        return CompiledPipeline(
            model, buckets=(stream_batch,), name="imagenet-score-head"
        )
    except (TypeError, RowDependenceError):
        return None


@dataclass
class ImageNetSiftLcsFVConfig:
    data_path: Optional[str] = None
    test_data_path: Optional[str] = None
    label_map_path: Optional[str] = None
    sift_step: int = 4
    sift_bin: int = 4
    sift_backend: str = "native"
    lcs_step: int = 4
    lcs_bin: int = 4
    pca_dims: int = 64
    # None = resolve by data source (resolve_scale): REAL data gets the
    # reference headline config — gmm_k=256 → 2·(2·256·64) = 65,536-dim
    # gathered features (BASELINE.json "64k-dim"), 3 solver epochs — while
    # the synthetic/CI path keeps gmm_k=16 (4,096-dim) so smoke runs stay
    # minutes, not hours. An explicit value always wins.
    gmm_k: Optional[int] = None
    gmm_iters: int = 20
    descriptor_sample: int = 200_000
    lam: float = 1e-3
    mixture_weight: float = 0.5
    block_size: "int | str" = "auto"  # resolve_block_size: HBM-safe, 8192 cap
    num_iters: Optional[int] = None
    top_k: int = 5
    # Test-time augmentation: score center+corner crops (flipped too) per
    # image and average (Ref: AugmentedExamplesEvaluator, SURVEY.md §2.10).
    augment: bool = False
    augment_crop: int = 0  # 0 = 7/8 of the image side
    fv_backend: str = "tpu"
    seed: int = 0
    synthetic_n: int = 512
    synthetic_classes: int = 16
    # Out-of-core mode: fit the featurizer on a bounded image sample, then
    # stream images from disk (decode-ahead) and featurize batch by batch —
    # only FEATURES are held on host, and the solve streams feature blocks
    # to the device. The single-host projection of the reference's
    # cache-features-not-images cluster layout (SURVEY.md §7 hard parts 1+4).
    stream: bool = False
    stream_batch: int = 256
    fit_sample_images: int = 512
    # Checkpoint directory for the chunked/streamed solve: the BCD solver
    # snapshots per-chunk accumulator state there and resumes after a
    # crash (including one mid-way through the donated chunk loop — the
    # chaos harness pins that path). None = no checkpointing.
    checkpoint_dir: Optional[str] = None


def resolve_scale(conf: ImageNetSiftLcsFVConfig) -> ImageNetSiftLcsFVConfig:
    """Fill gmm_k/num_iters by data source: the real-data path defaults to
    the reference's full-scale config (64k-dim features, 3 epochs), the
    synthetic path to CI scale. Called once at the top of run()."""
    from dataclasses import replace

    real = conf.data_path is not None
    return replace(
        conf,
        gmm_k=conf.gmm_k if conf.gmm_k is not None else (256 if real else 16),
        num_iters=(
            conf.num_iters if conf.num_iters is not None else (3 if real else 2)
        ),
    )


def build_featurizer(conf: ImageNetSiftLcsFVConfig, train_images) -> Pipeline:
    sift_front = GrayScaler().and_then(
        SIFTExtractor(step=conf.sift_step, bin_size=conf.sift_bin,
                      backend=conf.sift_backend)
    )
    lcs_front = LCSExtractor(step=conf.lcs_step, bin_size=conf.lcs_bin).to_pipeline()
    branches = [
        fit_fisher_featurizer(
            front,
            train_images,
            pca_dims=conf.pca_dims,
            gmm_k=conf.gmm_k,
            em_iters=conf.gmm_iters,
            sample_size=conf.descriptor_sample,
            backend=conf.fv_backend,
            seed=seed,
        )
        for front, seed in ((sift_front, conf.seed), (lcs_front, conf.seed + 1))
    ]
    return Pipeline.gather(branches)


def _build_tta(conf: ImageNetSiftLcsFVConfig, side: int):
    """Patcher + score averager for the reference's TTA protocol (center +
    four corners, each flipped = 10 views; crop defaults to 7/8 of the
    image side). Shared by the eager and streamed paths so the crop
    protocol can't drift between them."""
    from keystone_tpu.evaluation.augmented import AugmentedExamplesEvaluator
    from keystone_tpu.nodes.images import CenterCornerPatcher

    crop = conf.augment_crop or (side * 7) // 8
    patcher = CenterCornerPatcher(crop_size=crop, with_flips=True)
    return patcher, AugmentedExamplesEvaluator(patcher.num_views)


def run_streamed(conf: ImageNetSiftLcsFVConfig) -> dict:
    """Out-of-core execution of the north-star pipeline.

    Images never sit in memory all at once: the featurizer (PCA/GMM) fits
    on ``fit_sample_images``, train batches stream through it (decode of
    batch b+1 overlapping featurization of batch b on real paths), the
    accumulated FEATURE matrix — ~3× smaller than the images at the
    64k-dim config — feeds the host-streamed weighted BCD, and test
    batches stream through scoring the same way.

    With ``augment`` (the reference's AugmentedExamplesEvaluator protocol,
    SURVEY.md §2.10), each test batch expands to its center+corner crop
    views; views are featurized and scored in ``stream_batch``-sized
    slices so device batches stay bounded, and only the (views, classes)
    score rows are held before per-image averaging — the feature matrix
    for the views is never materialized whole.
    """
    if conf.data_path:
        if not (conf.test_data_path and conf.label_map_path):
            raise ValueError("real data requires test path and label map")
        label_map = ImageNetLoader.load_label_map(conf.label_map_path)
        # Class-balanced fitting sample: PCA/GMM fit on a few images from
        # EVERY synset — a prefix of the sorted walk would be one class.
        fit_sample = ImageNetLoader.load_balanced_sample(
            conf.data_path, label_map, total=conf.fit_sample_images
        )
        num_classes = max(label_map.values()) + 1

        def train_batches():
            return ImageNetLoader.stream_batches(
                conf.data_path, label_map, batch_size=conf.stream_batch
            )

        def test_batches():
            return ImageNetLoader.stream_batches(
                conf.test_data_path, label_map, batch_size=conf.stream_batch
            )

    else:
        from keystone_tpu.loaders.stream import BatchIterator

        train, test = ImageNetLoader.synthetic(
            n=conf.synthetic_n, num_classes=conf.synthetic_classes
        )
        fit_sample = train.data[: conf.fit_sample_images]
        num_classes = conf.synthetic_classes

        def train_batches():
            return iter(
                BatchIterator.from_arrays(
                    train.data, train.labels, conf.stream_batch
                )
            )

        def test_batches():
            return iter(
                BatchIterator.from_arrays(
                    test.data, test.labels, conf.stream_batch
                )
            )

    t0 = time.perf_counter()
    featurizer = build_featurizer(conf, fit_sample)

    # apply_batches runs the batch producer (JPEG decode / synthetic read)
    # on a prefetch thread while the fused featurizer chain computes on the
    # current batch — decode of batch b+1 overlaps featurization of b on
    # every source, not just the real-data loader's decode-ahead pool.
    feats, labels = [], []
    for F, y in featurizer.apply_batches(train_batches()):
        feats.append(np.asarray(F))
        labels.append(np.asarray(y))
    if not feats:
        raise ValueError(
            "the training stream produced no batches — check that the data "
            "directory's synsets appear in the label map"
        )
    # Assemble in place, freeing each chunk as it lands: peak host memory is
    # the feature matrix + ONE batch, not the 2× a concatenate would cost
    # (the whole point of this mode at the 64k-dim scale).
    n_total = sum(len(f) for f in feats)
    A_host = np.empty((n_total, feats[0].shape[1]), dtype=feats[0].dtype)
    off = 0
    while feats:
        f = feats.pop(0)
        A_host[off : off + len(f)] = f
        off += len(f)
    y_train = np.concatenate(labels)

    targets = np.asarray(ClassLabelIndicators(num_classes)(y_train))
    solver = BlockWeightedLeastSquaresEstimator(
        block_size=conf.block_size,
        num_iters=conf.num_iters,
        lam=conf.lam,
        mixture_weight=conf.mixture_weight,
        checkpoint_dir=conf.checkpoint_dir,
        stream=True,  # feature blocks stream to the device, double-buffered
    )
    model = solver.fit(A_host, targets)
    del A_host

    patcher = averager = None
    if conf.augment:
        patcher, averager = _build_tta(conf, int(np.asarray(fit_sample).shape[1]))

    def score_batches():
        """(scores, labels) per test batch, ingest-overlapped either way:
        the plain path featurizes via apply_batches (decode on the prefetch
        thread), the TTA path prefetches raw batches and expands views on
        the consumer side (the view tensor must stay sub-batch-bounded)."""
        if patcher is None:
            head = _scoring_engine(model, conf.stream_batch)
            feats = featurizer.apply_batches(test_batches())
            if head is not None:
                # Data-parallel offline scoring: the classifier head runs
                # from its replica pool (one AOT ladder per local device),
                # round-robining featurized batches so up to
                # inflight x replicas device calls overlap the prefetch
                # thread's decode/featurize. prefetch_depth=0: the source
                # generator already prefetches; the async window supplies
                # the overlap here.
                yield from head.apply_batches(feats, prefetch_depth=0)
                return
            for F, y in feats:
                # batch_call (not apply_batch) so the classifier head runs
                # jitted and, under KEYSTONE_SERVE_BUCKETS, shape-stable:
                # the stream's trailing partial batch otherwise recompiles
                # the whole blocked-gemm chain for its one-off row count.
                yield model.batch_call(np.asarray(F)), y
            return
        from keystone_tpu.loaders.stream import prefetched

        with prefetched(iter(test_batches())) as src:
            for X, y in src:
                # Patch per image sub-batch so the view tensor never
                # exceeds ~stream_batch rows on the device (a whole-batch
                # patch at the real-data scale is a ~2 GB transient, 10×
                # the working set this mode exists to bound).
                X = np.asarray(X)
                sub = max(1, conf.stream_batch // patcher.num_views)
                view_scores = np.concatenate([
                    np.asarray(model.batch_call(np.asarray(
                        featurizer(patcher(X[i : i + sub])).get()
                    )))
                    for i in range(0, len(X), sub)
                ])
                yield averager.average_scores(view_scores), y

    correct = []
    top1_wrong = []
    for scores, y in score_batches():
        topk = np.asarray(TopKClassifier(conf.top_k)(scores))
        correct.append((topk == np.asarray(y)[:, None]).any(axis=1))
        top1_wrong.append(topk[:, 0] != np.asarray(y))
    correct = np.concatenate(correct)
    top1_wrong = np.concatenate(top1_wrong)
    elapsed = time.perf_counter() - t0

    top_k_error = float(1.0 - correct.mean())
    top1 = float(top1_wrong.mean())
    return {
        "top_k_error": top_k_error,
        "top_1_error": top1,
        "feature_dim": 2 * (2 * conf.gmm_k * conf.pca_dims),
        "seconds": elapsed,
        "summary": (
            f"top-{conf.top_k} error: {top_k_error:.4f} | "
            f"top-1 error: {top1:.4f} (streamed"
            + (f", TTA x{patcher.num_views})" if patcher else ")")
        ),
        **({"num_views": patcher.num_views} if patcher else {}),
    }


def run(conf: ImageNetSiftLcsFVConfig) -> dict:
    conf = resolve_scale(conf)
    if conf.stream:
        return run_streamed(conf)
    if conf.data_path:
        if not (conf.test_data_path and conf.label_map_path):
            raise ValueError("real data requires test path and label map")
        label_map = ImageNetLoader.load_label_map(conf.label_map_path)
        train = ImageNetLoader.load(conf.data_path, label_map)
        test = ImageNetLoader.load(conf.test_data_path, label_map)
        num_classes = int(max(train.labels.max(), test.labels.max())) + 1
    else:
        train, test = ImageNetLoader.synthetic(
            n=conf.synthetic_n, num_classes=conf.synthetic_classes
        )
        num_classes = conf.synthetic_classes

    t0 = time.perf_counter()
    featurizer = build_featurizer(conf, train.data)
    targets = ClassLabelIndicators(num_classes)(train.labels)
    solver = BlockWeightedLeastSquaresEstimator(
        block_size=conf.block_size,
        num_iters=conf.num_iters,
        lam=conf.lam,
        mixture_weight=conf.mixture_weight,
        checkpoint_dir=conf.checkpoint_dir,
    )
    scored = featurizer.and_then(solver, train.data, targets)
    if conf.augment:
        patcher, averager = _build_tta(conf, test.data.shape[1])
        view_scores = np.asarray(scored(patcher(test.data)).get())
        avg = averager.average_scores(view_scores)
        topk = np.asarray(TopKClassifier(conf.top_k)(avg))
    else:
        pipeline = scored.and_then(TopKClassifier(conf.top_k))
        topk = np.asarray(pipeline(test.data).get())  # (n, top_k)
    elapsed = time.perf_counter() - t0

    correct = (topk == test.labels[:, None]).any(axis=1)
    top_k_error = float(1.0 - correct.mean())
    top1 = float((topk[:, 0] != test.labels).mean())
    return {
        "top_k_error": top_k_error,
        "top_1_error": top1,
        "feature_dim": 2 * (2 * conf.gmm_k * conf.pca_dims),
        "seconds": elapsed,
        "summary": (
            f"top-{conf.top_k} error: {top_k_error:.4f} | "
            f"top-1 error: {top1:.4f}"
        ),
    }


def main(argv=None):
    from keystone_tpu.utils.platform import setup_platform

    setup_platform()
    p = argparse.ArgumentParser(description="ImageNet SIFT+LCS+FV pipeline")
    p.add_argument("--data", dest="data_path")
    p.add_argument("--test-data", dest="test_data_path")
    p.add_argument("--label-map", dest="label_map_path")
    p.add_argument("--pca-dims", type=int, default=64)
    p.add_argument("--gmm-k", type=int, default=None,
                   help="GMM components per branch (default: 256 with real "
                   "data = the reference's 64k-dim config; 16 synthetic)")
    p.add_argument("--lam", type=float, default=1e-3)
    p.add_argument("--mixture-weight", type=float, default=0.5)
    p.add_argument("--top-k", type=int, default=5)
    p.add_argument("--augment", action="store_true",
                   help="test-time augmentation over center+corner crops")
    p.add_argument("--augment-crop", type=int, default=0,
                   help="crop side in pixels (0 = 7/8 of the image side)")
    p.add_argument("--fv-backend", choices=["tpu", "pallas", "native"], default="tpu")
    p.add_argument("--sift-backend", choices=["native", "xla"], default="native",
                   help="xla runs dense SIFT on the device (host keeps only decode)")
    p.add_argument("--stream", action="store_true",
                   help="out-of-core: stream images, hold only features")
    p.add_argument("--stream-batch", type=int, default=256)
    p.add_argument("--fit-sample-images", type=int, default=512)
    p.add_argument("--checkpoint-dir", default=None,
                   help="snapshot/resume dir for the chunked solve")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--synthetic-n", type=int, default=512)
    p.add_argument("--synthetic-classes", type=int, default=16)
    a = p.parse_args(argv)
    out = run(
        ImageNetSiftLcsFVConfig(
            data_path=a.data_path,
            test_data_path=a.test_data_path,
            label_map_path=a.label_map_path,
            pca_dims=a.pca_dims,
            gmm_k=a.gmm_k,
            lam=a.lam,
            mixture_weight=a.mixture_weight,
            top_k=a.top_k,
            augment=a.augment,
            augment_crop=a.augment_crop,
            fv_backend=a.fv_backend,
            sift_backend=a.sift_backend,
            stream=a.stream,
            stream_batch=a.stream_batch,
            fit_sample_images=a.fit_sample_images,
            checkpoint_dir=a.checkpoint_dir,
            seed=a.seed,
            synthetic_n=a.synthetic_n,
            synthetic_classes=a.synthetic_classes,
        )
    )
    print(out["summary"])
    print(f"feature dim {out['feature_dim']} | total {out['seconds']:.2f}s")
    return out


if __name__ == "__main__":
    main()
