"""NewsgroupsPipeline — the canonical text-classification pipeline.

Ref: src/main/scala/pipelines/text/NewsgroupsPipeline.scala
(BASELINE.json config: "NGrams + tf-idf + NaiveBayes /
LogisticRegressionEstimator"): Trim → LowerCase → Tokenizer →
NGramsFeaturizer → TermFrequency(log) → CommonSparseFeatures →
NaiveBayesEstimator → MaxClassifier (SURVEY.md §2.11) [unverified].
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass
from typing import Optional

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.newsgroups import NewsgroupsDataLoader
from keystone_tpu.nodes.learning import (
    LogisticRegressionEstimator,
    NaiveBayesEstimator,
)
from keystone_tpu.nodes.nlp import (
    CommonSparseFeatures,
    LowerCase,
    NGramsFeaturizer,
    TermFrequency,
    Tokenizer,
    Trim,
)
from keystone_tpu.nodes.util import MaxClassifier


@dataclass
class NewsgroupsConfig:
    train_path: Optional[str] = None
    test_path: Optional[str] = None
    num_features: int = 10000
    ngrams: int = 2
    classifier: str = "naive_bayes"  # or "logistic"
    num_classes: int = 5
    synthetic_n: int = 1000


def run(conf: NewsgroupsConfig) -> dict:
    if conf.train_path:
        if not conf.test_path:
            raise ValueError("--test is required when --train is given")
        train, classes = NewsgroupsDataLoader.load(conf.train_path)
        # Pass the train class list so test label indices align with it.
        test, _ = NewsgroupsDataLoader.load(conf.test_path, classes=classes)
        num_classes = len(classes)
    else:
        train, test, classes = NewsgroupsDataLoader.synthetic(
            n=conf.synthetic_n, num_classes=conf.num_classes
        )
        num_classes = len(classes)

    t0 = time.perf_counter()
    featurizer = (
        Trim()
        .and_then(LowerCase())
        .and_then(Tokenizer())
        .and_then(NGramsFeaturizer(1, conf.ngrams))
        .and_then(TermFrequency("log"))
        .and_then(CommonSparseFeatures(conf.num_features), train.data)
    )
    if conf.classifier == "naive_bayes":
        pipeline = featurizer.and_then(
            NaiveBayesEstimator(num_classes), train.data, train.labels
        )
    elif conf.classifier == "logistic":
        pipeline = featurizer.and_then(
            LogisticRegressionEstimator(num_classes), train.data, train.labels
        )
    else:
        raise ValueError(f"unknown classifier {conf.classifier!r}")
    pipeline = pipeline.and_then(MaxClassifier())
    predictions = pipeline(test.data).get()
    elapsed = time.perf_counter() - t0

    metrics = MulticlassClassifierEvaluator(num_classes).evaluate(
        predictions, test.labels
    )
    return {
        "test_accuracy": metrics.total_accuracy,
        "macro_f1": metrics.macro_f1,
        "seconds": elapsed,
        "classes": classes,
        "summary": metrics.summary(),
    }


def main(argv=None):
    from keystone_tpu.utils.platform import setup_platform

    setup_platform()
    p = argparse.ArgumentParser(description="Newsgroups text pipeline")
    p.add_argument("--train", dest="train_path")
    p.add_argument("--test", dest="test_path")
    p.add_argument("--num-features", type=int, default=10000)
    p.add_argument("--ngrams", type=int, default=2)
    p.add_argument(
        "--classifier", choices=["naive_bayes", "logistic"], default="naive_bayes"
    )
    p.add_argument("--synthetic-n", type=int, default=1000)
    a = p.parse_args(argv)
    out = run(
        NewsgroupsConfig(
            train_path=a.train_path,
            test_path=a.test_path,
            num_features=a.num_features,
            ngrams=a.ngrams,
            classifier=a.classifier,
            synthetic_n=a.synthetic_n,
        )
    )
    print(out["summary"])
    print(f"total {out['seconds']:.2f}s")
    return out


if __name__ == "__main__":
    main()
