"""AmazonReviewsPipeline — binary sentiment over the same text front-end.

Ref: src/main/scala/pipelines/text/AmazonReviewsPipeline.scala — text
front-end → logistic regression, binary evaluation (SURVEY.md §2.11)
[unverified].
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from keystone_tpu.evaluation.binary import BinaryClassifierEvaluator
from keystone_tpu.loaders.amazon import AmazonReviewsDataLoader
from keystone_tpu.nodes.learning import LogisticRegressionEstimator
from keystone_tpu.nodes.nlp import (
    CommonSparseFeatures,
    LowerCase,
    NGramsFeaturizer,
    TermFrequency,
    Tokenizer,
    Trim,
)


@dataclass
class AmazonReviewsConfig:
    train_path: Optional[str] = None
    test_path: Optional[str] = None
    num_features: int = 20000
    ngrams: int = 2
    reg: float = 1e-3
    synthetic_n: int = 1000


def run(conf: AmazonReviewsConfig) -> dict:
    if conf.train_path:
        if not conf.test_path:
            raise ValueError("--test is required when --train is given")
        train = AmazonReviewsDataLoader.load(conf.train_path)
        test = AmazonReviewsDataLoader.load(conf.test_path)
    else:
        train, test = AmazonReviewsDataLoader.synthetic(n=conf.synthetic_n)

    t0 = time.perf_counter()
    featurizer = (
        Trim()
        .and_then(LowerCase())
        .and_then(Tokenizer())
        .and_then(NGramsFeaturizer(1, conf.ngrams))
        .and_then(TermFrequency("log"))
        .and_then(CommonSparseFeatures(conf.num_features), train.data)
    )
    pipeline = featurizer.and_then(
        LogisticRegressionEstimator(num_classes=2, reg=conf.reg),
        train.data,
        train.labels,
    )
    scores = np.asarray(pipeline(test.data).get())
    elapsed = time.perf_counter() - t0

    predictions = scores.argmax(axis=1)
    margin = scores[:, 1] - scores[:, 0]
    metrics = BinaryClassifierEvaluator.evaluate(
        predictions, test.labels, scores=margin
    )
    return {
        "accuracy": metrics.accuracy,
        "auc": metrics.auc,
        "f1": metrics.f1,
        "seconds": elapsed,
        "summary": metrics.summary(),
    }


def main(argv=None):
    from keystone_tpu.utils.platform import setup_platform

    setup_platform()
    p = argparse.ArgumentParser(description="Amazon reviews sentiment pipeline")
    p.add_argument("--train", dest="train_path")
    p.add_argument("--test", dest="test_path")
    p.add_argument("--num-features", type=int, default=20000)
    p.add_argument("--ngrams", type=int, default=2)
    p.add_argument("--reg", type=float, default=1e-3)
    p.add_argument("--synthetic-n", type=int, default=1000)
    a = p.parse_args(argv)
    out = run(
        AmazonReviewsConfig(
            train_path=a.train_path,
            test_path=a.test_path,
            num_features=a.num_features,
            ngrams=a.ngrams,
            reg=a.reg,
            synthetic_n=a.synthetic_n,
        )
    )
    print(out["summary"])
    print(f"total {out['seconds']:.2f}s")
    return out


if __name__ == "__main__":
    main()
