"""TimitPipeline — the canonical speech pipeline.

Ref: src/main/scala/pipelines/speech/timit/TimitPipeline.scala
(BASELINE.json config: "MFCC + CosineRandomFeatures +
BlockLeastSquaresEstimator"): frame features → StandardScaler →
CosineRandomFeatures (Gaussian or Cauchy W, ~100k+ dims) → multi-epoch
BlockLeastSquaresEstimator → MaxClassifier (SURVEY.md §2.11) [unverified].

This is the first real stress of the distributed-linalg layer at high
feature dimension: the random-feature projection is one large MXU gemm and
the solve streams feature blocks through the psum-reduced BCD loop.
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass
from typing import Optional

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.timit import TimitFeaturesDataLoader
from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
from keystone_tpu.nodes.stats import CosineRandomFeatures, StandardScaler
from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier


@dataclass
class TimitConfig:
    features_path: Optional[str] = None
    labels_path: Optional[str] = None
    test_features_path: Optional[str] = None
    test_labels_path: Optional[str] = None
    num_features: int = 4096
    gamma: float = 0.055  # the RBF bandwidth scale of the reference setup
    distribution: str = "gaussian"  # or "cauchy"
    lam: float = 0.1
    block_size: int = 2048
    num_iters: int = 3
    num_phones: int = 24
    seed: int = 0
    synthetic_n: int = 4096


def run(conf: TimitConfig) -> dict:
    if conf.features_path:
        if not conf.test_features_path:
            raise ValueError("test features are required with real data")
        train = TimitFeaturesDataLoader.load(conf.features_path, conf.labels_path)
        test = TimitFeaturesDataLoader.load(
            conf.test_features_path, conf.test_labels_path
        )
        num_phones = TimitFeaturesDataLoader.NUM_PHONES
    else:
        train, test = TimitFeaturesDataLoader.synthetic(
            n=conf.synthetic_n, num_phones=conf.num_phones, seed=conf.seed
        )
        num_phones = conf.num_phones

    t0 = time.perf_counter()
    featurizer = StandardScaler().with_data(train.data).and_then(
        CosineRandomFeatures.create(
            input_dim=train.data.shape[1],
            num_features=conf.num_features,
            gamma=conf.gamma,
            distribution=conf.distribution,
            seed=conf.seed,
        )
    )
    targets = ClassLabelIndicators(num_phones)(train.labels)
    pipeline = featurizer.and_then(
        BlockLeastSquaresEstimator(
            block_size=conf.block_size,
            num_iters=conf.num_iters,
            lam=conf.lam,
        ),
        train.data,
        targets,
    ).and_then(MaxClassifier())
    predictions = pipeline(test.data).get()
    elapsed = time.perf_counter() - t0

    metrics = MulticlassClassifierEvaluator(num_phones).evaluate(
        predictions, test.labels
    )
    return {
        "test_accuracy": metrics.total_accuracy,
        "phone_error_rate": 1.0 - metrics.total_accuracy,
        "macro_f1": metrics.macro_f1,
        "seconds": elapsed,
        "summary": metrics.summary(),
    }


def main(argv=None):
    from keystone_tpu.utils.platform import setup_platform

    setup_platform()
    p = argparse.ArgumentParser(description="TIMIT speech pipeline")
    p.add_argument("--features", dest="features_path")
    p.add_argument("--labels", dest="labels_path")
    p.add_argument("--test-features", dest="test_features_path")
    p.add_argument("--test-labels", dest="test_labels_path")
    p.add_argument("--num-features", type=int, default=4096)
    p.add_argument("--gamma", type=float, default=0.055)
    p.add_argument(
        "--distribution", choices=["gaussian", "cauchy"], default="gaussian"
    )
    p.add_argument("--lam", type=float, default=0.1)
    p.add_argument("--block-size", type=int, default=2048)
    p.add_argument("--num-iters", type=int, default=3)
    p.add_argument("--num-phones", type=int, default=24)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--synthetic-n", type=int, default=4096)
    a = p.parse_args(argv)
    out = run(
        TimitConfig(
            features_path=a.features_path,
            labels_path=a.labels_path,
            test_features_path=a.test_features_path,
            test_labels_path=a.test_labels_path,
            num_features=a.num_features,
            gamma=a.gamma,
            distribution=a.distribution,
            lam=a.lam,
            block_size=a.block_size,
            num_iters=a.num_iters,
            num_phones=a.num_phones,
            seed=a.seed,
            synthetic_n=a.synthetic_n,
        )
    )
    print(out["summary"])
    print(
        f"PER {out['phone_error_rate']:.4f} | total {out['seconds']:.2f}s"
    )
    return out


if __name__ == "__main__":
    main()
