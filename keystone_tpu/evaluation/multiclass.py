"""Multiclass classification metrics.

Ref: src/main/scala/evaluation/MulticlassClassifierEvaluator.scala —
confusion matrix, total/per-class accuracy, macro F1, and a pretty-printed
summary [unverified].
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class MulticlassMetrics:
    confusion: np.ndarray  # (classes, classes); rows = actual, cols = predicted
    total_accuracy: float
    per_class_accuracy: np.ndarray
    macro_f1: float

    def summary(self) -> str:
        lines = [
            f"total accuracy: {self.total_accuracy:.4f}",
            f"macro F1:       {self.macro_f1:.4f}",
            "per-class accuracy: "
            + " ".join(f"{a:.3f}" for a in self.per_class_accuracy),
        ]
        return "\n".join(lines)


class MulticlassClassifierEvaluator:
    def __init__(self, num_classes: int):
        self.num_classes = num_classes

    def evaluate(self, predicted, actual) -> MulticlassMetrics:
        pred = np.asarray(predicted).astype(np.int64).ravel()
        act = np.asarray(actual).astype(np.int64).ravel()
        if pred.shape != act.shape:
            raise ValueError(f"shape mismatch {pred.shape} vs {act.shape}")
        c = self.num_classes
        confusion = np.zeros((c, c), dtype=np.int64)
        np.add.at(confusion, (act, pred), 1)
        total = confusion.sum()
        correct = np.trace(confusion)
        actual_counts = confusion.sum(axis=1)
        pred_counts = confusion.sum(axis=0)
        tp = np.diag(confusion).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per_class_acc = np.where(actual_counts > 0, tp / actual_counts, 0.0)
            precision = np.where(pred_counts > 0, tp / pred_counts, 0.0)
            recall = per_class_acc
            f1 = np.where(
                precision + recall > 0,
                2 * precision * recall / (precision + recall),
                0.0,
            )
        return MulticlassMetrics(
            confusion=confusion,
            total_accuracy=float(correct / total) if total else 0.0,
            per_class_accuracy=per_class_acc,
            macro_f1=float(f1.mean()),
        )
