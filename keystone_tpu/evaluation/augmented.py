"""Evaluation over augmented views (test-time augmentation).

Ref: src/main/scala/evaluation/AugmentedExamplesEvaluator.scala — averages
the classifier scores over an image's augmented crops before ranking
(ImageNet top-5; SURVEY.md §2.10) [unverified — name low confidence].
"""

from __future__ import annotations

import numpy as np


class AugmentedExamplesEvaluator:
    """Scores: (n·views, C) grouped per image (all views of image i
    contiguous); labels: (n,)."""

    def __init__(self, num_views: int):
        self.num_views = num_views

    def average_scores(self, scores) -> np.ndarray:
        scores = np.asarray(scores)
        n = scores.shape[0] // self.num_views
        if scores.shape[0] != n * self.num_views:
            raise ValueError(
                f"{scores.shape[0]} rows not divisible by {self.num_views} views"
            )
        return scores.reshape(n, self.num_views, -1).mean(axis=1)

    def top_k_error(self, scores, labels, k: int = 5) -> float:
        avg = self.average_scores(scores)
        labels = np.asarray(labels).ravel()
        topk = np.argsort(-avg, axis=1)[:, :k]
        correct = (topk == labels[:, None]).any(axis=1)
        return float(1.0 - correct.mean())
