"""VOC-style mean average precision.

Ref: src/main/scala/evaluation/MeanAveragePrecisionEvaluator.scala — the
VOC multi-label metric (SURVEY.md §2.10) [unverified]. Implements the
VOC2007 11-point interpolated AP (the metric the reference's VOC pipeline
reports) with the exact (area-under-PR) variant available.
"""

from __future__ import annotations

import numpy as np


class MeanAveragePrecisionEvaluator:
    def __init__(self, num_classes: int, eleven_point: bool = True):
        self.num_classes = num_classes
        self.eleven_point = eleven_point

    def evaluate(self, scores, actual) -> dict:
        """scores: (n, C) real-valued; actual: (n, C) binary multilabels."""
        scores = np.asarray(scores, dtype=np.float64)
        actual = np.asarray(actual).astype(bool)
        if scores.shape != actual.shape:
            raise ValueError(f"shape mismatch {scores.shape} vs {actual.shape}")
        aps = np.array(
            [
                self.average_precision(scores[:, c], actual[:, c])
                for c in range(self.num_classes)
            ]
        )
        return {"per_class_ap": aps, "map": float(np.nanmean(aps))}

    def average_precision(self, scores, positives) -> float:
        positives = np.asarray(positives).astype(bool)
        n_pos = int(positives.sum())
        if n_pos == 0:
            return float("nan")
        order = np.argsort(-np.asarray(scores, dtype=np.float64), kind="mergesort")
        hits = positives[order]
        tp = np.cumsum(hits)
        precision = tp / np.arange(1, len(hits) + 1)
        recall = tp / n_pos
        if self.eleven_point:
            ap = 0.0
            for t in np.linspace(0, 1, 11):
                mask = recall >= t
                ap += precision[mask].max() if mask.any() else 0.0
            return float(ap / 11.0)
        # Exact AP: sum of precision at each positive rank.
        return float(precision[hits].sum() / n_pos)
