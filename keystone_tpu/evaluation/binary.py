"""Binary classification metrics.

Ref: src/main/scala/evaluation/BinaryClassifierEvaluator.scala — tp/fp/tn/fn
counts, accuracy, precision, recall, F1 (SURVEY.md §2.10) [unverified].
AUC added via the rank-statistic estimator (ties averaged).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class BinaryMetrics:
    tp: int
    fp: int
    tn: int
    fn: int
    accuracy: float
    precision: float
    recall: float
    f1: float
    auc: float | None = None

    def summary(self) -> str:
        lines = [
            f"accuracy:  {self.accuracy:.4f}",
            f"precision: {self.precision:.4f}",
            f"recall:    {self.recall:.4f}",
            f"F1:        {self.f1:.4f}",
        ]
        if self.auc is not None:
            lines.append(f"AUC:       {self.auc:.4f}")
        return "\n".join(lines)


class BinaryClassifierEvaluator:
    @staticmethod
    def evaluate(predicted, actual, scores=None) -> BinaryMetrics:
        pred = np.asarray(predicted).astype(bool).ravel()
        act = np.asarray(actual).astype(bool).ravel()
        if pred.shape != act.shape:
            raise ValueError(f"shape mismatch {pred.shape} vs {act.shape}")
        tp = int(np.sum(pred & act))
        fp = int(np.sum(pred & ~act))
        tn = int(np.sum(~pred & ~act))
        fn = int(np.sum(~pred & act))
        n = len(pred)
        precision = tp / (tp + fp) if tp + fp else 0.0
        recall = tp / (tp + fn) if tp + fn else 0.0
        f1 = (
            2 * precision * recall / (precision + recall)
            if precision + recall
            else 0.0
        )
        auc = None
        if scores is not None:
            auc = BinaryClassifierEvaluator.auc(scores, act)
        return BinaryMetrics(
            tp, fp, tn, fn, (tp + tn) / n if n else 0.0, precision, recall, f1, auc
        )

    @staticmethod
    def auc(scores, actual) -> float:
        """Mann-Whitney rank estimator of ROC AUC (ties get average rank)."""
        s = np.asarray(scores, dtype=np.float64).ravel()
        a = np.asarray(actual).astype(bool).ravel()
        n_pos = int(a.sum())
        n_neg = len(a) - n_pos
        if n_pos == 0 or n_neg == 0:
            return 0.5
        order = np.argsort(s, kind="mergesort")
        ranks = np.empty(len(s), dtype=np.float64)
        sorted_s = s[order]
        i = 0
        while i < len(s):
            j = i
            while j + 1 < len(s) and sorted_s[j + 1] == sorted_s[i]:
                j += 1
            ranks[order[i : j + 1]] = 0.5 * (i + j) + 1.0
            i = j + 1
        rank_sum = ranks[a].sum()
        return float(
            (rank_sum - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg)
        )
