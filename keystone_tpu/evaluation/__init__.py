from keystone_tpu.evaluation.multiclass import (
    MulticlassClassifierEvaluator,
    MulticlassMetrics,
)

__all__ = ["MulticlassClassifierEvaluator", "MulticlassMetrics"]
