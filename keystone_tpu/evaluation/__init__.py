from keystone_tpu.evaluation.multiclass import (
    MulticlassClassifierEvaluator,
    MulticlassMetrics,
)
from keystone_tpu.evaluation.mean_average_precision import MeanAveragePrecisionEvaluator
from keystone_tpu.evaluation.augmented import AugmentedExamplesEvaluator
from keystone_tpu.evaluation.binary import (
    BinaryClassifierEvaluator,
    BinaryMetrics,
)

__all__ = [
    "MulticlassClassifierEvaluator",
    "MulticlassMetrics",
    "BinaryClassifierEvaluator",
    "BinaryMetrics",
    "MeanAveragePrecisionEvaluator",
    "AugmentedExamplesEvaluator",
]
