from keystone_tpu.evaluation.multiclass import (
    MulticlassClassifierEvaluator,
    MulticlassMetrics,
)
from keystone_tpu.evaluation.binary import (
    BinaryClassifierEvaluator,
    BinaryMetrics,
)

__all__ = [
    "MulticlassClassifierEvaluator",
    "MulticlassMetrics",
    "BinaryClassifierEvaluator",
    "BinaryMetrics",
]
