"""Shared Fisher-vector math constants.

Single source for the quantities every FV backend (XLA einsum, Pallas
kernel, native C++) must agree on: the starved-component weight clamp, the
Gaussian log-normalizers, and the improved-FV gradient scalings. The C++
path mirrors these in gmm_fv.cpp; the two Python backends import them.
"""

from __future__ import annotations

import jax.numpy as jnp

WEIGHT_FLOOR = 1e-12  # starved components yield zero blocks, not NaNs


def fv_constants(w, mu, var, m: int):
    """Returns (w, inv_var, logw_norm (k,), cm (k,1), cv (k,1))."""
    w = jnp.maximum(w, WEIGHT_FLOOR)
    d = mu.shape[1]
    inv = 1.0 / var
    log_norm = -0.5 * (
        d * jnp.log(2 * jnp.pi) + jnp.sum(jnp.log(var), axis=1)
    )
    logw_norm = jnp.log(w) + log_norm
    cm = (1.0 / (m * jnp.sqrt(w)))[:, None]
    cv = (1.0 / (m * jnp.sqrt(2.0 * w)))[:, None]
    return w, inv, logw_norm, cm, cv
