"""Custom TPU kernels (Pallas).

Hand-written kernels for hot ops where XLA's default scheduling leaves
HBM bandwidth on the table. Each kernel has an interpret-mode path so its
logic is exercised by the CPU-mesh test suite; on TPU the same code lowers
through Mosaic.
"""

from keystone_tpu.ops.fisher_vector_pallas import fisher_vectors_pallas

__all__ = ["fisher_vectors_pallas"]
