"""Fused Fisher-vector encoding as a Pallas TPU kernel.

The XLA path (nodes/images/external/fisher_vector._fv_tpu) materializes the
responsibility tensor r of shape (B, m, k) in HBM between the softmax and
the two gradient einsums. This kernel tiles the descriptor axis: each
(image, m-tile) program computes its responsibilities in VMEM, immediately
contracts them into the (k, d) gradient accumulators, and never writes r
out — saving a full (B·m·k) HBM round trip per encode (≈2 MB/image at the
ImageNet configuration k=256, m≈2000).

Math identical to the XLA/native backends (cross-checked in tests):

  gmu_j  = Σ_i r_ij (x_i − μ_j)/σ_j · 1/(m√w_j)
  gvar_j = Σ_i r_ij ((x_i − μ_j)²/var_j − 1) · 1/(m√(2w_j))

accumulated per tile via the expanded forms rᵀx and rᵀx² so every
contraction is an MXU matmul with f32 accumulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from keystone_tpu.config import config


def _fv_kernel(
    x_ref,  # (1, Tm, d) descriptor tile
    logw_norm_ref,  # (1, k) log w_j + log-normalizer
    mu_ref,  # (k, d)
    inv_ref,  # (k, d)   1/var
    mu_inv_ref,  # (k, d) mu/var
    sigma_ref,  # (k, d)  sqrt(var)
    c2_ref,  # (1, k)  Σ_d mu² / var
    gmu_ref,  # (1, k, d) out accumulator
    gvar_ref,  # (1, k, d) out accumulator
    *,
    tile_m: int,
    m_real: int,  # logical descriptor count (pre-padding) — static
):
    t = pl.program_id(1)
    x = x_ref[0]  # (Tm, d)
    # log p(x|j) + log w_j, gemm-shaped.
    quad = (
        jnp.dot(x * x, inv_ref[:].T, preferred_element_type=jnp.float32)
        - 2.0 * jnp.dot(x, mu_inv_ref[:].T, preferred_element_type=jnp.float32)
        + c2_ref[0][None, :]
    )
    logits = logw_norm_ref[0][None, :] - 0.5 * quad  # (Tm, k)
    r = jax.nn.softmax(logits, axis=-1)
    # Mask rows beyond the logical descriptor count (zero-padded tiles).
    row = t * tile_m + jax.lax.broadcasted_iota(jnp.int32, (tile_m, 1), 0)
    r = jnp.where(row < m_real, r, 0.0)

    rs = jnp.sum(r, axis=0)  # (k,)
    t1 = jnp.dot(r.T, x, preferred_element_type=jnp.float32)  # (k, d)
    t2 = jnp.dot(r.T, x * x, preferred_element_type=jnp.float32)  # (k, d)
    mu = mu_ref[:]
    inv = inv_ref[:]
    gmu_tile = (t1 - rs[:, None] * mu) / sigma_ref[:]
    gvar_tile = (t2 - 2.0 * mu * t1 + rs[:, None] * (mu * mu)) * inv - rs[
        :, None
    ]

    @pl.when(t == 0)
    def _():
        gmu_ref[0] = jnp.zeros_like(gmu_ref[0])
        gvar_ref[0] = jnp.zeros_like(gvar_ref[0])

    gmu_ref[0] += gmu_tile
    gvar_ref[0] += gvar_tile


@functools.partial(
    jax.jit, static_argnames=("tile_m", "interpret")
)
def _fv_pallas(X, w, mu, var, tile_m: int, interpret: bool):
    B, m, d = X.shape
    k = w.shape[0]
    m_pad = (-m) % tile_m
    if m_pad:
        X = jnp.pad(X, ((0, 0), (0, m_pad), (0, 0)))
    tiles = (m + m_pad) // tile_m

    from keystone_tpu.ops.fv_common import fv_constants

    w, inv, logw_norm_vec, cm, cv = fv_constants(w, mu, var, m)
    logw_norm = logw_norm_vec[None, :]  # (1, k)
    c2 = jnp.sum(mu * mu * inv, axis=1)[None, :]  # (1, k)

    # Grid semantics for Mosaic: image programs are independent
    # ("parallel"); the m-tile axis accumulates into the same output block
    # and must iterate in order ("arbitrary"). Ignored by the interpreter.
    # (TPUCompilerParams is the pre-rename spelling of CompilerParams.)
    params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    compiler_params = params_cls(
        dimension_semantics=("parallel", "arbitrary")
    )

    gmu, gvar = pl.pallas_call(
        functools.partial(_fv_kernel, tile_m=tile_m, m_real=m),
        grid=(B, tiles),
        in_specs=[
            pl.BlockSpec((1, tile_m, d), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, k), lambda b, t: (0, 0)),
            pl.BlockSpec((k, d), lambda b, t: (0, 0)),
            pl.BlockSpec((k, d), lambda b, t: (0, 0)),
            pl.BlockSpec((k, d), lambda b, t: (0, 0)),
            pl.BlockSpec((k, d), lambda b, t: (0, 0)),
            pl.BlockSpec((1, k), lambda b, t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, k, d), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, k, d), lambda b, t: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, k, d), jnp.float32),
            jax.ShapeDtypeStruct((B, k, d), jnp.float32),
        ],
        compiler_params=compiler_params,
        interpret=interpret,
    )(
        X,
        logw_norm,
        mu,
        inv,
        mu * inv,
        jnp.sqrt(var),
        c2,
    )
    out = jnp.concatenate(
        [(gmu * cm).reshape(B, -1), (gvar * cv).reshape(B, -1)], axis=-1
    )
    return out.astype(config.default_dtype)


def fisher_vectors_pallas(
    X,
    weights,
    means,
    variances,
    tile_m: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """(B, m, d) descriptor sets → (B, 2·k·d) raw Fisher vectors.

    ``interpret`` defaults to True off-TPU (CPU tests run the kernel logic
    through the Pallas interpreter) and False on TPU (Mosaic lowering).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    X = jnp.asarray(X, dtype=jnp.float32)
    return _fv_pallas(
        X,
        jnp.asarray(weights, dtype=jnp.float32),
        jnp.asarray(means, dtype=jnp.float32),
        jnp.asarray(variances, dtype=jnp.float32),
        tile_m=min(tile_m, X.shape[1]),
        interpret=interpret,
    )
