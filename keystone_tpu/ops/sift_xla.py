"""Dense SIFT as two grouped 1-D convolutions — the on-chip twin of the
native kernel.

Ref: src/main/scala/nodes/images/external/SIFTExtractor.scala /
utils.external.VLFeat.getSIFTs (SURVEY.md §2.3, §3.4) [unverified]. The
reference extracts descriptors in native C on executor CPUs; the clean-room
C++ parity port lives in native/src/sift.cpp. This module is the
TPU-native PERFORMANCE path with identical math, exploiting that every
per-pixel weight in the descriptor sum factorizes:

    desc[ky,kx,cy,cx,b]
      = Σ_{yy,xx} ori[ky·s+yy, kx·s+xx, b] · G(yy,xx) · wy(yy,cy) · wx(xx,cx)

with G a centered Gaussian (separable: G = gy(yy)·gx(xx)) and wy/wx the
bilinear cell weights. So the whole extraction is:

  1. per-pixel gradients (edge-clamped central differences — VPU),
  2. soft orientation binning into 8 channels (VPU),
  3. a stride-`step` 1-D conv along y with 4 per-channel filters
     (gy·wy(·,cy)), then the same along x (gx·wx(·,cx)) — grouped convs
     the MXU executes natively,
  4. L2 → 0.2-clamp → re-L2 normalization per descriptor.

Running SIFT on chip removes the last host-side featurization stage of
the ImageNet/VOC pipelines (the host keeps only JPEG decode — see
tools/northstar.py), and the whole SIFT→PCA→FV branch fuses into device
programs. Parity vs the native kernel is oracle-tested in
tests/test_descriptors.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

ORI_BINS = 8
SPATIAL_BINS = 4
DESC_DIM = SPATIAL_BINS * SPATIAL_BINS * ORI_BINS  # 128


def _cell_kernels(bin_size: int) -> np.ndarray:
    """(SPATIAL_BINS, span) separable 1-D weights: Gaussian × bilinear.

    Row c gives, for each offset within the span, the weight of spatial
    cell c along that axis — exactly the factorized form of the native
    kernel's per-pixel weighting (sift.cpp descriptor_at)."""
    span = SPATIAL_BINS * bin_size
    center = 0.5 * (span - 1)
    sigma = 0.5 * span
    off = np.arange(span)
    gauss = np.exp(-((off - center) ** 2) / (2.0 * sigma * sigma))
    # Position in cell units (bilinear support over adjacent cells).
    pos = (off + 0.5) / bin_size - 0.5
    cells = np.arange(SPATIAL_BINS)[:, None]
    w = np.maximum(0.0, 1.0 - np.abs(pos[None, :] - cells))
    return (w * gauss[None, :]).astype(np.float32)  # (4, span)


def _gradients(im: jnp.ndarray):
    """Edge-clamped central differences, matching the native kernel: at
    borders the clamped index makes the difference one-sided (still ×0.5)."""
    padx = jnp.pad(im, ((0, 0), (0, 0), (1, 1)), mode="edge")
    pady = jnp.pad(im, ((0, 0), (1, 1), (0, 0)), mode="edge")
    gx = 0.5 * (padx[:, :, 2:] - padx[:, :, :-2])
    gy = 0.5 * (pady[:, 2:, :] - pady[:, :-2, :])
    return gx, gy


def _orientation_channels(gx: jnp.ndarray, gy: jnp.ndarray) -> jnp.ndarray:
    """(n, h, w) gradients → (n, h, w, 8) soft-assigned magnitude channels
    (linear interpolation between the two adjacent orientation bins)."""
    mag = jnp.sqrt(gx * gx + gy * gy)
    theta = jnp.arctan2(gy, gx)
    theta = jnp.where(theta < 0, theta + 2.0 * np.pi, theta)
    fbin = theta * (ORI_BINS / (2.0 * np.pi))
    bins = jnp.arange(ORI_BINS, dtype=fbin.dtype)
    dist = jnp.abs(fbin[..., None] - bins)
    circ = jnp.minimum(dist, ORI_BINS - dist)
    return mag[..., None] * jnp.maximum(0.0, 1.0 - circ)


@partial(jax.jit, static_argnames=("step", "bin_size"))
def dense_sift_xla(
    images: jnp.ndarray, step: int = 4, bin_size: int = 4
) -> jnp.ndarray:
    """(n, h, w) grayscale → (n, nkp, 128) dense SIFT, all on device."""
    images = jnp.asarray(images, dtype=jnp.float32)
    n, h, w = images.shape
    span = SPATIAL_BINS * bin_size
    if h < span or w < span:
        raise ValueError(
            f"image ({h}x{w}) smaller than the {span}px descriptor support"
        )
    ori = _orientation_channels(*_gradients(images))  # (n, h, w, 8)

    k1d = _cell_kernels(bin_size)  # (4, span)
    # y-pass: grouped conv, each of the 8 orientation channels produces 4
    # cell-y responses. OHWI filters: O = 8·4 (group-major), I = 1.
    fy = jnp.asarray(
        np.tile(k1d[:, :, None, None], (ORI_BINS, 1, 1, 1))
    )  # (32, span, 1, 1)
    # HIGHEST precision: on TPU the default conv precision is bf16-class,
    # which would let xla-backend descriptors drift past the native-parity
    # tolerance while SIFTExtractor.signature() treats the backends as
    # cache-identical. These convs are a rounding error next to FV/solver
    # FLOPs, so full f32 costs nothing that matters.
    out = lax.conv_general_dilated(
        ori,
        fy,
        window_strides=(step, 1),
        padding="VALID",
        dimension_numbers=("NHWC", "OHWI", "NHWC"),
        feature_group_count=ORI_BINS,
        precision=lax.Precision.HIGHEST,
    )  # (n, ny, w, 32) channels ordered (b, cy)
    # x-pass: each (b, cy) channel produces 4 cell-x responses.
    fx = jnp.asarray(
        np.tile(k1d[:, None, :, None], (ORI_BINS * SPATIAL_BINS, 1, 1, 1))
    )  # (128, 1, span, 1)
    out = lax.conv_general_dilated(
        out,
        fx,
        window_strides=(1, step),
        padding="VALID",
        dimension_numbers=("NHWC", "OHWI", "NHWC"),
        feature_group_count=ORI_BINS * SPATIAL_BINS,
        precision=lax.Precision.HIGHEST,
    )  # (n, ny, nx, 128) channels ordered (b, cy, cx)
    ny, nx = out.shape[1], out.shape[2]

    # Native layout is (cy, cx, b); conv output is (b, cy, cx). Permute.
    b_i, cy_i, cx_i = np.meshgrid(
        np.arange(ORI_BINS),
        np.arange(SPATIAL_BINS),
        np.arange(SPATIAL_BINS),
        indexing="ij",
    )
    native_index = (cy_i * SPATIAL_BINS + cx_i) * ORI_BINS + b_i
    perm = np.empty(DESC_DIM, dtype=np.int32)
    perm[native_index.ravel()] = np.arange(DESC_DIM)
    desc = out.reshape(n, ny * nx, DESC_DIM)[..., jnp.asarray(perm)]

    # L2 → 0.2 clamp → re-L2, with the native kernel's norm guard: a
    # descriptor whose norm is at/below the floor stays exactly zero
    # (sift.cpp skips normalization entirely there) — without the guard, a
    # sub-1e-12 sum would amplify to a unit-norm noise descriptor after
    # renormalization. The floored denominator keeps the division NaN-free
    # under debug_nans; the where() only selects, never divides by zero.
    norm = jnp.linalg.norm(desc, axis=-1, keepdims=True)
    desc = jnp.minimum(desc / jnp.maximum(norm, 1e-12), 0.2)
    norm2 = jnp.linalg.norm(desc, axis=-1, keepdims=True)
    return jnp.where(norm > 1e-12, desc / jnp.maximum(norm2, 1e-12), 0.0)
