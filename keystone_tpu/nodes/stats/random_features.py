"""Random Fourier features: cos(XW + b).

Ref: src/main/scala/nodes/stats/CosineRandomFeatures.scala — W drawn
Gaussian (RBF kernel) or Cauchy (Laplacian kernel), b uniform in [0, 2π);
the TIMIT pipeline's featurizer (BASELINE.json) [unverified].

The projection is one large MXU gemm; gamma scales the kernel bandwidth.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.config import config
from keystone_tpu.workflow import Transformer


class CosineRandomFeatures(Transformer):
    def __init__(self, W: jax.Array, b: jax.Array):
        self.W = jnp.asarray(W)
        self.b = jnp.asarray(b)

    @classmethod
    def create(
        cls,
        input_dim: int,
        num_features: int,
        gamma: float = 1.0,
        distribution: str = "gaussian",
        seed: int = 0,
    ) -> "CosineRandomFeatures":
        kw, kb = jax.random.split(jax.random.PRNGKey(seed))
        dtype = config.default_dtype
        if distribution == "gaussian":
            W = jax.random.normal(kw, (input_dim, num_features), dtype=dtype)
        elif distribution == "cauchy":
            W = jax.random.cauchy(kw, (input_dim, num_features), dtype=dtype)
        else:
            raise ValueError(f"unknown distribution {distribution!r}")
        b = jax.random.uniform(
            kb, (num_features,), minval=0.0, maxval=2 * np.pi, dtype=dtype
        )
        node = cls(W * gamma, b)
        # dtype is part of the identity: the drawn W/b values depend on it.
        node._sig = node.stable_signature(
            input_dim, num_features, gamma, distribution, seed, str(dtype)
        )
        return node

    def apply_batch(self, X):
        return jnp.cos(X @ self.W + self.b)
