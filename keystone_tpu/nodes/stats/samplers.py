"""Row/column sampling helpers feeding profilers and sample-based fits
(GMM, PCA).

Ref: src/main/scala/nodes/stats/Sampler.scala, ColumnSampler [unverified].
Host-side index generation + device gather, deterministic by seed.
"""

from __future__ import annotations

import numpy as np


def sample_rows(X, num_samples: int, seed: int = 0):
    n = X.shape[0]
    if num_samples >= n:
        return X
    idx = np.random.default_rng(seed).choice(n, size=num_samples, replace=False)
    return X[np.sort(idx)]


def sample_columns(X, num_cols: int, seed: int = 0):
    d = X.shape[-1]
    if num_cols >= d:
        return X
    idx = np.random.default_rng(seed).choice(d, size=num_cols, replace=False)
    return X[..., np.sort(idx)]
