"""Random sign flip node.

Ref: src/main/scala/nodes/stats/RandomSignNode.scala — elementwise multiply
by a fixed random ±1 vector (the "D" matrix of Fastfood-style random
features) [unverified].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from keystone_tpu.workflow import Transformer


class RandomSignNode(Transformer):
    def __init__(self, signs: jax.Array):
        self.signs = jnp.asarray(signs)

    @classmethod
    def create(cls, dim: int, seed: int = 0) -> "RandomSignNode":
        key = jax.random.PRNGKey(seed)
        signs = jax.random.rademacher(key, (dim,), dtype=jnp.float32)
        node = cls(signs)
        node._sig = node.stable_signature(dim, seed)
        return node

    def apply_batch(self, X):
        return X * self.signs
