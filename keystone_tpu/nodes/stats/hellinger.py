"""Signed Hellinger (signed square root) mapper — Fisher-Vector
normalization step.

Ref: src/main/scala/nodes/stats/SignedHellingerMapper.scala [unverified].
"""

from __future__ import annotations

import jax.numpy as jnp

from keystone_tpu.workflow import Transformer


class SignedHellingerMapper(Transformer):
    def signature(self):
        return self.stable_signature()

    def apply_batch(self, X):
        return jnp.sign(X) * jnp.sqrt(jnp.abs(X))
