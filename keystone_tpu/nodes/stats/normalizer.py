"""Row L2 normalization (the FV normalization tail).

Ref: the reference normalizes Fisher vectors with SignedHellingerMapper
followed by an L2 normalizer inside the VOC/ImageNet pipelines
(SURVEY.md §2.11, §3.4) [unverified].
"""

from __future__ import annotations

import jax.numpy as jnp

from keystone_tpu.workflow import Transformer


class L2Normalizer(Transformer):
    def __init__(self, eps: float = 1e-12):
        self.eps = eps

    def signature(self):
        return self.stable_signature(self.eps)

    def apply_batch(self, X):
        norm = jnp.linalg.norm(X, axis=-1, keepdims=True)
        return X / jnp.maximum(norm, self.eps)
