from keystone_tpu.nodes.stats.random_signs import RandomSignNode
from keystone_tpu.nodes.stats.fft import PaddedFFT
from keystone_tpu.nodes.stats.rectifier import LinearRectifier
from keystone_tpu.nodes.stats.scalers import StandardScaler, StandardScalerModel
from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
from keystone_tpu.nodes.stats.hellinger import SignedHellingerMapper
from keystone_tpu.nodes.stats.normalizer import L2Normalizer
from keystone_tpu.nodes.stats.samplers import sample_rows, sample_columns

__all__ = [
    "RandomSignNode",
    "PaddedFFT",
    "LinearRectifier",
    "StandardScaler",
    "StandardScalerModel",
    "CosineRandomFeatures",
    "SignedHellingerMapper",
    "L2Normalizer",
    "sample_rows",
    "sample_columns",
]
