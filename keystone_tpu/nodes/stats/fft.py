"""Zero-padded FFT featurizer.

Ref: src/main/scala/nodes/stats/PaddedFFT.scala — zero-pad the input vector
to the next power of two and take the FFT (used by MnistRandomFFT,
BASELINE.json) [unverified]. We use the real-input FFT and lay out the
real and imaginary parts side by side, scaled by 1/sqrt(n) so downstream
solvers see O(1) features; on TPU the batched FFT lowers to a single XLA op.
"""

from __future__ import annotations

import jax.numpy as jnp

from keystone_tpu.workflow import Transformer


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class PaddedFFT(Transformer):
    def signature(self):
        return self.stable_signature()

    def apply_batch(self, X):
        n = _next_pow2(X.shape[-1])
        Xp = jnp.pad(X, [(0, 0)] * (X.ndim - 1) + [(0, n - X.shape[-1])])
        F = jnp.fft.rfft(Xp, axis=-1) / jnp.sqrt(n).astype(Xp.dtype)
        return jnp.concatenate([F.real, F.imag], axis=-1).astype(X.dtype)
